"""Load generator for the serving stack: open/closed-loop, loopback-first.

Closed loop: ``concurrency`` workers issue back-to-back requests over
KEEP-ALIVE connections — measures the service's sustainable throughput and
the latency AT that throughput. Open loop: requests are launched on a
fixed-rate schedule regardless of completions (the arrival process real
traffic has), drained by a worker pool — latency then includes queueing
delay, and a rate above capacity shows up as a growing p99 (and eventually
503s) rather than a politely slowed client. :func:`run_ladder` sweeps a
rate ladder with per-step warmup/measure windows. Every run reports
p50/p95/p99/mean/max latency, sustained throughput, and an ALWAYS-present
error accounting (non-2xx by status, timeouts, connection failures) plus
retry counts — with ``retries > 0`` a dropped connection (e.g. a replica
killed mid-flight) is retried on a fresh connection, which a
``SO_REUSEPORT`` fleet routes to a surviving replica.

``bench_serving()`` is the PR-3 baseline benchmark (deprecated threaded
server); ``bench_serving_async()`` is the production path: a supervised
replica fleet on one shared port, driven closed-loop at c=32 and up a rate
ladder, over both the JSON-list and compact base64 wire formats. Both feed
``bench.py`` sections and ``BENCH_SERVING.json``.
"""

from __future__ import annotations

import argparse
import json
import socket
import threading
import time
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from ..observability.tracecontext import (
    new_span_id,
    new_trace_id,
    trace_sampled,
)

Payload = Union[Dict[str, Any], bytes, Callable[[int], Any]]

# bounded per-run trace-id evidence lists: enough to cross-check every
# retry/error of a fault-matrix run without letting a pathological run
# grow the result dict unboundedly
MAX_TRACE_IDS = 512


def _post_json(url: str, payload: Dict[str, Any],
               timeout: float = 30.0) -> Dict[str, Any]:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class KeepAliveClient:
    """One persistent raw-socket HTTP/1.1 connection to a POST endpoint.

    Raw sockets instead of ``http.client``: at hundreds of rps the
    stdlib's per-request header formatting and response object machinery
    costs ~3 CPU-ms — 3× the entire serving path — so the loadgen would
    measure itself. Here a request is one prebuilt header + ``sendall``
    and a response parse is two reads. ``post`` returns (status, body
    bytes); any transport failure closes the connection so the next call
    reconnects — against an SO_REUSEPORT fleet that lands on a (possibly
    different) live replica.
    """

    def __init__(self, url: str, timeout_s: float = 30.0,
                 content_type: str = "application/json"):
        u = urllib.parse.urlsplit(url)
        self.host, self.port = u.hostname, u.port or 80
        self.path = u.path or "/"
        self.timeout_s = timeout_s
        self._header = (
            f"POST {self.path} HTTP/1.1\r\nHost: {self.host}\r\n"
            f"Content-Type: {content_type}\r\nContent-Length: "
        ).encode()
        self._sock: Optional[socket.socket] = None
        self._rfile = None

    def post(self, body: bytes, extra_headers: bytes = b""):
        """``extra_headers``: pre-encoded ``Name: value\\r\\n`` lines
        appended after Content-Length (the loadgen's per-request
        ``traceparent`` rides here without re-building the base header)."""
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._rfile = self._sock.makefile("rb")
        try:
            self._sock.sendall(
                self._header + str(len(body)).encode() + b"\r\n"
                + extra_headers + b"\r\n" + body)
            line = self._rfile.readline()
            if not line:
                raise ConnectionError("server closed the connection")
            status = int(line.split()[1])
            length = 0
            server_closes = line.startswith(b"HTTP/1.0")
            while True:
                h = self._rfile.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                hl = h.lower()
                if hl.startswith(b"content-length:"):
                    length = int(h.split(b":", 1)[1])
                elif hl.startswith(b"connection:") and b"close" in hl:
                    server_closes = True
            data = self._rfile.read(length) if length else b""
            if server_closes:
                # one-response connection (e.g. an HTTP/1.0 server):
                # reconnect on the next post instead of writing into a
                # socket the peer is closing
                self.close()
            return status, data
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._rfile.close()
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._rfile = None


def _percentiles(latencies_s: List[float]) -> Optional[Dict[str, float]]:
    # the shared nearest-rank summary (observability.report) so loadgen,
    # /metrics, and the report CLI agree numerically; mean/max ride along
    from ..observability.report import latency_percentiles_ms

    out = latency_percentiles_ms(latencies_s)
    if out is not None:
        out["mean_ms"] = round(sum(latencies_s) / len(latencies_s) * 1e3, 3)
        out["max_ms"] = round(max(latencies_s) * 1e3, 3)
    return out


def _encode_payload(p) -> bytes:
    return p if isinstance(p, (bytes, bytearray)) else json.dumps(p).encode()


def run_loadgen(
    url: str,
    payload: Payload,
    mode: str = "closed",
    concurrency: int = 4,
    n_requests: int = 200,
    rate_rps: Optional[float] = None,
    warmup_requests: int = 4,
    timeout_s: float = 30.0,
    retries: int = 0,
    retry_backoff_s: float = 0.05,
    open_workers: int = 32,
    content_type: str = "application/json",
    reconnect_every: int = 0,
    trace: bool = True,
    events: Any = None,
    rates_schedule: Optional[List[Any]] = None,
    class_of: Optional[Callable[[int], str]] = None,
    extra_headers_of: Optional[Callable[[int], bytes]] = None,
) -> Dict[str, Any]:
    """Drive `url` (a POST endpoint) and report the latency distribution.

    `payload` is one dict (or pre-encoded ``bytes``) reused for every
    request, or a callable ``i -> dict | bytes`` for varied traffic. Closed
    loop: `concurrency` workers × back-to-back requests, each worker on one
    keep-alive connection. Open loop (`mode="open"`): requests are due at
    ``i / rate_rps``; an ``open_workers``-thread pool issues each at its
    due time (late issues are counted, not silently absorbed).

    ``retries``: transport failures (dropped connection — e.g. a replica
    dying mid-request) and 503s are retried up to this many times, on a
    fresh connection, with ``retry_backoff_s`` between attempts; the
    request's latency then spans all attempts. Errors are ALWAYS reported
    as a (possibly empty) dict: non-2xx counts by status, timeouts and
    connection failures by exception name.

    ``reconnect_every``: close each worker's connection every N requests.
    Against an SO_REUSEPORT fleet a long-lived connection is pinned to one
    replica for its whole life; periodic reconnects re-randomize the
    assignment so a skewed initial spread cannot dominate the tail.

    ``trace``: send a W3C ``traceparent`` header per request, generated at
    THIS edge and REUSED across retries — a request killed on one replica
    and retried on another is one trace in the merged ``report --trace``.
    The sampled flag follows ``DLAP_TRACE_SAMPLE`` deterministically, so
    client and servers agree per trace id. Retried and failed requests'
    trace ids are returned (``retried_trace_ids`` / ``error_trace_ids``,
    bounded) so the report's retry section can be cross-checked against
    the trace. ``events``: an ``observability.EventLog`` — when given,
    every finished request emits one ``client/request`` row (trace id,
    attempts, status, latency), the client half of the merged flow trace.

    ``rates_schedule``: a list of ``(rate_rps, duration_s)`` steps —
    open-loop arrival times swing THROUGH the schedule mid-run on the
    SAME worker pool and keep-alive connections (no reconnect between
    steps; ``mode="open"`` implied, ``n_requests``/``rate_rps`` derived).
    The result then carries a per-step breakdown (``steps``).
    ``class_of``: maps a request index to its priority class
    (``interactive``/``bulk``) — the class rides the request as an
    ``x-dlap-priority`` header AND the result gains per-class latency /
    error / shed accounting (``by_class``). ``extra_headers_of``: raw
    pre-encoded ``Name: value\\r\\n`` lines per request index (e.g. a
    deadline header).
    """
    if rates_schedule:
        mode = "open"
        rate_rps = rate_rps or rates_schedule[0][0]
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be closed|open: {mode!r}")
    if mode == "open" and not rate_rps:
        raise ValueError("open-loop mode requires rate_rps")
    make = payload if callable(payload) else (lambda i: payload)
    endpoint = urllib.parse.urlsplit(url).path or "/"

    # schedule → per-index due offsets + step ids; one worker pool rides
    # the whole swing (the rate changes, the connections do not)
    due_offsets: Optional[List[float]] = None
    step_of: Optional[List[int]] = None
    step_meta: List[Dict[str, Any]] = []
    if rates_schedule:
        due_offsets, step_of = [], []
        t_off = 0.0
        for s, (rate, duration) in enumerate(rates_schedule):
            rate = float(rate)
            if rate <= 0 or duration <= 0:
                raise ValueError(
                    f"rates_schedule step {s} needs rate > 0 and "
                    f"duration > 0: ({rate}, {duration})")
            n_step = max(1, int(rate * duration))
            for k in range(n_step):
                due_offsets.append(t_off + k / rate)
                step_of.append(s)
            step_meta.append({"offered_rate_rps": rate,
                              "duration_s": duration,
                              "n_requests": n_step})
            t_off += duration
        n_requests = len(due_offsets)

    # compile warmth, untimed; indices beyond the measured range so a
    # result cache in front of the server cannot pre-absorb measured traffic
    warm_client = KeepAliveClient(url, timeout_s=timeout_s)
    for i in range(warmup_requests):
        try:
            warm_client.post(_encode_payload(make(n_requests + i)))
        except Exception:
            pass
    warm_client.close()

    lock = threading.Lock()
    latencies: List[float] = []
    errors: Dict[str, int] = {}
    error_trace_ids: Dict[str, List[str]] = {}
    retried_trace_ids: List[str] = []
    stats = {"retried": 0, "late": 0, "max_lag_s": 0.0}
    # per-priority-class and per-schedule-step accounting sinks
    class_acc: Dict[str, Dict[str, Any]] = {}
    step_acc: List[Dict[str, Any]] = [
        {"lat": [], "errors": {}} for _ in step_meta]
    local = threading.local()

    def client() -> KeepAliveClient:
        c = getattr(local, "client", None)
        if c is None:
            c = local.client = KeepAliveClient(
                url, timeout_s=timeout_s, content_type=content_type)
        return c

    def _class_bucket(i: int) -> Optional[Dict[str, Any]]:
        if class_of is None:
            return None
        cls = class_of(i)
        return class_acc.setdefault(cls, {"lat": [], "errors": {},
                                          "n_requests": 0})

    def record_ok(i: int, dt: float) -> None:
        with lock:
            latencies.append(dt)
            cb = _class_bucket(i)
            if cb is not None:
                cb["lat"].append(dt)
            if step_of is not None:
                step_acc[step_of[i]]["lat"].append(dt)

    def record_error(key: str, trace_id: Optional[str],
                     i: Optional[int] = None) -> None:
        with lock:
            errors[key] = errors.get(key, 0) + 1
            if trace_id is not None:
                ids = error_trace_ids.setdefault(key, [])
                if len(ids) < MAX_TRACE_IDS:
                    ids.append(trace_id)
            if i is not None:
                cb = _class_bucket(i)
                if cb is not None:
                    cb["errors"][key] = cb["errors"].get(key, 0) + 1
                if step_of is not None:
                    se = step_acc[step_of[i]]["errors"]
                    se[key] = se.get(key, 0) + 1

    def emit_client_row(trace_id, sampled, status, dt, attempt) -> None:
        if events is None or not sampled:
            return
        events.emit("request", "client/request", trace_id=trace_id,
                    endpoint=endpoint, status=status,
                    duration_s=round(dt, 6), attempts=attempt + 1,
                    retried=attempt > 0)

    def one(i: int) -> None:
        body = _encode_payload(make(i))
        # ONE trace id for the request's whole life — every retry reuses
        # it (fresh span id per attempt), so the merged trace shows one
        # request spanning every replica that touched it
        trace_id = new_trace_id() if trace else None
        sampled = trace and trace_sampled(trace_id)
        base_hdr = b""
        if class_of is not None:
            cls = class_of(i)
            base_hdr += f"x-dlap-priority: {cls}\r\n".encode()
            with lock:
                _class_bucket(i)["n_requests"] += 1
        if extra_headers_of is not None:
            base_hdr += extra_headers_of(i)
        t0 = time.monotonic()
        attempt = 0
        while True:
            hdr = base_hdr
            if trace_id is not None:
                hdr = hdr + (
                    f"traceparent: 00-{trace_id}-{new_span_id()}-"
                    f"{'01' if sampled else '00'}\r\n").encode()
            try:
                status, _ = client().post(body, extra_headers=hdr)
            except socket.timeout:
                record_error("timeout", trace_id, i)
                emit_client_row(trace_id, sampled, "timeout",
                                time.monotonic() - t0, attempt)
                return
            except (OSError, ValueError, IndexError) as e:
                # OSError: transport death. ValueError/IndexError: a
                # garbled status line from a dying peer — same remedy
                # (KeepAliveClient closed itself; retry reconnects), and
                # the worker must survive either way or the run silently
                # loses concurrency
                if attempt < retries:
                    attempt += 1
                    with lock:
                        stats["retried"] += 1
                        if (trace_id is not None
                                and len(retried_trace_ids) < MAX_TRACE_IDS):
                            retried_trace_ids.append(trace_id)
                    time.sleep(retry_backoff_s)
                    continue
                record_error(type(e).__name__, trace_id, i)
                emit_client_row(trace_id, sampled, type(e).__name__,
                                time.monotonic() - t0, attempt)
                return
            if 200 <= status < 300:
                dt = time.monotonic() - t0
                record_ok(i, dt)
                emit_client_row(trace_id, sampled, status, dt, attempt)
                return
            if status == 503 and attempt < retries:
                attempt += 1
                with lock:
                    stats["retried"] += 1
                    if (trace_id is not None
                            and len(retried_trace_ids) < MAX_TRACE_IDS):
                        retried_trace_ids.append(trace_id)
                time.sleep(retry_backoff_s)
                continue
            # 429 (shed) is NOT retried even with retries set: the server
            # deliberately chose to drop it and said when to come back —
            # it lands in the error accounting as its own status
            record_error(str(status), trace_id, i)
            emit_client_row(trace_id, sampled, status,
                            time.monotonic() - t0, attempt)
            return

    t_start = time.monotonic()
    counter = {"next": 0}

    def next_index() -> Optional[int]:
        with lock:
            i = counter["next"]
            if i >= n_requests:
                return None
            counter["next"] = i + 1
            return i

    def maybe_reconnect(done: int) -> None:
        if reconnect_every and done % reconnect_every == 0:
            client().close()

    if mode == "closed":
        def worker():
            done = 0
            while True:
                i = next_index()
                if i is None:
                    return
                one(i)
                done += 1
                maybe_reconnect(done)

        n_workers = concurrency
    else:
        period = 1.0 / rate_rps

        def worker():
            done = 0
            while True:
                i = next_index()
                if i is None:
                    return
                target = t_start + (due_offsets[i]
                                    if due_offsets is not None
                                    else i * period)
                lag = time.monotonic() - target
                if lag < 0:
                    time.sleep(-lag)
                elif lag > 0.001:
                    # all workers busy past this slot's due time: the
                    # client is saturated — visible, not absorbed
                    with lock:
                        stats["late"] += 1
                        stats["max_lag_s"] = max(stats["max_lag_s"], lag)
                one(i)
                done += 1
                maybe_reconnect(done)

        n_workers = open_workers
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t_start

    n_ok = len(latencies)
    out = {
        "mode": mode,
        "url": url,
        "concurrency": concurrency if mode == "closed" else None,
        "rate_rps": rate_rps if mode == "open" else None,
        "n_requests": n_requests,
        "n_ok": n_ok,
        "errors": errors,
        "error_trace_ids": error_trace_ids,
        "retried_trace_ids": retried_trace_ids,
        "n_retried": stats["retried"],
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(n_ok / wall_s, 2) if wall_s > 0 else None,
        "latency": _percentiles(latencies),
    }
    if mode == "open":
        out["late_sends"] = stats["late"]
        out["max_send_lag_ms"] = round(stats["max_lag_s"] * 1e3, 3)
    if class_of is not None:
        out["by_class"] = {
            cls: {
                "n_requests": acc["n_requests"],
                "n_ok": len(acc["lat"]),
                "dropped": acc["n_requests"] - len(acc["lat"]),
                "n_shed_429": acc["errors"].get("429", 0),
                "errors": dict(sorted(acc["errors"].items())),
                "latency": _percentiles(acc["lat"]),
            }
            for cls, acc in sorted(class_acc.items())
        }
    if rates_schedule:
        out["rates_schedule"] = [[r, d] for r, d in rates_schedule]
        out["steps"] = [
            dict(meta,
                 n_ok=len(acc["lat"]),
                 errors=dict(sorted(acc["errors"].items())),
                 latency=_percentiles(acc["lat"]))
            for meta, acc in zip(step_meta, step_acc)
        ]
    return out


def run_ladder(
    url: str,
    payload: Payload,
    rates: List[float],
    warmup_s: float = 1.0,
    measure_s: float = 4.0,
    timeout_s: float = 30.0,
    retries: int = 0,
    open_workers: int = 32,
    stop_error_rate: float = 0.5,
    content_type: str = "application/json",
    trace: bool = True,
    events: Any = None,
    durations: Optional[List[float]] = None,
    class_of: Optional[Callable[[int], str]] = None,
    extra_headers_of: Optional[Callable[[int], bytes]] = None,
) -> Dict[str, Any]:
    """Open-loop rate ladder: for each rate, an UNTIMED warmup window then
    a measured window, both issuing at that fixed rate. The ladder stops
    early once a step's error rate exceeds ``stop_error_rate`` (the service
    is past saturation; higher rates would only time out the client).
    Returns the per-step results plus ``max_clean_rate_rps`` — the highest
    offered rate served with zero errors. ``events`` (client-side
    ``client/request`` rows) covers the MEASURED windows only.

    ``durations``: SWING mode — one ``(rates[s], durations[s])`` schedule
    driven as a single continuous run on one persistent worker pool (no
    reconnect, no warmup windows between steps: the offered rate swings
    mid-run, which is exactly what the autoscaler must track). Per-step
    results come from the schedule accounting; ``max_clean_rate_rps`` is
    the highest rate whose step finished error-free. ``class_of``/
    ``extra_headers_of`` ride through to :func:`run_loadgen` (per-
    priority-class accounting + admission headers), in both modes."""
    if durations is not None:
        if len(durations) != len(rates):
            raise ValueError(
                f"durations ({len(durations)}) must match rates "
                f"({len(rates)})")
        run = run_loadgen(
            url, payload, rates_schedule=list(zip(rates, durations)),
            warmup_requests=0, timeout_s=timeout_s, retries=retries,
            open_workers=open_workers, content_type=content_type,
            trace=trace, events=events, class_of=class_of,
            extra_headers_of=extra_headers_of)
        max_clean = None
        for step in run["steps"]:
            if not step["errors"]:
                max_clean = max(max_clean or 0.0,
                                step["offered_rate_rps"])
        return {"steps": run["steps"], "swing": True, "run": run,
                "max_clean_rate_rps": max_clean}
    steps: List[Dict[str, Any]] = []
    max_clean = None
    for rate in rates:
        n_warm = max(1, int(rate * warmup_s))
        run_loadgen(url, payload, mode="open", rate_rps=rate,
                    n_requests=n_warm, warmup_requests=0,
                    timeout_s=timeout_s, retries=retries,
                    open_workers=open_workers, content_type=content_type,
                    trace=trace, extra_headers_of=extra_headers_of)
        n_meas = max(1, int(rate * measure_s))
        step = run_loadgen(url, payload, mode="open", rate_rps=rate,
                           n_requests=n_meas, warmup_requests=0,
                           timeout_s=timeout_s, retries=retries,
                           open_workers=open_workers,
                           content_type=content_type,
                           trace=trace, events=events, class_of=class_of,
                           extra_headers_of=extra_headers_of)
        step["offered_rate_rps"] = rate
        steps.append(step)
        n_err = step["n_requests"] - step["n_ok"]
        if not n_err:
            max_clean = rate
        if step["n_requests"] and n_err / step["n_requests"] > stop_error_rate:
            step["ladder_stopped"] = (
                f"error rate {n_err}/{step['n_requests']} exceeds "
                f"{stop_error_rate:.0%}; not driving higher rates")
            break
    return {"steps": steps, "max_clean_rate_rps": max_clean,
            "warmup_s": warmup_s, "measure_s": measure_s}


# -- self-contained serving benchmark (bench.py `serving` section) -----------


def _make_member_dirs(root, cfg, seeds):
    """Random-init member checkpoints: serving latency/throughput depend on
    shapes, not trained values, so the bench needs no training run."""
    import jax

    from ..models.gan import GAN
    from ..training.checkpoint import save_params

    gan = GAN(cfg)
    dirs = []
    for s in seeds:
        d = root / f"seed_{s}"
        d.mkdir(parents=True, exist_ok=True)
        cfg.save(d / "config.json")
        save_params(d / "best_model_sharpe.msgpack",
                    gan.init(jax.random.key(s)))
        dirs.append(str(d))
    return dirs


def bench_serving(
    n_stocks: int = 500,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 4,
    months: int = 60,
    n_requests: int = 200,
    seed: int = 42,
) -> Dict[str, Any]:
    """End-to-end loopback serving benchmark: random-init K-member ensemble,
    AOT-warmed engine, HTTP loopback, closed loop at c=1/c=4 plus an open
    loop near the measured capacity. Returns one JSON-able dict."""
    import tempfile
    from pathlib import Path

    from ..utils.config import GANConfig
    from .engine import InferenceEngine, bucket_for
    from .server import ServingService, make_server

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    macro = rng.standard_normal((months, n_macro)).astype(np.float32)

    with tempfile.TemporaryDirectory(prefix="dlap_serving_bench_") as td:
        td = Path(td)
        dirs = _make_member_dirs(td / "ckpts", cfg, range(1, n_members + 1))
        t0 = time.monotonic()
        stock_bucket = bucket_for(n_stocks, [64 * 2**i for i in range(9)])
        engine = InferenceEngine(
            dirs, macro_history=macro, stock_buckets=(stock_bucket,))
        load_s = time.monotonic() - t0
        service = ServingService(engine, run_dir=str(td / "serve_run"))
        t0 = time.monotonic()
        service.warmup()
        warmup_s = time.monotonic() - t0
        httpd = make_server(service)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        host, port = httpd.server_address[:2]
        url = f"http://{host}:{port}/v1/weights"

        def make_payload(offset: int) -> Callable[[int], Dict[str, Any]]:
            # every request of every loop is a distinct payload — the LRU
            # cache must not absorb any of the measured traffic
            def payload(i: int) -> Dict[str, Any]:
                r = np.random.default_rng(seed + 1 + offset + i)
                return {
                    "individual": r.standard_normal(
                        (n_stocks, n_features)).astype(np.float32).tolist(),
                    "month": int(i % months),
                }

            return payload

        try:
            closed_1 = run_loadgen(url, make_payload(0), mode="closed",
                                   concurrency=1, n_requests=n_requests)
            closed_4 = run_loadgen(url, make_payload(10**6), mode="closed",
                                   concurrency=4, n_requests=n_requests)
            cap = closed_4["throughput_rps"] or 1.0
            open_loop = run_loadgen(
                url, make_payload(2 * 10**6), mode="open",
                rate_rps=max(1.0, 0.8 * cap),
                n_requests=min(n_requests, int(cap * 5) or n_requests))
            stats = engine.stats()
            metrics = service.metrics()
        finally:
            httpd.shutdown()
            service.close()

    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months}",
        "stock_bucket": stock_bucket,
        "engine_load_s": round(load_s, 3),
        "warmup_compile_s": round(warmup_s, 3),
        "closed_loop_c1": closed_1,
        "closed_loop_c4": closed_4,
        "open_loop_0.8cap": open_loop,
        "compiles": stats["compiles"],
        "dispatches": stats["dispatches"],
        "batcher_flushes": metrics["batcher"]["flushes"],
        "note": "HTTP loopback, random-init members (latency depends on "
                "shapes, not trained values); compiles must not grow "
                "after warmup — steady state is recompile-free",
    }


# -- replicated async benchmark (bench.py `serving_async` section) -----------


def compact_payload_bytes(individual: np.ndarray, month: int,
                          b64_response: bool = True) -> bytes:
    """One pre-encoded compact-wire request body: base64 float32
    characteristics (+ ``encoding: b64`` for a compact response)."""
    import base64

    a = np.ascontiguousarray(individual, np.float32)
    d: Dict[str, Any] = {
        "individual_b64": base64.b64encode(a.tobytes()).decode(),
        "month": int(month),
    }
    if b64_response:
        d["encoding"] = "b64"
    return json.dumps(d).encode()


def binary_payload_bytes(individual: np.ndarray, month: int) -> bytes:
    """One raw-f32-wire request body (``server.BINARY_CONTENT_TYPE``):
    [i32 month][u32 n][n*F f32 row-major characteristics]."""
    import struct

    a = np.ascontiguousarray(individual, np.float32)
    return struct.pack("<iI", int(month), a.shape[0]) + a.tobytes()


def bench_serving_async(
    n_stocks: int = 500,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 4,
    months: int = 60,
    replicas: int = 2,
    n_requests: int = 320,
    ladder_rates=(100.0, 200.0, 300.0, 400.0, 500.0),
    seed: int = 42,
) -> Dict[str, Any]:
    """The production-path benchmark: a supervised R-replica fleet on one
    SO_REUSEPORT port (each replica its own process: engine, continuous
    batcher, cache shard), driven closed-loop at c=32 and c=4 plus an
    open-loop rate ladder, over both wire formats. Result caching is
    DISABLED (--cache_size 0): every measured request reaches an engine.
    Steady-state recompiles are computed per replica and must be zero."""
    import tempfile
    from pathlib import Path

    from ..utils.config import GANConfig
    from .aserver import pick_free_port
    from .engine import bucket_for
    from .fleet import ReplicaFleet, server_child_argv
    from .server import build_arg_parser

    from .server import BINARY_CONTENT_TYPE

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    # cap flushes at 8: a 16-deep flush is an ~11 ms head-of-line block on
    # CPU — two 8-deep flushes give the same throughput with half the tail
    batch_buckets = (1, 2, 4, 8)
    with tempfile.TemporaryDirectory(prefix="dlap_serving_async_") as td:
        td = Path(td)
        dirs = _make_member_dirs(td / "ckpts", cfg, range(1, n_members + 1))
        macro = rng.standard_normal((months, n_macro)).astype(np.float32)
        np.save(td / "macro.npy", macro)
        stock_bucket = bucket_for(n_stocks, [64 * 2**i for i in range(9)])
        run_dir = td / "fleet_run"
        args = build_arg_parser().parse_args([
            "--checkpoint_dirs", *dirs,
            "--macro_npy", str(td / "macro.npy"),
            "--stock_buckets", str(stock_bucket),
            "--batch_buckets", ",".join(str(b) for b in batch_buckets),
            "--max_queue", "512",
            "--cache_size", "0",
            "--run_dir", str(run_dir),
        ])
        port = pick_free_port()
        argvs = [server_child_argv(args, i, run_dir / f"replica{i}", port)
                 for i in range(replicas)]
        fleet = ReplicaFleet(argvs, run_dir)
        url = f"http://127.0.0.1:{port}/v1/weights"

        # pre-encoded request bodies (more than any replica could cache —
        # and caching is off anyway): the client's 20 ms-per-payload
        # json.dumps must not be measured as server latency
        n_payloads = 64

        def bodies(wire: str) -> List[bytes]:
            out = []
            for i in range(n_payloads):
                r = np.random.default_rng(seed + 1 + i)
                a = r.standard_normal(
                    (n_stocks, n_features)).astype(np.float32)
                if wire == "binary":
                    out.append(binary_payload_bytes(a, i % months))
                elif wire == "b64":
                    out.append(compact_payload_bytes(a, i % months))
                else:
                    out.append(json.dumps(
                        {"individual": a.tolist(),
                         "month": int(i % months)}).encode())
            return out

        bin_bodies = bodies("binary")
        b64_bodies = bodies("b64")
        json_bodies = bodies("json")

        def make(pool):
            return lambda i: pool[i % len(pool)]

        def best_of(n_trials, **kwargs):
            # this bench runs on shared infrastructure whose CPU quota
            # throttles in bursts (identical back-to-back trials swing
            # ~1.8×); best-of-N isolates the serving stack from the
            # neighbors, and every trial's numbers stay in `trials`
            runs = [run_loadgen(url, **kwargs) for _ in range(n_trials)]
            best = max(runs, key=lambda r: r["throughput_rps"] or 0)
            best = dict(best)
            best["trials"] = [
                {"throughput_rps": r["throughput_rps"],
                 "p99_ms": (r["latency"] or {}).get("p99_ms")}
                for r in runs]
            return best

        try:
            # start INSIDE the try: a replica that crash-loops during
            # startup must not leak live children past the bench
            t0 = time.monotonic()
            fleet.start()
            fleet.wait_ready(timeout=600.0)
            startup_s = time.monotonic() - t0
            # warm every batch-bucket shape's first execution before the
            # measured windows (warmup() compiles but does not run them)
            run_loadgen(url, make(bin_bodies), mode="closed",
                        concurrency=32, n_requests=4 * n_payloads,
                        warmup_requests=4,
                        content_type=BINARY_CONTENT_TYPE)
            closed_32_bin = best_of(
                3, payload=make(bin_bodies), mode="closed", concurrency=32,
                n_requests=n_requests, warmup_requests=0, retries=2,
                content_type=BINARY_CONTENT_TYPE)
            closed_16_bin = best_of(
                3, payload=make(bin_bodies), mode="closed", concurrency=16,
                n_requests=n_requests, warmup_requests=0, retries=2,
                content_type=BINARY_CONTENT_TYPE)
            closed_32_b64 = run_loadgen(
                url, make(b64_bodies), mode="closed", concurrency=32,
                n_requests=n_requests, warmup_requests=4, retries=2)
            closed_32_json = run_loadgen(
                url, make(json_bodies), mode="closed", concurrency=32,
                n_requests=max(64, n_requests // 2), warmup_requests=4,
                retries=2)
            closed_4_json = run_loadgen(
                url, make(json_bodies), mode="closed", concurrency=4,
                n_requests=max(64, n_requests // 2), warmup_requests=4,
                retries=2)
            ladder = run_ladder(
                url, make(bin_bodies), rates=list(ladder_rates),
                warmup_s=1.0, measure_s=3.0, retries=2,
                content_type=BINARY_CONTENT_TYPE)

            # per-replica engine metrics: each fresh connection lands on
            # some live replica; poll until every id has answered
            per_replica: Dict[str, Any] = {}
            for _ in range(40 * replicas):
                if len(per_replica) >= replicas:
                    break
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics",
                            timeout=10) as r:
                        m = json.loads(r.read())
                    per_replica.setdefault(str(m.get("replica")), m)
                except OSError:
                    time.sleep(0.1)
        finally:
            summaries = fleet.stop()

    # one compile per (stock bucket × batch bucket) program + the macro
    # LSTM step program — everything beyond that happened under traffic
    expected_warmup = len(batch_buckets) + 1
    steady_state_recompiles = {
        r: m["engine"]["compiles"] - expected_warmup
        for r, m in sorted(per_replica.items())
    }
    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months}",
        "replicas": replicas,
        "stock_bucket": stock_bucket,
        "batch_buckets": list(batch_buckets),
        "fleet_startup_s": round(startup_s, 3),
        "closed_loop_c32_bin": closed_32_bin,
        "closed_loop_c16_bin": closed_16_bin,
        "closed_loop_c32_b64": closed_32_b64,
        "closed_loop_c32_json": closed_32_json,
        "closed_loop_c4_json": closed_4_json,
        "open_loop_ladder_bin": ladder,
        "steady_state_recompiles": steady_state_recompiles,
        "dispatches": {r: m["engine"]["dispatches"]
                       for r, m in sorted(per_replica.items())},
        "batcher": {r: m["batcher"] for r, m in sorted(per_replica.items())},
        "replica_restarts": [
            (s or {}).get("restarts", 0) for s in summaries],
        "note": "supervised SO_REUSEPORT replica fleet, HTTP loopback "
                "keep-alive, result cache DISABLED (every request reaches "
                "an engine), random-init members; *_bin = raw-f32 wire "
                "(application/x-dlap-f32), *_b64 = base64 float32 JSON "
                "envelope, *_json = plain JSON lists; "
                "steady_state_recompiles must be all zero",
    }


# -- rolling-reload benchmark (bench.py --promotion, BENCH_PROMOTION.json) ---


def bench_rolling_reload(
    n_stocks: int = 500,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 2,
    months: int = 60,
    replicas: int = 2,
    rate_rps: float = 40.0,
    load_seconds: float = 12.0,
    seed: int = 42,
) -> Dict[str, Any]:
    """The promotion control plane's acceptance benchmark: a supervised
    R-replica fleet boots from the promotion pointer, an OPEN-loop load
    runs the whole time, and mid-load a new candidate is promoted and
    rolled across the fleet one replica at a time
    (``fleet.RollingUpdater``: per-replica admin endpoints, post-reload
    health window). The bars budgets.json gates:

      * ``dropped_requests == 0`` — the hot-swap dropped no traffic;
      * per-replica ``steady_state_recompiles == 0`` — a reload re-stacks
        params in place and NEVER recompiles;
      * both replicas converged on the promoted fingerprint.
    """
    import tempfile
    from pathlib import Path

    from ..reliability.promotion import promote
    from ..utils.config import GANConfig
    from .aserver import pick_free_port
    from .engine import bucket_for
    from .fleet import ReplicaFleet, RollingUpdater, server_child_argv
    from .server import BINARY_CONTENT_TYPE, build_arg_parser

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    with tempfile.TemporaryDirectory(prefix="dlap_rolling_reload_") as td:
        td = Path(td)
        v1 = _make_member_dirs(td / "v1", cfg, range(1, n_members + 1))
        v2 = _make_member_dirs(td / "v2", cfg,
                               range(101, 101 + n_members))
        macro = rng.standard_normal((months, n_macro)).astype(np.float32)
        np.save(td / "macro.npy", macro)
        ctl = td / "ctl"
        incumbent = promote(ctl, v1, source="bench_v1")

        stock_bucket = bucket_for(n_stocks, [64 * 2**i for i in range(9)])
        run_dir = td / "fleet_run"
        args = build_arg_parser().parse_args([
            "--pointer", str(ctl),
            "--macro_npy", str(td / "macro.npy"),
            "--stock_buckets", str(stock_bucket),
            "--batch_buckets", "1,2,4,8",
            "--max_queue", "512",
            "--cache_size", "0",
            "--run_dir", str(run_dir),
        ])
        port = pick_free_port()
        admin_ports = []
        for _ in range(replicas):
            ap = pick_free_port()
            while ap in admin_ports or ap == port:
                ap = pick_free_port()
            admin_ports.append(ap)
        argvs = [server_child_argv(args, i, run_dir / f"replica{i}", port,
                                   admin_port=admin_ports[i])
                 for i in range(replicas)]
        admin_urls = [f"http://127.0.0.1:{ap}" for ap in admin_ports]
        fleet = ReplicaFleet(argvs, run_dir)
        url = f"http://127.0.0.1:{port}/v1/weights"
        bodies = []
        for i in range(64):
            r = np.random.default_rng(seed + 1 + i)
            bodies.append(binary_payload_bytes(
                r.standard_normal(
                    (n_stocks, n_features)).astype(np.float32),
                i % months))

        n_requests = int(rate_rps * load_seconds)
        load_out: Dict[str, Any] = {}

        def _drive():
            load_out.update(run_loadgen(
                url, lambda i: bodies[i % len(bodies)], mode="open",
                rate_rps=rate_rps, n_requests=n_requests,
                warmup_requests=0, retries=2, timeout_s=30.0,
                open_workers=8, content_type=BINARY_CONTENT_TYPE))

        try:
            t0 = time.monotonic()
            fleet.start()
            fleet.wait_ready(timeout=600.0)
            startup_s = time.monotonic() - t0
            # warm every batch-bucket shape before the measured window
            run_loadgen(url, lambda i: bodies[i % len(bodies)],
                        mode="closed", concurrency=16, n_requests=128,
                        warmup_requests=4,
                        content_type=BINARY_CONTENT_TYPE)
            loader = threading.Thread(target=_drive, name="bench-load")
            loader.start()
            time.sleep(min(2.0, load_seconds / 4))
            promoted = promote(ctl, v2, source="bench_v2")
            t0 = time.monotonic()
            roll = RollingUpdater(admin_urls, ctl).roll()
            roll_s = time.monotonic() - t0
            loader.join()

            per_replica: Dict[str, Any] = {}
            for u in admin_urls:
                with urllib.request.urlopen(u + "/metrics", timeout=10) as r:
                    m = json.loads(r.read())
                per_replica[str(m.get("replica"))] = m
        finally:
            summaries = fleet.stop()

    target_fp = str(promoted["params_fingerprint"])[:16]
    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months}",
        "replicas": replicas,
        "rate_rps": rate_rps,
        "fleet_startup_s": round(startup_s, 3),
        "roll_s": round(roll_s, 3),
        "roll_status": roll["status"],
        "incumbent_generation": incumbent["generation"],
        "promoted_generation": promoted["generation"],
        "n_requests": load_out.get("n_requests"),
        "n_ok": load_out.get("n_ok"),
        "dropped_requests": (
            int(load_out["n_requests"]) - int(load_out["n_ok"])),
        "errors": load_out.get("errors"),
        "n_retried": load_out.get("n_retried"),
        "throughput_rps": load_out.get("throughput_rps"),
        "latency": load_out.get("latency"),
        "steady_state_recompiles": {
            r: m["engine"]["steady_state_recompiles"]
            for r, m in sorted(per_replica.items())},
        "serving_fingerprints": {
            r: m["engine"]["params_fingerprint"]
            for r, m in sorted(per_replica.items())},
        "converged": all(
            m["engine"]["params_fingerprint"] == target_fp
            for m in per_replica.values()),
        "generations": {
            r: m["engine"]["params_generation"]
            for r, m in sorted(per_replica.items())},
        "replica_restarts": [
            (s or {}).get("restarts", 0) for s in summaries],
        "note": "supervised SO_REUSEPORT fleet boots from the promotion "
                "pointer; open-loop raw-f32 load runs across promote → "
                "health-gated rolling reload (RollingUpdater over the "
                "per-replica admin endpoints); dropped_requests and every "
                "replica's steady_state_recompiles must be 0 and both "
                "replicas must converge on the promoted fingerprint",
    }


# -- load-adaptive fleet benchmark (bench.py --loadadapt, BENCH_LOADADAPT) ---


def bench_loadadapt(
    n_stocks: int = 1000,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 2,
    months: int = 60,
    max_replicas: int = 2,
    n_distinct: int = 48,
    bulk_every: int = 4,
    phase_s=(5.0, 14.0, 8.0),
    surge_factor: float = 1.3,
    settle_timeout_s: float = 60.0,
    seed: int = 42,
) -> Dict[str, Any]:
    """The load-adaptive fleet's acceptance benchmark: a supervised fleet
    boots at ONE replica with the autoscaler live, and the loadgen drives
    a 10× mid-run rate swing (base → 10×base → base, one worker pool, no
    reconnect) of mixed-priority traffic — every ``bulk_every``-th request
    is bulk, the rest interactive — drawn from ``n_distinct`` distinct
    payloads so concurrent twins exercise single-flight coalescing. The
    surge rate is calibrated to ``surge_factor ×`` the single replica's
    measured closed-loop capacity over DISTINCT payloads (coalescing
    cannot absorb it for free — the calibration must measure real
    dispatch capacity), so the surge genuinely exceeds what the boot
    fleet can serve. A dedicated duplicate-heavy closed-loop burst after
    the swing measures the pure coalescing lever. The bars budgets.json
    gates:

      * ``dropped_interactive == 0`` — interactive traffic survives the
        surge (DAGOR-style shedding turns the overload onto bulk, client
        retries cover replica churn);
      * ``shed_bulk_429 >= 1`` — bulk was deliberately shed with 429s;
      * ``autoscale.scale_ups >= 1`` and ``scale_downs >= 1`` — the
        replica count demonstrably tracked the swing up AND back down;
      * ``coalesce_burst.dispatch_ratio`` ≪ 1 — concurrent identical
        queries collapsed onto shared dispatches (O(users) →
        O(distinct));
      * ``steady_state_recompiles_max == 0`` — per replica incarnation,
        measured from each replica's own events.
    """
    import tempfile
    from pathlib import Path

    from ..observability.events import EventLog
    from ..observability.trace import read_jsonl
    from ..utils.config import GANConfig
    from .aserver import pick_free_port
    from .autoscale import AutoscalePolicy, Autoscaler, FleetController
    from .engine import bucket_for
    from .fleet import ReplicaFleet, read_fleet_json, server_child_argv
    from .flight import FlightRecorder
    from .server import BINARY_CONTENT_TYPE, build_arg_parser

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    batch_buckets = (1, 2, 4, 8)
    with tempfile.TemporaryDirectory(prefix="dlap_loadadapt_") as td:
        td = Path(td)
        dirs = _make_member_dirs(td / "ckpts", cfg, range(1, n_members + 1))
        macro = rng.standard_normal((months, n_macro)).astype(np.float32)
        np.save(td / "macro.npy", macro)
        stock_bucket = bucket_for(n_stocks, [64 * 2**i for i in range(9)])
        run_dir = td / "fleet_run"
        args = build_arg_parser().parse_args([
            "--checkpoint_dirs", *dirs,
            "--macro_npy", str(td / "macro.npy"),
            "--stock_buckets", str(stock_bucket),
            "--batch_buckets", ",".join(str(b) for b in batch_buckets),
            "--max_queue", "32",           # small queue → visible shedding
            "--bulk_threshold", "0.5",
            "--cache_size", "0",           # coalescing, not the LRU, dedups
            "--run_dir", str(run_dir),
        ])
        # distinct calibration bodies: every request its own payload, so
        # the measured closed-loop rps is true DISPATCH capacity, not the
        # coalescer absorbing duplicates
        cal_bodies = []
        for i in range(512):
            r = np.random.default_rng(seed + 10_000 + i)
            cal_bodies.append(binary_payload_bytes(
                r.standard_normal(
                    (n_stocks, n_features)).astype(np.float32),
                i % months))
        host, port = "127.0.0.1", pick_free_port()
        admin0 = pick_free_port()
        while admin0 == port:
            admin0 = pick_free_port()

        def make_argv(replica_id: int, admin_port: int):
            return server_child_argv(
                args, replica_id, run_dir / f"replica{replica_id}", port,
                admin_port=admin_port)

        fleet = ReplicaFleet([make_argv(0, admin0)], run_dir)
        events = EventLog(run_dir, process_index=0,
                          filename="events.autoscaler.jsonl")
        flight = FlightRecorder(run_dir=run_dir, events=events)
        controller = FleetController(
            fleet, make_argv, host, port, admin_ports={0: admin0})
        policy = AutoscalePolicy(
            min_replicas=1, max_replicas=max_replicas,
            poll_s=0.25, up_queue_depth=6.0, up_shed_rate=0.02,
            down_queue_depth=1.0, up_hysteresis=2, down_hysteresis=12,
            cooldown_s=3.0, drain_timeout_s=8.0)
        autoscaler = Autoscaler(controller, policy, events=events,
                                flight=flight)
        url = f"http://{host}:{port}/v1/weights"
        bodies = []
        for i in range(n_distinct):
            r = np.random.default_rng(seed + 1 + i)
            bodies.append(binary_payload_bytes(
                r.standard_normal(
                    (n_stocks, n_features)).astype(np.float32),
                i % months))

        def payload(i: int) -> bytes:
            return bodies[i % len(bodies)]

        def class_of(i: int) -> str:
            return "bulk" if i % bulk_every == 0 else "interactive"

        try:
            t0 = time.monotonic()
            fleet.start()
            fleet.wait_ready(timeout=600.0)
            controller.publish_layout()
            startup_s = time.monotonic() - t0
            # warm every batch-bucket shape, then calibrate the single
            # replica's closed-loop DISPATCH capacity over distinct
            # payloads (autoscaler NOT yet running: the calibration burst
            # must not trigger a scale-up)
            run_loadgen(url, lambda i: cal_bodies[i % len(cal_bodies)],
                        mode="closed", concurrency=16,
                        n_requests=96, warmup_requests=4,
                        content_type=BINARY_CONTENT_TYPE)
            cal = run_loadgen(url, lambda i: cal_bodies[i % len(cal_bodies)],
                              mode="closed", concurrency=8,
                              n_requests=160, warmup_requests=0,
                              content_type=BINARY_CONTENT_TYPE)
            capacity_rps = cal["throughput_rps"] or 50.0
            surge_rate = max(10.0, round(surge_factor * capacity_rps, 1))
            base_rate = round(surge_rate / 10.0, 2)  # THE 10x swing
            autoscaler.start()
            swing = run_ladder(
                url, payload,
                rates=[base_rate, surge_rate, base_rate],
                durations=list(phase_s),
                retries=6, open_workers=64, timeout_s=30.0,
                content_type=BINARY_CONTENT_TYPE, class_of=class_of)
            # settle: the trailing quiet phase must bring the fleet back
            # down to min_replicas (scale-down drain included)
            deadline = time.monotonic() + settle_timeout_s
            while time.monotonic() < deadline:
                if len(fleet.live_ids()) <= policy.min_replicas \
                        and autoscaler.scale_downs >= 1:
                    break
                time.sleep(0.5)
            settle_live = list(fleet.live_ids())
            # the pure coalescing lever, measured in isolation: a closed-
            # loop burst of 16 concurrent clients over TWO distinct
            # payloads — O(users) requests must become O(distinct)
            # dispatches
            pre = [controller.metrics(rid) for rid in settle_live]
            burst = run_loadgen(
                url, lambda i: bodies[i % 2], mode="closed",
                concurrency=16, n_requests=480, warmup_requests=0,
                content_type=BINARY_CONTENT_TYPE)
            post = [controller.metrics(rid) for rid in settle_live]

            def _co(ms):
                h = sum((m or {}).get("coalesce", {}).get("hits", 0)
                        for m in ms)
                d = sum((m or {}).get("coalesce", {}).get("dispatches", 0)
                        for m in ms)
                return h, d

            (h0, d0), (h1, d1) = _co(pre), _co(post)
            burst_hits, burst_disp = h1 - h0, d1 - d0
            # live replicas' own view (steady-state gauge cross-check)
            live_metrics = {
                rid: controller.metrics(rid) for rid in settle_live}
        finally:
            autoscaler.stop()
            summaries = fleet.stop()
            events.close()

        # per-replica evidence from each incarnation's OWN events (drained
        # replicas included — their files outlive the processes)
        expected_warmup = len(batch_buckets) + 1  # fwd per bucket + macro
        recompiles: Dict[str, int] = {}
        shed_by_reason: Dict[str, int] = {}
        coalesce_hits = coalesce_dispatches = 0
        for rdir in sorted(run_dir.glob("replica*")):
            if not rdir.is_dir():
                continue
            n_compiles = 0
            for row in read_jsonl(rdir / "events.jsonl"):
                if row.get("kind") != "counter":
                    continue
                name = row.get("name")
                if name == "serve/recompile":
                    n_compiles += 1
                elif name == "serve/shed":
                    reason = str(row.get("reason"))
                    shed_by_reason[reason] = (
                        shed_by_reason.get(reason, 0) + 1)
                elif name == "serve/coalesce":
                    if row.get("hit"):
                        coalesce_hits += 1
                    else:
                        coalesce_dispatches += 1
            recompiles[rdir.name] = n_compiles - expected_warmup
        fleet_layout = read_fleet_json(run_dir)

    by_class = swing["run"]["by_class"]
    interactive = by_class.get("interactive") or {}
    bulk = by_class.get("bulk") or {}
    lookups = coalesce_hits + coalesce_dispatches
    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months}",
        "fleet_startup_s": round(startup_s, 3),
        "calibration_closed_c8_rps": capacity_rps,
        "base_rate_rps": base_rate,
        "surge_rate_rps": surge_rate,
        "swing_factor": round(surge_rate / base_rate, 2),
        "phases_s": list(phase_s),
        "steps": swing["steps"],
        "by_class": by_class,
        "n_requests": swing["run"]["n_requests"],
        "n_ok": swing["run"]["n_ok"],
        "n_retried": swing["run"]["n_retried"],
        "dropped_interactive": interactive.get("dropped"),
        "interactive_requests": interactive.get("n_requests"),
        "shed_bulk_429": bulk.get("n_shed_429"),
        "shed_by_reason_server": dict(sorted(shed_by_reason.items())),
        "coalesce": {
            "hits": coalesce_hits,
            "dispatches": coalesce_dispatches,
            "dispatch_ratio": (round(coalesce_dispatches / lookups, 4)
                               if lookups else None),
        },
        "coalesce_burst": {
            "n_requests": burst["n_requests"],
            "n_ok": burst["n_ok"],
            "hits": burst_hits,
            "dispatches": burst_disp,
            "dispatch_ratio": (round(
                burst_disp / (burst_hits + burst_disp), 4)
                if (burst_hits + burst_disp) else None),
            "throughput_rps": burst["throughput_rps"],
        },
        "autoscale": {
            "scale_ups": autoscaler.scale_ups,
            "scale_downs": autoscaler.scale_downs,
            "peak_replicas": fleet.replicas,
            "final_live_replicas": len(settle_live),
            "decisions_tail": list(autoscaler.decisions)[-8:],
        },
        "steady_state_recompiles": dict(sorted(recompiles.items())),
        "steady_state_recompiles_max": (max(recompiles.values())
                                        if recompiles else None),
        "fleet_json_final": fleet_layout,
        "live_engine_fingerprints": {
            str(rid): ((m or {}).get("engine") or {}).get(
                "params_fingerprint")
            for rid, m in sorted(live_metrics.items())},
        "replica_summaries": [
            {"outcome": (s or {}).get("outcome"),
             "restarts": (s or {}).get("restarts")} for s in summaries],
        "note": "supervised SO_REUSEPORT fleet boots at 1 replica with "
                "the autoscaler live; open-loop mixed-priority traffic "
                "(every Nth request bulk) swings base -> 10x base -> "
                "base on one persistent worker pool; surge is calibrated "
                "above single-replica capacity so the fleet MUST shed "
                "bulk (429 + Retry-After) and scale up, then drain back "
                "to 1 replica in the quiet tail; distinct-payload pool "
                "of size n_distinct makes concurrent twins coalesce — "
                "dispatch_ratio is dispatches / coalesce-eligible "
                "requests; dropped_interactive and every replica's "
                "steady-state recompiles must be 0",
    }


# -- SLO detection drill + probe overhead (bench.py --slo, BENCH_SLO.json) ---


def bench_slo(
    n_stocks: int = 500,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 2,
    months: int = 60,
    n_distinct: int = 64,
    probe_interval_s: float = 0.25,
    overhead_probe_interval_s: float = 1.0,
    probe_timeout_s: float = 1.0,
    engine_poll_s: float = 0.1,
    restart_backoff_s: float = 3.0,
    firing_timeout_s: float = 30.0,
    resolve_timeout_s: float = 120.0,
    seed: int = 42,
) -> Dict[str, Any]:
    """The SLO plane's acceptance benchmark: a supervised 2-replica fleet
    under the live blackbox prober + burn-rate engine, with two detection
    drills and a probe-overhead measurement. The bars budgets.json gates:

      * ``probe_overhead.rps_ratio >= 0.95`` — the prober's fixture
        traffic at the production cadence costs at most 5% of closed-loop
        throughput (interleaved best-of-3, prober on vs off);
      * ``kill_drill.detection_s`` / ``wedge_drill.detection_s`` under
        budget — a replica SIGKILLed (dead: connections refused) and,
        separately, SIGSTOPped (wedged-but-accepting: the kernel backlog
        accepts, nothing answers — invisible to whitebox metrics and
        between autoscaler polls) produces a FIRING availability alert
        within seconds;
      * ``steady_state_recompiles_max == 0`` — per replica incarnation
        (the restarted incarnation's warmup compiles are budgeted), probe
        traffic included: the fixture rides existing buckets.

    Both drills also prove the resolve path: the supervisor restarts the
    killed replica (the wedged one is SIGCONTed), probes recover, and the
    alert RESOLVES once the long window's burn drops back under
    threshold.
    """
    import dataclasses
    import os as _os
    import signal as _signal
    import subprocess
    import sys
    import tempfile
    from pathlib import Path

    from ..observability.events import EventLog
    from ..observability.slo import FileAlertSink, SLOEngine, drill_spec
    from ..observability.trace import read_jsonl
    from ..utils.config import GANConfig
    from .aserver import pick_free_port
    from .engine import bucket_for
    from .fleet import REPLICA_POLICY, ReplicaFleet, server_child_argv
    from .flight import FlightRecorder
    from .probe import Prober, fixture_payload
    from .server import BINARY_CONTENT_TYPE, build_arg_parser

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    batch_buckets = (1, 2, 4, 8)
    with tempfile.TemporaryDirectory(prefix="dlap_slo_") as td:
        td = Path(td)
        dirs = _make_member_dirs(td / "ckpts", cfg, range(1, n_members + 1))
        macro = rng.standard_normal((months, n_macro)).astype(np.float32)
        np.save(td / "macro.npy", macro)
        stock_bucket = bucket_for(
            max(n_stocks, 64), [64 * 2**i for i in range(9)])
        run_dir = td / "fleet_run"
        args = build_arg_parser().parse_args([
            "--checkpoint_dirs", *dirs,
            "--macro_npy", str(td / "macro.npy"),
            "--stock_buckets", str(stock_bucket),
            "--batch_buckets", ",".join(str(b) for b in batch_buckets),
            "--max_queue", "64", "--cache_size", "0",
            "--run_dir", str(run_dir),
        ])
        host, port = "127.0.0.1", pick_free_port()
        admin_ports = {}
        for i in range(2):
            p = pick_free_port()
            while p == port or p in admin_ports.values():
                p = pick_free_port()
            admin_ports[i] = p
        # the drill must own the restart timing: a killed replica stays
        # down for ~restart_backoff_s (long enough to measure detection),
        # then comes back for the resolve leg
        policy = dataclasses.replace(
            REPLICA_POLICY, backoff_base_s=restart_backoff_s,
            backoff_max_s=restart_backoff_s, jitter_frac=0.0,
            min_uptime_s=0.5, poll_s=0.2)

        def make_argv(rid, admin_port):
            return server_child_argv(
                args, rid, run_dir / f"replica{rid}", port,
                admin_port=admin_port)

        fleet = ReplicaFleet(
            [make_argv(i, admin_ports[i]) for i in range(2)],
            run_dir, policy=policy)
        from .autoscale import FleetController

        controller = FleetController(
            fleet, make_argv, host, port, admin_ports=dict(admin_ports))
        url = f"http://{host}:{port}/v1/weights"
        bodies = []
        for i in range(n_distinct):
            r = np.random.default_rng(seed + 1 + i)
            bodies.append(binary_payload_bytes(
                r.standard_normal(
                    (n_stocks, n_features)).astype(np.float32),
                i % months))
        events = EventLog(run_dir, process_index=0,
                          filename="events.probe.jsonl")
        flight = FlightRecorder(run_dir=run_dir, events=events)
        prober = Prober(
            events, public_url=f"http://{host}:{port}",
            fixture=fixture_payload(n_features, month=0),
            fleet_dir=run_dir, interval_s=probe_interval_s,
            timeout_s=probe_timeout_s)
        spec = drill_spec()
        engine = SLOEngine(
            spec, {"probe": prober.counts}, events=events, flight=flight,
            sinks=(FileAlertSink(run_dir / "alerts.jsonl"),),
            poll_s=engine_poll_s)

        def measure() -> float:
            out = run_loadgen(
                url, lambda i: bodies[i % len(bodies)], mode="closed",
                concurrency=8, n_requests=160, warmup_requests=0,
                content_type=BINARY_CONTENT_TYPE)
            return out["throughput_rps"] or 0.0

        def wait_for(predicate, timeout_s: float) -> Optional[float]:
            t0 = time.monotonic()
            deadline = t0 + timeout_s
            while time.monotonic() < deadline:
                if predicate():
                    return time.monotonic() - t0
                time.sleep(0.05)
            return None

        def firing() -> bool:
            return bool(engine.firing())

        try:
            fleet.start()
            fleet.wait_ready(timeout=600.0)
            controller.publish_layout()
            # warmup: every batch bucket + the fixture shape
            run_loadgen(url, lambda i: bodies[i % len(bodies)],
                        mode="closed", concurrency=16, n_requests=96,
                        warmup_requests=4,
                        content_type=BINARY_CONTENT_TYPE)
            prober.probe_once()
            # -- probe overhead: interleaved best-of-3, prober off vs on.
            # The "on" prober is the standalone CLI in its OWN process —
            # exactly how a deployment runs it — so the measurement is the
            # server-side cost of probe traffic, not GIL contention
            # between prober threads and this process's loadgen workers
            # (measured at ~10% on the 2-core runner when co-located,
            # ~0% of which is the servers' doing)
            pkg = __name__.rsplit(".", 2)[0]
            cli_dir = run_dir / "probe_cli"
            probe_cmd = [
                sys.executable, "-m", f"{pkg}.serving.probe",
                "--url", f"http://{host}:{port}",
                "--fleet_dir", str(run_dir), "--run_dir", str(cli_dir),
                "--n_features", str(n_features),
                "--interval", str(overhead_probe_interval_s),
                "--timeout", str(probe_timeout_s)]
            off_rps, on_rps = [], []
            for _rep in range(3):
                off_rps.append(measure())
                # the "on" window must actually contain THIS rep's probe
                # traffic: the CLI's EventLog appends, so "file exists"
                # is satisfied by a previous rep — wait for GROWTH past
                # the pre-spawn size instead
                cli_events = cli_dir / "events.probe.jsonl"
                size_before = (cli_events.stat().st_size
                               if cli_events.exists() else 0)
                proc = subprocess.Popen(
                    probe_cmd, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL)
                try:
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline:
                        if (cli_events.exists()
                                and cli_events.stat().st_size
                                > size_before):
                            break
                        time.sleep(0.1)
                    time.sleep(overhead_probe_interval_s)
                    on_rps.append(measure())
                finally:
                    proc.terminate()
                    proc.wait(timeout=30)
            prober.start()
            engine.start()
            # settle: the engine needs one long window of clean probes
            # before a drill (otherwise the first window has no far edge)
            settle = wait_for(
                lambda: engine.ticks > 0
                and prober.counts()[1] >= 8, timeout_s=30.0)
            time.sleep(spec["objectives"][0]["windows"][0]["short_s"])
            # clean baseline (a transient startup blip may fire once on a
            # loaded runner — give it one window to resolve, then insist)
            wait_for(lambda: not firing(), timeout_s=30.0)
            assert not firing(), (
                "availability alert firing before any drill: "
                f"{engine.state()}")

            # -- drill 1: SIGKILL (dead replica: connections refused)
            pid0 = fleet.replica_pid(0)
            assert pid0 is not None
            _os.kill(pid0, _signal.SIGKILL)
            kill_detection_s = wait_for(firing, firing_timeout_s)
            kill_alert = list(engine.alerts)[-1] if engine.alerts else None
            # resolve: the supervisor restarts it; probes go clean again
            kill_resolve_s = wait_for(
                lambda: not firing(), resolve_timeout_s)

            # -- drill 2: SIGSTOP (wedged-but-accepting: backlog accepts,
            # nothing answers — the whitebox planes see a healthy process)
            pid1 = fleet.replica_pid(1)
            assert pid1 is not None
            _os.kill(pid1, _signal.SIGSTOP)
            try:
                wedge_detection_s = wait_for(firing, firing_timeout_s)
            finally:
                _os.kill(pid1, _signal.SIGCONT)
            wedge_resolve_s = wait_for(
                lambda: not firing(), resolve_timeout_s)
            probe_stats = prober.stats()
            engine_state = engine.state()
        finally:
            engine.stop()
            prober.stop()
            summaries = fleet.stop()
            events.close()

        # per-incarnation recompile evidence: a restarted replica pays its
        # warmup compiles again under a fresh run_id — steady state within
        # EVERY incarnation must stay at zero
        expected_warmup = len(batch_buckets) + 1  # fwd per bucket + macro
        recompiles: Dict[str, int] = {}
        for rdir in sorted(run_dir.glob("replica*")):
            if not rdir.is_dir():
                continue
            by_run: Dict[str, int] = {}
            for row in read_jsonl(rdir / "events.jsonl"):
                if (row.get("kind") == "counter"
                        and row.get("name") == "serve/recompile"):
                    rid = str(row.get("run_id"))
                    by_run[rid] = by_run.get(rid, 0) + 1
            for j, rid in enumerate(sorted(by_run)):
                recompiles[f"{rdir.name}.gen{j}"] = (
                    by_run[rid] - expected_warmup)
        alerts_file = [
            json.loads(line) for line in
            (run_dir / "alerts.jsonl").read_text().splitlines()
        ] if (run_dir / "alerts.jsonl").exists() else []

    best_off = max(off_rps) if off_rps else None
    best_on = max(on_rps) if on_rps else None
    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months} replicas=2",
        "slo_spec": spec,
        "probe": {
            "interval_s": probe_interval_s,
            "timeout_s": probe_timeout_s,
            **probe_stats,
        },
        "probe_overhead": {
            "closed_c8_rps_prober_off": off_rps,
            "closed_c8_rps_prober_on": on_rps,
            "rps_off": best_off,
            "rps_on": best_on,
            "rps_ratio": (round(best_on / best_off, 4)
                          if best_off else None),
        },
        "settle_s": settle,
        "kill_drill": {
            "detection_s": (round(kill_detection_s, 3)
                            if kill_detection_s is not None else None),
            "resolve_s": (round(kill_resolve_s, 3)
                          if kill_resolve_s is not None else None),
            "alert": kill_alert,
        },
        "wedge_drill": {
            "detection_s": (round(wedge_detection_s, 3)
                            if wedge_detection_s is not None else None),
            "resolve_s": (round(wedge_resolve_s, 3)
                          if wedge_resolve_s is not None else None),
        },
        "alerts_file_transitions": len(alerts_file),
        "engine": engine_state,
        "steady_state_recompiles": dict(sorted(recompiles.items())),
        "steady_state_recompiles_max": (max(recompiles.values())
                                        if recompiles else None),
        "replica_summaries": [
            {"outcome": (s or {}).get("outcome"),
             "restarts": (s or {}).get("restarts")} for s in summaries],
        "note": "supervised 2-replica SO_REUSEPORT fleet under the live "
                "blackbox prober (fixture /v1/weights on the raw-f32 "
                "wire + per-replica admin /healthz + /metrics from "
                "fleet.json) and the burn-rate SLOEngine (drill spec: "
                "probe-success availability, one "
                "long/short window pair). Drill 1 SIGKILLs replica0 "
                "(dead: refused connections); drill 2 SIGSTOPs replica1 "
                "(wedged-but-accepting: kernel backlog accepts, nothing "
                "answers — invisible to whitebox metrics, between "
                "autoscaler polls). detection_s is seconds from the "
                "signal to the FIRING availability alert; both drills "
                "then RESOLVE (supervised restart / SIGCONT). "
                "probe_overhead interleaves closed-loop c8 throughput "
                "prober-off vs prober-on at the production probe cadence "
                "(overhead_probe_interval_s), best of 3 each; the drills "
                "run the prober at the hotter drill cadence "
                "(probe_interval_s) the seconds-scale windows need. "
                "steady_state_recompiles is per replica INCARNATION "
                "(warmup compiles budgeted per run_id).",
    }


# -- tracing-overhead benchmark (bench.py --tracing, BENCH_TRACING.json) -----


def bench_tracing_overhead(
    n_stocks: int = 500,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 4,
    months: int = 60,
    n_requests: int = 320,
    concurrency: int = 8,
    trials: int = 3,
    seed: int = 42,
) -> Dict[str, Any]:
    """Closed-loop throughput with request tracing fully ON
    (``DLAP_TRACE_SAMPLE=1``: every request emits its segment-timed
    ``request`` row) vs fully OFF (``=0``: only the aggregate span_end
    twin) against ONE in-process async server — no fleet, no supervisor,
    so the measured delta is the tracing hot-path cost alone. Trials
    interleave on/off (best-of-N each) to ride out CPU-quota bursts.
    budgets.json gates ``rps_ratio_on_off >= 0.95`` — tracing may cost at
    most 5% of closed-loop throughput."""
    import os
    import tempfile
    from pathlib import Path

    from ..observability.tracecontext import ENV_SAMPLE
    from ..utils.config import GANConfig
    from .aserver import AsyncServerThread
    from .engine import InferenceEngine, bucket_for
    from .server import BINARY_CONTENT_TYPE, ServingService

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    macro = rng.standard_normal((months, n_macro)).astype(np.float32)
    with tempfile.TemporaryDirectory(prefix="dlap_tracing_bench_") as td:
        td = Path(td)
        dirs = _make_member_dirs(td / "ckpts", cfg, range(1, n_members + 1))
        stock_bucket = bucket_for(n_stocks, [64 * 2**i for i in range(9)])
        engine = InferenceEngine(
            dirs, macro_history=macro, stock_buckets=(stock_bucket,),
            batch_buckets=(1, 2, 4, 8))
        service = ServingService(engine, run_dir=str(td / "serve_run"),
                                 mode="async", cache_size=0)
        service.warmup()
        server = AsyncServerThread(service)
        port = server.start()
        url = f"http://127.0.0.1:{port}/v1/weights"
        bodies = []
        for i in range(64):
            r = np.random.default_rng(seed + 1 + i)
            bodies.append(binary_payload_bytes(
                r.standard_normal(
                    (n_stocks, n_features)).astype(np.float32),
                i % months))

        def run_once():
            return run_loadgen(
                url, lambda i: bodies[i % len(bodies)], mode="closed",
                concurrency=concurrency, n_requests=n_requests,
                warmup_requests=8, content_type=BINARY_CONTENT_TYPE)

        prev = os.environ.get(ENV_SAMPLE)
        runs: Dict[str, List[Dict[str, Any]]] = {"off": [], "on": []}
        try:
            run_once()  # warm every batch-bucket shape off the clock
            for _ in range(max(1, trials)):
                for mode, sample in (("off", "0"), ("on", "1")):
                    os.environ[ENV_SAMPLE] = sample
                    runs[mode].append(run_once())
        finally:
            if prev is None:
                os.environ.pop(ENV_SAMPLE, None)
            else:
                os.environ[ENV_SAMPLE] = prev
            server.stop()
            service.close()

    def best(mode):
        return max(runs[mode], key=lambda r: r["throughput_rps"] or 0)

    b_off, b_on = best("off"), best("on")
    ratio = (b_on["throughput_rps"] / b_off["throughput_rps"]
             if b_off["throughput_rps"] else None)
    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months}",
        "concurrency": concurrency,
        "n_requests": n_requests,
        "trials": trials,
        "rps_tracing_off": b_off["throughput_rps"],
        "rps_tracing_on": b_on["throughput_rps"],
        "rps_ratio_on_off": round(ratio, 4) if ratio is not None else None,
        "p99_ms_tracing_off": (b_off["latency"] or {}).get("p99_ms"),
        "p99_ms_tracing_on": (b_on["latency"] or {}).get("p99_ms"),
        "all_trials": {
            mode: [{"throughput_rps": r["throughput_rps"],
                    "p99_ms": (r["latency"] or {}).get("p99_ms")}
                   for r in rs]
            for mode, rs in runs.items()},
        "note": "one in-process async server, raw-f32 wire, cache off, "
                "closed loop, trials interleaved on/off and best-of-N "
                "each; DLAP_TRACE_SAMPLE=1 emits a full segment-timed "
                "request row per request, =0 only the aggregate span_end "
                "twin; the budget gate requires the ratio >= 0.95 "
                "(tracing overhead <= 5% of closed-loop rps)",
    }


# -- mesh-serving benchmark (bench.py --meshserve, BENCH_MESHSERVE.json) -----


def bench_meshserve(
    n_stocks: int = 10_240,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 3,
    months: int = 24,
    n_pairs: int = 24,
    mesh_spec: str = "stocks=8",
    tol: float = 1e-5,
    fleet_stocks: int = 512,
    fleet_rate_rps: float = 30.0,
    fleet_seconds: float = 10.0,
    seed: int = 42,
) -> Dict[str, Any]:
    """Multi-device serving acceptance benchmark (8 virtual CPU devices —
    the BENCH_MESH recipe; bench.py --meshserve sets the env before jax
    loads). Three legs:

      * identity — the mesh engine vs the single-device engine at the
        paper stock shape (N≈10k × 46 chars): a degenerate ``stocks=1``
        mesh must be BITWISE identical (placement-only change), and the
        ``stocks=8``-sharded engine must match within the stock-GSPMD
        tolerance contract documented since PR 13 (the masked cross-
        sectional sums become cross-device psums whose reduction order
        differs from the serial sum — the one surface where bitwise is
        physically off the table; measured ~4e-8, gated at ``tol``).
        ``bit_identical`` is that compound criterion, with
        ``sharded_max_abs_diff`` and ``degenerate_bitwise`` disclosed
        beside it. A mid-run hot-swap (reload of a rewritten member)
        re-checks identity on the swapped generation.
      * invariants — per-incarnation ``steady_state_recompiles == 0`` on
        both engines across the traffic, dispatch counters advancing,
        warmup compile counts equal (same bucket ladder, sharded or not).
      * fault matrix — a supervised 2-replica fleet, each replica's mesh
        on a DISJOINT 4-device slice (``--mesh stocks=-1 --mesh_slices
        2``), under open-loop load with retries; replica0 is SIGKILLed
        mid-load and supervised-restarted. ``dropped_requests == 0``.

    Honest disclosure: on a few-core CPU runner the 8 virtual devices
    share the same cores, so cross-device compute parallelism is
    INVISIBLE — wall-clock is gated on paired medians staying within
    noise of parity (1-core-runner policy), never on absolute speedup;
    the sharding win is structural (per-device panel spans + psums) and
    shows up only on real multi-chip hosts.
    """
    import os as _os
    import signal as _signal
    import tempfile
    from pathlib import Path

    from ..utils.config import GANConfig
    from .aserver import pick_free_port
    from .engine import InferenceEngine, InferenceRequest
    from .fleet import ReplicaFleet, server_child_argv
    from .server import BINARY_CONTENT_TYPE, build_arg_parser

    import jax

    n_devices = len(jax.devices())
    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    macro = rng.standard_normal((months, n_macro)).astype(np.float32)

    def _requests(n, stocks, offset=0):
        out = []
        for i in range(n):
            r = np.random.default_rng(seed + 1 + offset + i)
            out.append(InferenceRequest(
                individual=r.standard_normal(
                    (stocks, n_features)).astype(np.float32),
                mask=(r.random(stocks) > 0.1).astype(np.float32),
                returns=(r.standard_normal(stocks) * 0.05).astype(
                    np.float32),
                month=int(i % months)))
        return out

    def _identity(a, b):
        """(bitwise, max_abs_diff) over a pair of results."""
        d = 0.0
        if a.weights.size:
            d = float(np.max(np.abs(np.asarray(a.weights)
                                    - np.asarray(b.weights))))
        if a.sdf is not None and b.sdf is not None:
            d = max(d, abs(float(a.sdf) - float(b.sdf)))
        bit = (np.array_equal(np.asarray(a.weights), np.asarray(b.weights))
               and a.sdf == b.sdf)
        return bit, d

    with tempfile.TemporaryDirectory(prefix="dlap_meshserve_") as td:
        td = Path(td)
        dirs = _make_member_dirs(td / "v1", cfg, range(1, n_members + 1))

        t0 = time.monotonic()
        single = InferenceEngine(dirs, macro_history=macro,
                                 stock_buckets=(n_stocks,),
                                 batch_buckets=(1,))
        single_load_s = time.monotonic() - t0
        t0 = time.monotonic()
        sharded = InferenceEngine(dirs, macro_history=macro,
                                  stock_buckets=(n_stocks,),
                                  batch_buckets=(1,), mesh=mesh_spec)
        sharded_load_s = time.monotonic() - t0
        degenerate = InferenceEngine(dirs, macro_history=macro,
                                     stock_buckets=(n_stocks,),
                                     batch_buckets=(1,), mesh="stocks=1")

        t0 = time.monotonic()
        warmed_single = single.warmup()
        single_warmup_s = time.monotonic() - t0
        t0 = time.monotonic()
        warmed_sharded = sharded.warmup()
        sharded_warmup_s = time.monotonic() - t0
        degenerate.warmup()

        # paired A/B at the paper shape: same request through both
        # engines, order alternated per pair to de-bias cache/scheduler
        # drift; identity + per-pair walls accumulated together
        reqs = _requests(n_pairs, n_stocks)
        pair_single_s: List[float] = []
        pair_sharded_s: List[float] = []
        bitwise_all = True
        degenerate_bitwise = True
        max_diff = 0.0
        for i, req in enumerate(reqs):
            order = ((single, pair_single_s), (sharded, pair_sharded_s))
            if i % 2:
                order = order[::-1]
            results = {}
            for eng, walls in order:
                t0 = time.monotonic()
                results[id(eng)] = eng.infer_one(req)
                walls.append(time.monotonic() - t0)
            bit, d = _identity(results[id(single)], results[id(sharded)])
            bitwise_all = bitwise_all and bit
            max_diff = max(max_diff, d)
            dbit, _ = _identity(results[id(single)],
                                degenerate.infer_one(req))
            degenerate_bitwise = degenerate_bitwise and dbit

        # hot-swap drill: member 0 rewritten on disk, sharded engine
        # hot-reloads (re-stack + macro re-derivation, NO recompile), and
        # the swapped generation must hold the same identity contract
        # against a fresh single-device engine of the new params
        _make_member_dirs(td / "v1", cfg, (101,))
        swap_src = td / "v1" / "seed_101"
        member0 = Path(dirs[0])
        for f in ("config.json", "best_model_sharpe.msgpack",
                  "best_model_sharpe.msgpack.sha256"):
            (member0 / f).write_bytes((swap_src / f).read_bytes())
        t0 = time.monotonic()
        reload_out = sharded.reload()
        reload_s = time.monotonic() - t0
        single2 = InferenceEngine(dirs, macro_history=macro,
                                  stock_buckets=(n_stocks,),
                                  batch_buckets=(1,))
        single2.warmup()
        swap_bitwise = True
        swap_max_diff = 0.0
        for req in _requests(4, n_stocks, offset=10**6):
            bit, d = _identity(single2.infer_one(req),
                               sharded.infer_one(req))
            swap_bitwise = swap_bitwise and bit
            swap_max_diff = max(swap_max_diff, d)

        stats_single = single.stats()
        stats_sharded = sharded.stats()

        # -- fault matrix: 2-replica fleet on disjoint device slices ----
        np.save(td / "macro.npy", macro)
        run_dir = td / "fleet_run"
        args = build_arg_parser().parse_args([
            "--checkpoint_dirs", *dirs,
            "--macro_npy", str(td / "macro.npy"),
            "--stock_buckets", str(fleet_stocks),
            "--batch_buckets", "1,2,4",
            "--mesh", "stocks=-1", "--mesh_slices", "2",
            "--max_queue", "512",
            "--cache_size", "0",
            "--run_dir", str(run_dir),
        ])
        port = pick_free_port()
        admin_ports: List[int] = []
        for _ in range(2):
            ap = pick_free_port()
            while ap in admin_ports or ap == port:
                ap = pick_free_port()
            admin_ports.append(ap)
        argvs = [server_child_argv(args, i, run_dir / f"replica{i}", port,
                                   admin_port=admin_ports[i])
                 for i in range(2)]
        fleet = ReplicaFleet(argvs, run_dir)
        url = f"http://127.0.0.1:{port}/v1/weights"
        bodies = []
        for i in range(64):
            r = np.random.default_rng(seed + 1 + i)
            bodies.append(binary_payload_bytes(
                r.standard_normal(
                    (fleet_stocks, n_features)).astype(np.float32),
                i % months))
        n_requests = int(fleet_rate_rps * fleet_seconds)
        load_out: Dict[str, Any] = {}

        def _drive():
            load_out.update(run_loadgen(
                url, lambda i: bodies[i % len(bodies)], mode="open",
                rate_rps=fleet_rate_rps, n_requests=n_requests,
                warmup_requests=0, retries=2, timeout_s=30.0,
                open_workers=8, content_type=BINARY_CONTENT_TYPE))

        try:
            t0 = time.monotonic()
            fleet.start()
            fleet.wait_ready(timeout=600.0)
            startup_s = time.monotonic() - t0
            # warm every batch-bucket shape before the measured window
            run_loadgen(url, lambda i: bodies[i % len(bodies)],
                        mode="closed", concurrency=8, n_requests=64,
                        warmup_requests=4,
                        content_type=BINARY_CONTENT_TYPE)
            loader = threading.Thread(target=_drive, name="meshserve-load")
            loader.start()
            time.sleep(min(2.0, fleet_seconds / 4))
            pid0 = fleet.replica_pid(0)
            assert pid0 is not None
            _os.kill(pid0, _signal.SIGKILL)
            loader.join()
            # replica0's supervised restart may still be compiling its
            # warmup; wait for the NEW incarnation to accept before the
            # per-replica scrape (the gate reads its post-restart counters)
            fleet.wait_ready(timeout=600.0)
            per_replica: Dict[str, Any] = {}
            for ap in admin_ports:
                deadline = time.monotonic() + 120.0
                while True:
                    try:
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{ap}/metrics",
                                timeout=10) as r:
                            m = json.loads(r.read())
                        break
                    except OSError:
                        if time.monotonic() >= deadline:
                            raise
                        time.sleep(0.5)
                per_replica[str(m.get("replica"))] = m
        finally:
            summaries = fleet.stop()

    med_single = float(np.median(pair_single_s)) if pair_single_s else None
    med_sharded = (float(np.median(pair_sharded_s))
                   if pair_sharded_s else None)
    paired_ratio = (round(med_single / med_sharded, 4)
                    if med_single and med_sharded else None)
    bit_identical = int(degenerate_bitwise and max_diff <= tol
                        and swap_max_diff <= tol)
    recompiles = {
        "single": stats_single["steady_state_recompiles"],
        "sharded": stats_sharded["steady_state_recompiles"],
        **{str(r): m["engine"]["steady_state_recompiles"]
           for r, m in sorted(per_replica.items())},
    }
    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months}",
        "devices": n_devices,
        "mesh": mesh_spec,
        "sharded_mesh": stats_sharded["mesh"],
        "stock_shards": stats_sharded["stock_shards"],
        "n_pairs": n_pairs,
        "engine_load_s": {"single": round(single_load_s, 3),
                          "sharded": round(sharded_load_s, 3)},
        "warmup_compile_s": {"single": round(single_warmup_s, 3),
                             "sharded": round(sharded_warmup_s, 3)},
        "warmed_programs": {"single": warmed_single,
                            "sharded": warmed_sharded},
        "median_infer_ms": {
            "single": (round(med_single * 1e3, 3)
                       if med_single is not None else None),
            "sharded": (round(med_sharded * 1e3, 3)
                        if med_sharded is not None else None)},
        "paired_median_ratio_single_over_sharded": paired_ratio,
        "bit_identical": bit_identical,
        "bitwise_equal_sharded": int(bitwise_all),
        "degenerate_bitwise": int(degenerate_bitwise),
        "sharded_max_abs_diff": max_diff,
        "tolerance": tol,
        "hot_swap": {
            "swapped": reload_out.get("swapped"),
            "reload_s": round(reload_s, 3),
            "max_abs_diff": swap_max_diff,
            "bitwise_equal": int(swap_bitwise)},
        "dispatches": {"single": stats_single["dispatches"],
                       "sharded": stats_sharded["dispatches"]},
        "compiles": {"single": stats_single["compiles"],
                     "sharded": stats_sharded["compiles"]},
        "steady_state_recompiles": recompiles,
        "steady_state_recompiles_max": max(recompiles.values()),
        "fault_matrix": {
            "replicas": 2,
            "mesh": "stocks=-1 over 2 disjoint slices",
            "fleet_stocks": fleet_stocks,
            "rate_rps": fleet_rate_rps,
            "fleet_startup_s": round(startup_s, 3),
            "n_requests": load_out.get("n_requests"),
            "n_ok": load_out.get("n_ok"),
            "dropped_requests": (int(load_out["n_requests"])
                                 - int(load_out["n_ok"])),
            "n_retried": load_out.get("n_retried"),
            "errors": load_out.get("errors"),
            "latency": load_out.get("latency"),
            "replica_meshes": {
                r: m["engine"]["mesh"]
                for r, m in sorted(per_replica.items())},
            "replica_restarts": [
                (s or {}).get("restarts", 0) for s in summaries],
        },
        "note": "8 virtual CPU devices (xla_force_host_platform_device_"
                "count) share the runner's cores, so cross-device compute "
                "parallelism is invisible here — the gate is invariants + "
                "paired medians (1-core-runner policy), NOT absolute "
                "speedup. bit_identical = degenerate stocks=1 mesh "
                "bitwise-equal AND stocks=8 within the stock-GSPMD "
                "reduction-order tolerance (PR-13 contract), across the "
                "hot-swap. Fault matrix: replica0 SIGKILLed mid-load, "
                "supervised restart, retries route to the surviving "
                "disjoint-slice replica — dropped_requests must be 0.",
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Serving load generator / loopback benchmark")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bench",
                       help="self-contained loopback benchmark "
                            "(DEPRECATED threaded server baseline)")
    b.add_argument("--n_stocks", type=int, default=500)
    b.add_argument("--n_members", type=int, default=4)
    b.add_argument("--n_requests", type=int, default=200)
    a = sub.add_parser("bench_async",
                       help="replicated async-fleet loopback benchmark")
    a.add_argument("--n_stocks", type=int, default=500)
    a.add_argument("--n_members", type=int, default=4)
    a.add_argument("--n_requests", type=int, default=320)
    a.add_argument("--replicas", type=int, default=2)
    la = sub.add_parser("bench_loadadapt",
                        help="load-adaptive fleet: autoscaler + priority "
                             "shedding + coalescing under a 10x rate swing")
    la.add_argument("--n_stocks", type=int, default=500)
    la.add_argument("--n_members", type=int, default=2)
    la.add_argument("--max_replicas", type=int, default=2)
    r = sub.add_parser("bench_rolling_reload",
                       help="promotion control plane: open-loop load "
                            "across a health-gated rolling hot-swap")
    r.add_argument("--n_stocks", type=int, default=500)
    r.add_argument("--n_members", type=int, default=2)
    r.add_argument("--replicas", type=int, default=2)
    r.add_argument("--rate_rps", type=float, default=40.0)
    r.add_argument("--load_seconds", type=float, default=12.0)
    d = sub.add_parser("drive", help="drive an already-running server")
    d.add_argument("--url", type=str, required=True)
    d.add_argument("--payload_json", type=str, required=True,
                   help="path to one JSON request payload")
    d.add_argument("--mode", type=str, default="closed",
                   choices=("closed", "open"))
    d.add_argument("--concurrency", type=int, default=4)
    d.add_argument("--rate_rps", type=float, default=None)
    d.add_argument("--rate_ladder", type=str, default=None,
                   help="comma-separated open-loop rate ladder (rps); "
                        "overrides --rate_rps/--mode")
    d.add_argument("--n_requests", type=int, default=200)
    d.add_argument("--retries", type=int, default=0)
    args = p.parse_args(argv)

    if args.cmd == "bench":
        from ..utils.platform import apply_env_platforms

        apply_env_platforms()
        out = bench_serving(n_stocks=args.n_stocks,
                            n_members=args.n_members,
                            n_requests=args.n_requests)
    elif args.cmd == "bench_async":
        # the fleet parent stays backend-free; replicas apply their own env
        out = bench_serving_async(n_stocks=args.n_stocks,
                                  n_members=args.n_members,
                                  n_requests=args.n_requests,
                                  replicas=args.replicas)
    elif args.cmd == "bench_loadadapt":
        from ..utils.platform import apply_env_platforms

        # member checkpoints are written in THIS process (jax init only;
        # serving happens in the replica children)
        apply_env_platforms()
        out = bench_loadadapt(n_stocks=args.n_stocks,
                              n_members=args.n_members,
                              max_replicas=args.max_replicas)
    elif args.cmd == "bench_rolling_reload":
        from ..utils.platform import apply_env_platforms

        # promote() stacks the candidates in THIS process (jax)
        apply_env_platforms()
        out = bench_rolling_reload(n_stocks=args.n_stocks,
                                   n_members=args.n_members,
                                   replicas=args.replicas,
                                   rate_rps=args.rate_rps,
                                   load_seconds=args.load_seconds)
    else:
        payload = json.loads(open(args.payload_json).read())
        if args.rate_ladder:
            rates = [float(x) for x in args.rate_ladder.split(",")]
            out = run_ladder(args.url, payload, rates=rates,
                             retries=args.retries)
        else:
            out = run_loadgen(args.url, payload, mode=args.mode,
                              concurrency=args.concurrency,
                              rate_rps=args.rate_rps,
                              n_requests=args.n_requests,
                              retries=args.retries)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
