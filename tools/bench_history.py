"""Fold every checked-in BENCH_*.json / artifacts/*.json headline metric
into an append-only ``benches/history.jsonl`` keyed by git sha.

The repo's perf trajectory currently lives only in git history — reading
it means checking out each commit and diffing JSON by hand. This tool
makes it a first-class artifact: run it (ideally right after a bench
lands), and each artifact's headline numbers append as one history line::

    {"file": "BENCH_SERVING.json", "sha": "<git sha>",
     "commit_time": "<ISO-8601 of HEAD>", "digest": "<sha256 of bytes>",
     "metrics": {"...": 1.23, ...}}

Idempotent by construction: a (file, digest) pair already present is
skipped, so re-running on an unchanged tree appends nothing — the history
only grows when an artifact's bytes actually change. ``report
--bench-trend`` renders the per-metric trajectory across the file.

Headline extraction is shape-generic: numeric scalars at depth <= 2
(``a`` and ``a.b``), skipping lists and obviously non-headline keys —
robust to every BENCH_* schema in the repo without a per-file table.

Stdlib-only; runnable as ``python tools/bench_history.py``.
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import subprocess
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

REPO = Path(__file__).resolve().parents[1]
DEFAULT_OUT = REPO / "benches" / "history.jsonl"

# keys that are bookkeeping, not performance headlines
_SKIP_KEYS = frozenset({
    "schema", "seed", "ts", "timestamp", "pid", "attempt", "attempts",
})


def headline_metrics(doc: Any, max_depth: int = 2,
                     max_metrics: int = 64) -> Dict[str, float]:
    """Numeric scalars at depth <= ``max_depth``, dotted-path keyed,
    deterministically ordered and bounded."""
    out: Dict[str, float] = {}

    def walk(node: Any, prefix: str, depth: int) -> None:
        if not isinstance(node, dict) or depth > max_depth:
            return
        for key in sorted(node):
            if key in _SKIP_KEYS or key.startswith("_"):
                continue
            value = node[key]
            path = f"{prefix}.{key}" if prefix else str(key)
            if isinstance(value, bool):
                out[path] = float(value)
            elif isinstance(value, (int, float)):
                out[path] = float(value)
            elif isinstance(value, dict):
                walk(value, path, depth + 1)

    walk(doc, "", 1)
    if len(out) > max_metrics:
        out = dict(sorted(out.items())[:max_metrics])
    return out


def _git(args: List[str], repo: Path) -> Optional[str]:
    """Run git IN the repo whose artifacts are being recorded — a
    ``--repo`` pointing at another checkout must key its history lines
    by THAT checkout's HEAD, not this tool's."""
    try:
        r = subprocess.run(["git", *args], capture_output=True, text=True,
                           cwd=repo, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if r.returncode != 0:
        return None
    return r.stdout.strip() or None


def artifact_paths(repo: Path) -> List[Path]:
    """Every bench-shaped artifact, deterministically ordered."""
    paths = sorted(glob.glob(str(repo / "BENCH_*.json")))
    paths += sorted(glob.glob(str(repo / "artifacts" / "*.json")))
    return [Path(p) for p in paths]


def read_history(path) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    try:
        text = Path(path).read_text()
    except OSError:
        return rows
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail from a killed writer
        if isinstance(row, dict):
            rows.append(row)
    return rows


def update_history(repo=REPO, out_path=None) -> List[Dict[str, Any]]:
    """Append one history line per CHANGED artifact (new (file, digest)
    pair); returns the appended entries. Existing lines are never
    rewritten — the file is the trajectory."""
    repo = Path(repo)
    out_path = Path(out_path) if out_path else repo / "benches" / \
        "history.jsonl"
    out_path.parent.mkdir(parents=True, exist_ok=True)
    existing = read_history(out_path)
    seen = {(r.get("file"), r.get("digest")) for r in existing}
    sha = _git(["rev-parse", "HEAD"], repo) or "unknown"
    commit_time = _git(["show", "-s", "--format=%cI", "HEAD"],
                       repo) or "unknown"
    appended: List[Dict[str, Any]] = []
    for path in artifact_paths(repo):
        try:
            data = path.read_bytes()
            doc = json.loads(data)
        except (OSError, json.JSONDecodeError):
            continue  # a torn artifact is not history
        rel = str(path.relative_to(repo))
        digest = hashlib.sha256(data).hexdigest()
        if (rel, digest) in seen:
            continue
        metrics = headline_metrics(doc)
        if not metrics:
            continue
        entry = {"file": rel, "sha": sha, "commit_time": commit_time,
                 "digest": digest, "metrics": metrics}
        appended.append(entry)
        seen.add((rel, digest))
    if appended:
        with open(out_path, "a") as f:
            for entry in appended:
                f.write(json.dumps(entry, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
    return appended


def format_trend(rows: List[Dict[str, Any]],
                 files: Optional[List[str]] = None) -> str:
    """The per-metric trajectory across history entries, in append order
    (the file IS the timeline): one line per (artifact, metric) that has
    ever been recorded, oldest → newest."""
    if not rows:
        return "bench trend: (no history — run tools/bench_history.py)"
    series: Dict[tuple, List[tuple]] = {}
    order: Dict[str, int] = {}
    for i, row in enumerate(rows):
        fname = str(row.get("file"))
        if files and fname not in files:
            continue
        order.setdefault(fname, i)
        sha = str(row.get("sha") or "unknown")[:7]
        for metric, value in (row.get("metrics") or {}).items():
            series.setdefault((fname, metric), []).append((sha, value))
    lines = [f"bench trend ({len(rows)} history entries):"]
    for fname in sorted(order, key=lambda f: (order[f], f)):
        lines.append(f"  {fname}:")
        for (f, metric), points in sorted(series.items()):
            if f != fname:
                continue
            traj = " -> ".join(
                f"{v:g}@{sha}" if len(points) > 1 else f"{v:g}"
                for sha, v in points)
            lines.append(f"    {metric}: {traj}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Append changed BENCH_*/artifacts headline metrics "
                    "to benches/history.jsonl (idempotent)")
    ap.add_argument("--repo", type=str, default=str(REPO))
    ap.add_argument("--out", type=str, default=None,
                    help="history file (default: REPO/benches/"
                         "history.jsonl)")
    ap.add_argument("--show", action="store_true",
                    help="render the trajectory instead of appending")
    args = ap.parse_args(argv)
    repo = Path(args.repo)
    out = Path(args.out) if args.out else repo / "benches" / \
        "history.jsonl"
    if args.show:
        try:
            print(format_trend(read_history(out)))
        except BrokenPipeError:
            pass  # `... --show | head` closing the pipe is not an error
        return 0
    appended = update_history(repo, out)
    print(f"bench history: {len(appended)} new entries "
          f"({len(read_history(out))} total) in {out}")
    for e in appended:
        print(f"  + {e['file']} ({len(e['metrics'])} metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
