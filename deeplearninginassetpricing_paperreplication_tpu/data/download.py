"""Real-data acquisition: the authors' 1.2 GB .npz panel from Google Drive.

Counterpart of the reference's ``src/download_data.py`` (pointers and
expected sizes from ``/root/reference/src/download_data.py:31-45``). The
`gdown` dependency is hard-gated: everything except the actual network pull
(existence checks, size validation, restructuring) works without it, and the
synthetic generator (``data/synthetic.py``) is the offline substitute.

Layout produced:
    data_dir/char/Char_{train,valid,test}.npz
    data_dir/macro/macro_{train,valid,test}.npz
"""

from __future__ import annotations

import argparse
import shutil
import zipfile
from pathlib import Path
from typing import Dict, List, Tuple, Union

# Authors' Google Drive (Chen-Pelger-Zhu replication data)
DATASETS_ZIP_ID = "1h9O7YwPLaRBbghtF50Cr-JmIq0aHHi4Y"
GDRIVE_FOLDER_ID = "1TrYzMUA_xLID5-gXOy_as8sH2ahLwz-l"

EXPECTED_SIZES_BYTES: Dict[str, int] = {
    "Char_train.npz": 317 * 1024 * 1024,
    "Char_valid.npz": 72 * 1024 * 1024,
    "Char_test.npz": 768 * 1024 * 1024,
    "macro_train.npz": 351 * 1024,
    "macro_valid.npz": 96 * 1024,
    "macro_test.npz": 436 * 1024,
}

REQUIRED_FILES: List[Tuple[str, str]] = [
    ("char", "Char_train.npz"),
    ("char", "Char_valid.npz"),
    ("char", "Char_test.npz"),
    ("macro", "macro_train.npz"),
    ("macro", "macro_valid.npz"),
    ("macro", "macro_test.npz"),
]


def check_data_exists(data_dir: Union[str, Path], verbose: bool = True) -> bool:
    """True iff all six .npz files are present (download_data.py:48-76)."""
    data_dir = Path(data_dir)
    missing = [
        sub + "/" + name
        for sub, name in REQUIRED_FILES
        if not (data_dir / sub / name).exists()
    ]
    if verbose:
        if missing:
            print(f"Missing {len(missing)}/6 data files under {data_dir}:")
            for m in missing:
                print(f"  - {m}")
        else:
            print(f"All 6 data files present under {data_dir}")
    return not missing


def validate_sizes(data_dir: Union[str, Path], tolerance: float = 0.5) -> Dict[str, bool]:
    """Compare on-disk sizes against the expected table (±tolerance)."""
    data_dir = Path(data_dir)
    out = {}
    for sub, name in REQUIRED_FILES:
        p = data_dir / sub / name
        if not p.exists():
            out[name] = False
            continue
        expected = EXPECTED_SIZES_BYTES[name]
        out[name] = abs(p.stat().st_size - expected) <= tolerance * expected
    return out


def validate_schema(data_dir: Union[str, Path], verbose: bool = True):
    """Deep-validate whatever landed in `data_dir` against the npz schema the
    loader assumes (shapes, dtypes, date format, sentinel convention) — a
    loud pass/fail BEFORE a user points training at real downloaded bytes.

    The Drive download path in this repo has never been exercised against
    the live 1.2 GB artifacts (no network egress in the build environment;
    the schema is taken from ``/root/reference/src/download_data.py:347-375``
    and the reference loader's conventions) — which is exactly why a user
    with the real files gets this validator instead of a trust-me.

    Checks per char file: `data` [T, N, 1+F] float with returns in slice 0,
    no NaN/Inf (missing entries must use the -99.99 sentinel, not NaN),
    `date` [T] monotonically increasing YYYYMM ints, `variable` [1+F].
    Per macro file: `data` [T, M] float, finite, `date` [T] matching the
    char split's dates. Cross-split: F and N consistent, M consistent.

    Returns (ok, report) where report maps filename → dict with `shape` and
    an `errors` list (empty = pass).
    """
    import numpy as np

    data_dir = Path(data_dir)
    report: Dict[str, Dict] = {}
    char_meta: Dict[str, Dict] = {}
    macro_meta: Dict[str, Dict] = {}

    def _check_dates(date, T, errors):
        if date.shape != (T,):
            errors.append(f"date shape {date.shape} != ({T},)")
            return
        d = date.astype(np.int64)
        months = d % 100
        if not ((d >= 190001) & (d <= 210012) & (months >= 1)
                & (months <= 12)).all():
            errors.append("date entries are not YYYYMM ints in [190001, 210012]")
        if T > 1 and not (np.diff(d) > 0).all():
            errors.append("dates are not strictly increasing")

    def _check_file(sub, name, data, date, variable, info, errors):
        info["shape"] = tuple(data.shape)
        if not np.issubdtype(data.dtype, np.floating):
            errors.append(f"data dtype {data.dtype} is not floating")
            return
        if sub == "char":
            if data.ndim != 3 or data.shape[2] < 2:
                errors.append(
                    f"char data must be [T, N, 1+F] with F>=1, got {data.shape}")
                return
            T, N, one_plus_f = data.shape
            if not np.isfinite(data).all():
                errors.append(
                    "char data contains NaN/Inf — missing entries must use "
                    "the -99.99 sentinel the loader masks on")
            info["missing_frac"] = float(
                np.isclose(data[..., 1:], -99.99, atol=1e-4).mean())
            if variable is not None and variable.shape[0] != one_plus_f:
                errors.append(
                    f"variable has {variable.shape[0]} names for "
                    f"{one_plus_f} data channels")
            _check_dates(date, T, errors)
            char_meta[name.split("_")[1].split(".")[0]] = {
                "T": T, "N": N, "F": one_plus_f - 1, "date": date,
            }
        else:
            if data.ndim != 2:
                errors.append(f"macro data must be [T, M], got {data.shape}")
                return
            T, M = data.shape
            if not np.isfinite(data).all():
                errors.append("macro data contains NaN/Inf")
            _check_dates(date, T, errors)
            macro_meta[name.split("_")[1].split(".")[0]] = {
                "T": T, "M": M, "date": date,
            }

    for sub, name in REQUIRED_FILES:
        p = data_dir / sub / name
        errors: List[str] = []
        info: Dict = {"errors": errors}
        report[name] = info
        if not p.exists():
            errors.append("missing")
            continue
        try:
            with np.load(p, allow_pickle=False) as z:
                files = set(z.files)
                need = {"data", "date"}
                if missing := need - files:
                    errors.append(f"missing npz keys: {sorted(missing)}")
                    continue
                data = z["data"]
                date = z["date"]
                variable = z["variable"] if "variable" in files else None
        except (OSError, ValueError, zipfile.BadZipFile) as e:
            errors.append(f"unreadable npz: {e}")
            continue
        try:
            _check_file(sub, name, data, date, variable, info, errors)
        except Exception as e:  # noqa: BLE001 — the validator exists for
            # never-before-seen real bytes; ANY surprise (string dates,
            # object arrays, ...) must become a loud per-file error, not an
            # uncaught traceback that kills the report
            errors.append(f"validation error: {e!r}")

    cross: List[str] = []
    if len({m["F"] for m in char_meta.values()}) > 1:
        cross.append(f"inconsistent F across splits: "
                     f"{ {k: v['F'] for k, v in char_meta.items()} }")
    if len({m["N"] for m in char_meta.values()}) > 1:
        cross.append(f"inconsistent N across splits: "
                     f"{ {k: v['N'] for k, v in char_meta.items()} }")
    if len({m["M"] for m in macro_meta.values()}) > 1:
        cross.append(f"inconsistent M across splits: "
                     f"{ {k: v['M'] for k, v in macro_meta.items()} }")
    for split, cm in char_meta.items():
        mm = macro_meta.get(split)
        if mm is None:
            continue
        if cm["T"] != mm["T"]:
            cross.append(f"{split}: char T={cm['T']} != macro T={mm['T']}")
        elif not np.array_equal(cm["date"], mm["date"]):
            cross.append(f"{split}: char and macro dates disagree")
    report["cross_split"] = {"errors": cross}

    ok = all(not info["errors"] for info in report.values())
    if verbose:
        for name, info in report.items():
            status = "ok" if not info["errors"] else "FAIL"
            shape = info.get("shape")
            extra = f" shape={shape}" if shape else ""
            mf = info.get("missing_frac")
            if mf is not None:
                extra += f" missing={mf:.1%}"
            print(f"  [{status}] {name}{extra}")
            for e in info["errors"]:
                print(f"         - {e}")
        print(f"Schema validation: {'PASS' if ok else 'FAIL'}")
    return ok, report


def _require_gdown():
    try:
        import gdown  # noqa

        return gdown
    except ImportError as e:
        raise ImportError(
            "Downloading the real dataset requires `gdown` (not bundled in "
            "this environment). Install it, or use the offline synthetic "
            "generator instead:\n  python -m "
            "deeplearninginassetpricing_paperreplication_tpu.data.synthetic "
            "--output_dir ./data"
        ) from e


def restructure_zip(zip_path: Union[str, Path], data_dir: Union[str, Path]) -> None:
    """Unpack datasets.zip and arrange files into char/ and macro/
    (download_data.py:121-159)."""
    data_dir = Path(data_dir)
    (data_dir / "char").mkdir(parents=True, exist_ok=True)
    (data_dir / "macro").mkdir(parents=True, exist_ok=True)
    extract_dir = data_dir / "_extract"
    with zipfile.ZipFile(zip_path) as zf:
        zf.extractall(extract_dir)
    for npz in extract_dir.rglob("*.npz"):
        sub = "char" if npz.name.startswith("Char") else "macro"
        shutil.move(str(npz), str(data_dir / sub / npz.name))
    shutil.rmtree(extract_dir, ignore_errors=True)


def download_from_zip(data_dir: Union[str, Path], quiet: bool = False) -> bool:
    """Pull datasets.zip directly by file id (the fast path,
    download_data.py:79-118)."""
    gdown = _require_gdown()
    data_dir = Path(data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    zip_path = data_dir / "datasets.zip"
    url = f"https://drive.google.com/uc?id={DATASETS_ZIP_ID}"
    if not quiet:
        print(f"Downloading {url} → {zip_path} (~1.2 GB)")
    result = gdown.download(url, str(zip_path), quiet=quiet)
    # gdown returns None (without raising) on failure, e.g. Drive quota
    # exceeded — a common state for this public 1.2 GB file
    if result is None or not zip_path.exists() or not zipfile.is_zipfile(zip_path):
        zip_path.unlink(missing_ok=True)
        return False
    restructure_zip(zip_path, data_dir)
    zip_path.unlink(missing_ok=True)
    return True


def download_from_folder(data_dir: Union[str, Path], quiet: bool = False) -> bool:
    """Pull the whole Drive folder, then unpack any datasets.zip inside —
    the fallback when the direct file id hits quota
    (download_data.py:177-263)."""
    gdown = _require_gdown()
    data_dir = Path(data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    url = f"https://drive.google.com/drive/folders/{GDRIVE_FOLDER_ID}"
    if not quiet:
        print(f"Downloading Drive folder {url} → {data_dir} (may take a while)")
    try:
        gdown.download_folder(url=url, output=str(data_dir), quiet=quiet,
                              use_cookies=False)
    except Exception as e:  # gdown raises on folder listing failures
        if not quiet:
            print(f"Folder download failed: {e}")
        return False
    zip_path = data_dir / "datasets.zip"
    if zip_path.exists():
        restructure_zip(zip_path, data_dir)
        zip_path.unlink(missing_ok=True)
    # stray macOS metadata folder ships inside the authors' archive
    shutil.rmtree(data_dir / "__MACOSX", ignore_errors=True)
    return check_data_exists(data_dir, verbose=False)


def download_all_data(
    data_dir: Union[str, Path] = "./data",
    force: bool = False,
    quiet: bool = False,
    method: str = "zip",
) -> bool:
    """Fetch + restructure the real panel. `method` is 'zip' (direct file id,
    fast) or 'folder' (whole-folder crawl); on zip failure the folder method
    is tried automatically, mirroring the reference's two methods."""
    if method not in ("zip", "folder"):
        raise ValueError(f"method must be 'zip' or 'folder', got {method!r}")
    data_dir = Path(data_dir)
    if not force and check_data_exists(data_dir, verbose=False):
        if not quiet:
            print("Data already present; use force=True to re-download")
        return True

    ok = False
    if method == "zip":
        ok = download_from_zip(data_dir, quiet=quiet)
        if not ok and not quiet:
            print("zip method failed; falling back to folder method")
    if not ok:
        ok = download_from_folder(data_dir, quiet=quiet)
    if not ok:
        raise RuntimeError(
            "Download failed (Google Drive quota exceeded or network error). "
            "Retry later, download manually from "
            f"https://drive.google.com/drive/folders/{GDRIVE_FOLDER_ID}, or "
            "use the offline synthetic generator:\n  python -m "
            "deeplearninginassetpricing_paperreplication_tpu.data.synthetic"
        )
    ok = check_data_exists(data_dir, verbose=not quiet)
    if ok:
        bad = [k for k, v in validate_sizes(data_dir).items() if not v]
        if bad and not quiet:
            print(f"WARNING: unexpected file sizes: {bad}")
    return ok


def print_data_info() -> None:
    """Describe the expected dataset (facts per download_data.py:347-375:
    the Drive source, the six files and their sizes, and the npz schema —
    constants shared with the reference by necessity)."""
    print(f"""
Expected dataset: six .npz files, ~1.2 GB altogether, laid out as

  data/
  ├── char/    firm characteristics + returns, one file per split
  │     Char_train.npz (317 MB)   Char_valid.npz (72 MB)   Char_test.npz (768 MB)
  └── macro/   macroeconomic series, one file per split
        macro_train.npz (351 KB)  macro_valid.npz (96 KB)  macro_test.npz (436 KB)

Where it comes from:
  the authors' Google Drive folder
  https://drive.google.com/drive/folders/{GDRIVE_FOLDER_ID}
  (linked from https://mpelger.people.stanford.edu/data-and-code)

Schema inside each npz:
  char files : data [T, N, 1+F] (slice 0 = returns, 1: = characteristics,
               -99.99 marks missing), date [T] as YYYYMM, variable [1+F]
  macro files: data [T, M], date [T]

No network? Generate a schema-identical seeded panel instead:
  python -m deeplearninginassetpricing_paperreplication_tpu.data.synthetic
""")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Download the real asset-pricing panel",
        epilog="On Drive quota errors, retry later or use --method folder.",
    )
    p.add_argument("--data_dir", "--output_dir", "-o", dest="data_dir",
                   type=str, default="./data")
    p.add_argument("--check", action="store_true",
                   help="Check existence + validate the npz schema "
                        "(shapes/dtypes/dates/sentinel) of what's on disk")
    p.add_argument("--force", "-f", action="store_true")
    p.add_argument("--quiet", "-q", action="store_true")
    p.add_argument("--info", "-i", action="store_true",
                   help="Print data information and exit")
    p.add_argument("--method", "-m", choices=["zip", "folder"], default="zip",
                   help="'zip' = direct datasets.zip pull (fast); "
                        "'folder' = whole Drive folder crawl")
    args = p.parse_args(argv)
    if args.info:
        print_data_info()
        return
    if args.check:
        ok = check_data_exists(args.data_dir)
        if ok:
            for sub, name in REQUIRED_FILES:
                f = Path(args.data_dir) / sub / name
                print(f"  {f} ({f.stat().st_size / (1024 * 1024):.1f} MB)")
            ok, _ = validate_schema(args.data_dir)
        raise SystemExit(0 if ok else 1)
    ok = download_all_data(args.data_dir, force=args.force, quiet=args.quiet,
                           method=args.method)
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
