"""Crash flight recorder: the last moments of a replica, kept in memory,
dumped on trouble.

When a replica 503-bursts, is SIGTERMed, or is watchdog-killed mid-flight,
the post-hoc evidence (events.jsonl tail, metrics snapshot) says *that*
something died but not *what was in the air*. The :class:`FlightRecorder`
keeps two bounded rings — the last N completed request records (trace id,
status, segment timings, flush id) and the last K flushes — plus the set
of requests currently IN FLIGHT, and dumps all of it atomically to
``flightrecorder.json`` in the run dir when triggered:

  * **error burst** — ≥ ``burst_threshold`` 5xx or shed-429 responses
    inside ``burst_window_s`` (rate-limited to one dump per
    ``cooldown_s``) — an overload/admission-control storm counts as
    trouble, and the dump carries the autoscaler's last decisions
    (``record_decision`` ring) so it shows *why* the fleet was shedding;
  * **SIGTERM / clean shutdown** — the serving CLI's close path;
  * **watchdog kill** — the supervisor sends the pre-kill flare signal
    (SIGUSR1) before SIGKILL on a stale heartbeat
    (``RestartPolicy.prekill_signal``); the replica's handler dumps
    best-effort inside the grace window;
  * **on demand** — ``POST /v1/debug/flightrecorder`` on the PR-9 private
    admin port.

The dump is a tmp+``os.replace`` atomic write, so a reader (or a second
trigger racing the first) always sees a complete JSON document. Ring
mutation is O(1) per request with one small dict append — cheap enough to
run unconditionally on the hot path. Stdlib-only by contract: the
recorder must work inside a signal handler and in thin parents.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional

DEFAULT_REQUESTS = 256
DEFAULT_FLUSHES = 64
FILENAME = "flightrecorder.json"
# one-deep rotation: a NEW process incarnation moves its predecessor's
# last dump here before writing its own — a supervised restart's routine
# autosaves/shutdown dumps can never clobber the crash evidence
FILENAME_PREV = "flightrecorder.prev.json"

# background autosave cadence (seconds; 0 disables): a replica SIGKILLed
# with no chance to dump (real OOM kill) leaves a snapshot at most one
# interval stale on disk
ENV_AUTOSAVE = "DLAP_FLIGHT_AUTOSAVE_S"
DEFAULT_AUTOSAVE_S = 1.0


class FlightRecorder:
    """Bounded in-memory rings + atomic dump (see module doc)."""

    def __init__(
        self,
        run_dir=None,
        replica: Optional[str] = None,
        max_requests: int = DEFAULT_REQUESTS,
        max_flushes: int = DEFAULT_FLUSHES,
        burst_threshold: int = 8,
        burst_window_s: float = 5.0,
        cooldown_s: float = 30.0,
        events: Any = None,
    ):
        self.path = (Path(run_dir) / FILENAME) if run_dir else None
        if self.path is not None and self.path.exists():
            # rotate the previous incarnation's dump (see FILENAME_PREV):
            # the acceptance matrix reads a SIGKILLed replica's in-flight
            # evidence from here after the supervisor restarted it
            try:
                os.replace(self.path, self.path.with_name(FILENAME_PREV))
            except OSError:
                pass
        self.replica = replica
        self.events = events
        self._lock = threading.Lock()
        self._requests: deque = deque(maxlen=max_requests)
        self._flushes: deque = deque(maxlen=max_flushes)
        # token -> begin record of a request currently being served; a
        # replica killed mid-flight leaves these as the "what was in the
        # air" evidence the acceptance matrix reads back
        self._in_flight: Dict[int, Dict[str, Any]] = {}
        # the autoscaler's last decisions (signals + actions): an overload
        # crash dump then shows WHY the fleet was shedding, not just that
        # it was
        self._decisions: deque = deque(maxlen=64)
        # the SLO engine's last alert transitions: a crash dump carries
        # which budgets were burning when the process died
        self._alerts: deque = deque(maxlen=64)
        self._next_token = 0
        self.burst_threshold = int(burst_threshold)
        self.burst_window_s = float(burst_window_s)
        self.cooldown_s = float(cooldown_s)
        self._recent_errors: deque = deque(maxlen=max(self.burst_threshold,
                                                      1))
        self._last_burst_mono = -float("inf")
        self.dumps = 0
        # mutation sequence: the autosave thread only rewrites the file
        # when something actually changed since the last write
        self._seq = 0
        self._saved_seq = 0
        self._stop = threading.Event()
        self._autosave_thread: Optional[threading.Thread] = None

    # -- hot-path recording --------------------------------------------------

    def begin_request(self, trace_id: Optional[str], endpoint: str) -> int:
        """Mark a request in flight; returns the token for end_request."""
        rec = {"trace_id": trace_id, "endpoint": endpoint,
               "ts": round(time.time(), 6)}
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._in_flight[token] = rec
            self._seq += 1
        return token

    def end_request(self, token: int, record: Dict[str, Any]) -> None:
        """Retire an in-flight request into the completed ring; a 5xx or a
        shed 429 outcome also feeds the burst detector — an admission-
        control storm is exactly the moment the rings are evidence."""
        with self._lock:
            begin = self._in_flight.pop(token, None)
            if begin is not None and "ts" not in record:
                record = dict(record, ts=begin["ts"])
            self._requests.append(record)
            self._seq += 1
            status = record.get("status")
            if isinstance(status, int) and (status >= 500 or status == 429):
                self._recent_errors.append(time.monotonic())

    def note_alert(self) -> None:
        """Feed a non-HTTP alert (e.g. a model drift alert) into the SAME
        burst detector 5xx/429 responses arm: a storm of drift alerts
        triggers one rate-limited flight dump, exactly like an error
        burst."""
        with self._lock:
            self._recent_errors.append(time.monotonic())

    def record_flush(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._flushes.append(record)
            self._seq += 1

    def record_decision(self, record: Dict[str, Any]) -> None:
        """Append one autoscaler decision (signals + action) to the
        bounded ring the dump carries."""
        with self._lock:
            self._decisions.append(record)
            self._seq += 1

    def record_alert(self, record: Dict[str, Any]) -> None:
        """Append one SLO alert transition (firing/resolved) to the
        bounded ring the dump carries."""
        with self._lock:
            self._alerts.append(record)
            self._seq += 1

    def error_burst(self) -> bool:
        """True when the last ``burst_threshold`` 5xx responses all landed
        inside ``burst_window_s`` — arming the per-``cooldown_s`` rate
        limit as a side effect, so one burst produces one dump."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_burst_mono < self.cooldown_s:
                return False
            if len(self._recent_errors) < self.burst_threshold:
                return False
            if now - self._recent_errors[0] > self.burst_window_s:
                return False
            self._last_burst_mono = now
            return True

    # -- the dump ------------------------------------------------------------

    def snapshot(self, reason: str) -> Dict[str, Any]:
        with self._lock:
            return {
                "reason": reason,
                "replica": self.replica,
                "ts": round(time.time(), 6),
                "pid": os.getpid(),
                "n_requests": len(self._requests),
                "n_flushes": len(self._flushes),
                "in_flight": sorted(
                    self._in_flight.values(),
                    key=lambda r: (r.get("ts") or 0,
                                   str(r.get("trace_id")))),
                "in_flight_trace_ids": sorted(
                    str(r["trace_id"]) for r in self._in_flight.values()
                    if r.get("trace_id")),
                "requests": list(self._requests),
                "flushes": list(self._flushes),
                "autoscaler_decisions": list(self._decisions),
                "alerts": list(self._alerts),
            }

    def dump(self, reason: str) -> Optional[Path]:
        """Atomic write of the current snapshot; returns the path (None
        when the recorder has no run dir). Never raises — a full disk must
        not turn a trigger into a second failure."""
        if self.path is None:
            return None
        snap = self.snapshot(reason)
        with self._lock:
            self.dumps += 1
            self._saved_seq = self._seq
        tmp = self.path.with_suffix(f".tmp.{os.getpid()}")
        try:
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            return None
        if self.events is not None and reason != "autosave":
            # the periodic autosave is housekeeping, not an incident — only
            # triggered dumps leave an event row
            try:
                self.events.counter(
                    "serve/flightrecorder", reason=reason,
                    replica=self.replica,
                    in_flight=len(snap["in_flight"]))
            except Exception:
                pass  # telemetry must not fail the dump path
        return self.path

    # -- background autosave --------------------------------------------------

    def start_autosave(self, interval_s: Optional[float] = None) -> None:
        """Persist the rings every ``interval_s`` while they change
        (``DLAP_FLIGHT_AUTOSAVE_S``, default 1.0; <= 0 disables): a
        replica SIGKILLed with no last words leaves a snapshot at most one
        interval stale."""
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(ENV_AUTOSAVE,
                                                  DEFAULT_AUTOSAVE_S))
            except ValueError:
                interval_s = DEFAULT_AUTOSAVE_S
        if interval_s <= 0 or self.path is None \
                or self._autosave_thread is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                with self._lock:
                    dirty = self._seq != self._saved_seq
                if dirty:
                    self.dump("autosave")

        self._autosave_thread = threading.Thread(
            target=loop, daemon=True, name="flight-autosave")
        self._autosave_thread.start()

    def stop_autosave(self) -> None:
        self._stop.set()
        if self._autosave_thread is not None:
            self._autosave_thread.join(timeout=2)
            self._autosave_thread = None


def load_flightrecorder(run_dir,
                        prev: bool = False) -> Optional[Dict[str, Any]]:
    """Read a run dir's ``flightrecorder.json`` (``prev=True``: the
    rotated previous-incarnation dump — where a SIGKILLed replica's last
    snapshot lands after its supervised restart). Tolerant: missing or
    torn → None. The atomic dump makes torn documents unreachable in
    practice; this guard covers manual copies."""
    path = Path(run_dir) / (FILENAME_PREV if prev else FILENAME)
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None


def slowest_requests(records: List[Dict[str, Any]],
                     n: int = 5) -> List[Dict[str, Any]]:
    """The slowest-N request records by total duration, deterministically
    ordered (duration desc, then trace id) — shared by the report CLI's
    tail-latency section and ad-hoc recorder reads."""
    keyed = [r for r in records
             if isinstance(r.get("duration_s"), (int, float))]
    keyed.sort(key=lambda r: (-r["duration_s"], str(r.get("trace_id"))))
    return keyed[:n]
