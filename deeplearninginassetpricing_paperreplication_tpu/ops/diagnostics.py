"""Model-health diagnostic kernels — the moment-condition residuals as
observables.

The losses in :mod:`ops.losses` already form every residual the paper's
no-arbitrage claim rests on (``E[h_j · w·R · M] = 0`` per moment function
h_j, Chen–Pelger–Zhu JFE 2024); training just collapses them into one
scalar and throws the structure away. This module keeps the structure:
per-moment-function conditional violation norms (one scalar per h_j), the
unconditional pricing-error norm, SDF series statistics, portfolio
concentration/turnover diagnostics, and the generator-vs-discriminator
adversarial gap — all as pure jittable functions of (params, batch) that
fold into the scanned phase programs (``training/trainer.py
--diag_stride``), the promotion gate, and the serving quality monitors
without a single host sync.

Every function reuses the exact masked-panel semantics of
:mod:`ops.losses` (per-asset valid lengths T_i clamped to ≥ 1, per-period
valid counts), so ``mean_k violations[k]² == conditional_loss`` holds to
float32 ulps — asserted in tier-1.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .losses import conditional_loss, portfolio_returns, unconditional_loss
from .metrics import normalize_weights_abs

# the scalar diagnostic keys panel_diagnostics emits, in a stable order
# (history.npz fields are 'diag_' + key; 'moment_violations' is the one
# [K]-vector companion). 'computed' is the explicit stride sentinel: 1.0
# on epochs the diagnostics actually ran, 0.0 on the zero-filled
# off-stride epochs — consumers must NOT infer computedness from a value
# field (a degenerate epoch can legitimately record 0.0 or NaN-mapped
# values everywhere else)
SCALAR_KEYS = (
    "computed",
    "moment_violation_max",
    "unc_violation",
    "sdf_mean",
    "sdf_vol",
    "sdf_min",
    "sdf_finite_frac",
    "weight_hhi",
    "weight_max_abs",
    "short_fraction",
    "turnover",
    "adv_gap",
    "loss_unc",
    "loss_cond",
)


def moment_violations(
    weights: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
    moments: jnp.ndarray,
    weighted: bool = True,
    F: jnp.ndarray = None,
    n_assets: jnp.ndarray = None,
) -> jnp.ndarray:
    """Per-moment-function conditional violation norms [K]:

        v_k = sqrt( mean_i ( Σ_t h_k·R·m·M / T_i )² )

    — the square root of each h_k's contribution to the conditional loss,
    so ``mean_k v_k² == conditional_loss``. One einsum over the moment
    axis, identical ragged-panel denominators as
    :func:`ops.losses.conditional_loss` — INCLUDING ``n_assets``, the
    true asset count when the stock axis is padded (sharding / kernel
    tiling): padded all-masked columns contribute exactly 0 to em, so
    dividing by the true count keeps the norms equal to the unpadded
    panel's instead of diluted by the pad ratio.
    """
    if F is None:
        F = portfolio_returns(weights, returns, mask, weighted)
    sdf = 1.0 + F
    t_per_asset = jnp.clip(mask.sum(axis=0), 1, None)  # [N]
    x = returns * mask * sdf[:, None]  # [T, N]
    em = jnp.einsum("ktn,tn->kn", moments, x) / t_per_asset[None, :]
    if n_assets is None:
        return jnp.sqrt((em**2).mean(axis=1))  # [K]
    return jnp.sqrt((em**2).sum(axis=1) / n_assets)


def unconditional_violation(
    weights: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
    weighted: bool = True,
    F: jnp.ndarray = None,
    n_assets: jnp.ndarray = None,
) -> jnp.ndarray:
    """sqrt of the unconditional pricing-error norm — h ≡ 1's violation."""
    loss, _ = unconditional_loss(weights, returns, mask, weighted, F=F,
                                 n_assets=n_assets)
    return jnp.sqrt(loss)


def sdf_series_stats(F: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Stats of the SDF series M_t = 1 + F_t: mean, vol (ddof=0), min,
    and the finite fraction (a degenerate generation shows up here first).
    Non-finite entries are excluded from the moments so one NaN month does
    not erase the rest of the story."""
    m = 1.0 + F
    finite = jnp.isfinite(m)
    frac = finite.mean()
    safe = jnp.where(finite, m, 0.0)
    n = jnp.clip(finite.sum(), 1, None)
    mean = safe.sum() / n
    vol = jnp.sqrt(jnp.clip((((safe - mean) * finite) ** 2).sum() / n, 0.0,
                            None))
    mmin = jnp.where(finite, m, jnp.inf).min()
    return {"sdf_mean": mean, "sdf_vol": vol, "sdf_min": mmin,
            "sdf_finite_frac": frac}


def portfolio_diagnostics(
    weights: jnp.ndarray, mask: jnp.ndarray
) -> Dict[str, jnp.ndarray]:
    """Concentration and churn of the served portfolio, on the abs-sum-
    normalized weights (Σ_i |w·m| = 1 per period, the serving convention):

      * ``weight_hhi``     — mean_t Σ_i (|w|·m)²: Herfindahl concentration
        (1/N̄ for equal weight, → 1 for a one-stock book);
      * ``weight_max_abs`` — max |w·m| over the panel;
      * ``short_fraction`` — mean_t Σ_i max(−w, 0)·m (share of the unit
        gross book held short);
      * ``turnover``       — mean_{t≥1} ½ Σ_i |w_t − w_{t−1}|·(m_t·m_{t−1})
        month-to-month churn over stocks valid in both months.
    """
    nw = normalize_weights_abs(weights, mask) * mask
    hhi = (jnp.abs(nw) ** 2).sum(axis=1).mean()
    max_abs = jnp.abs(nw).max()
    short = jnp.clip(-nw, 0.0, None).sum(axis=1).mean()
    both = mask[1:] * mask[:-1]
    churn = 0.5 * (jnp.abs(nw[1:] - nw[:-1]) * both).sum(axis=1)
    n_steps = jnp.clip(jnp.asarray(churn.shape[0], jnp.float32), 1, None)
    turnover = churn.sum() / n_steps
    return {"weight_hhi": hhi, "weight_max_abs": max_abs,
            "short_fraction": short, "turnover": turnover}


def panel_diagnostics(
    weights: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
    moments: jnp.ndarray,
    weighted: bool = True,
    n_assets: jnp.ndarray = None,
) -> Dict[str, jnp.ndarray]:
    """The full diagnostic set from one eval-mode forward's outputs.

    Returns ``moment_violations`` ([K]) plus every scalar in
    :data:`SCALAR_KEYS` (float32). ``adv_gap`` is the generator-vs-
    discriminator gap ``loss_cond − loss_unc``: the conditional (h-weighted)
    pricing error the discriminator still finds beyond the unconditional
    one the generator already prices. ``n_assets``: the true asset count
    under stock-axis padding — the SAME correction every loss in
    :mod:`ops.losses` takes, so the diagnostics agree with the trained
    losses on padded (``--shard_stocks``) panels.
    """
    F = portfolio_returns(weights, returns, mask, weighted)
    violations = moment_violations(weights, returns, mask, moments,
                                   weighted, F=F, n_assets=n_assets)
    loss_cond, _ = conditional_loss(weights, returns, mask, moments,
                                    weighted, F=F, n_assets=n_assets)
    loss_unc, _ = unconditional_loss(weights, returns, mask, weighted, F=F,
                                     n_assets=n_assets)
    out: Dict[str, jnp.ndarray] = {
        "computed": jnp.float32(1.0),
        "moment_violations": violations.astype(jnp.float32),
        "moment_violation_max": violations.max(),
        "unc_violation": jnp.sqrt(loss_unc),
        "adv_gap": loss_cond - loss_unc,
        "loss_unc": loss_unc,
        "loss_cond": loss_cond,
    }
    out.update(sdf_series_stats(F))
    out.update(portfolio_diagnostics(weights, mask))
    return {k: jnp.asarray(v, jnp.float32) for k, v in out.items()}


def make_diag_fn(gan):
    """diag(params, batch) → :func:`panel_diagnostics` dict, from an
    eval-mode forward (no dropout). Safe to close over inside jit / scan /
    vmap — this is what the trainer folds into the phase programs and the
    promotion gate vmaps over candidate members."""

    def diag(params, batch) -> Dict[str, jnp.ndarray]:
        batch = gan.prepare_batch(batch)
        weights = gan.weights(params, batch)
        moments = gan.moments(params, batch)
        return panel_diagnostics(weights, batch["returns"], batch["mask"],
                                 moments, gan.cfg.weighted_loss,
                                 n_assets=batch.get("n_assets"))

    return diag


def zeros_diagnostics(num_moments: int) -> Dict[str, jnp.ndarray]:
    """The zero-valued pytree matching :func:`panel_diagnostics` output —
    the off-stride branch of the scanned ``lax.cond`` (both branches must
    return the identical structure)."""
    out = {k: jnp.float32(0.0) for k in SCALAR_KEYS}
    out["moment_violations"] = jnp.zeros((num_moments,), jnp.float32)
    return out


def strided_diagnostics(
    diag_fn, params: Any, batch, epoch: jnp.ndarray, stride: int,
    num_moments: int,
) -> Dict[str, jnp.ndarray]:
    """Compute the diagnostics only every ``stride`` epochs inside a
    scanned body (``lax.cond`` on the traced epoch index; off-epochs emit
    zeros). The cond operand is the ~12k-float params tree — the panel
    batch stays a closure constant, so the skipped branch moves nothing."""
    return jax.lax.cond(
        epoch % stride == 0,
        lambda p: diag_fn(p, batch),
        lambda p: zeros_diagnostics(num_moments),
        params,
    )
