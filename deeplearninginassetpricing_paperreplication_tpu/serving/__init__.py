"""Online SDF inference: run-dir checkpoints → a low-latency service.

The offline pipeline ends at checkpoints (``train``/``evaluate_ensemble``);
this subpackage is the online path from "month of firm characteristics +
macro state" to "portfolio weights / SDF factor":

  * :mod:`.engine`  — ``InferenceEngine``: K stacked checkpoints, AOT-
    compiled per-bucket forward programs (zero steady-state recompiles),
    incremental O(1) macro LSTM state;
  * :mod:`.batcher` — deadline/size-triggered micro-batching with
    per-bucket lanes and bounded backpressure;
  * :mod:`.server`  — stdlib ``ThreadingHTTPServer`` JSON API
    (``/v1/weights``, ``/v1/sdf``, ``/v1/macro``, ``/v1/models``,
    ``/healthz``, ``/metrics``) with observability spans, bench-format
    heartbeats, and an LRU result cache;
  * :mod:`.loadgen` — open/closed-loop load generator (p50/p95/p99,
    throughput) and the ``bench.py`` ``serving`` section.

Served outputs are bit-identical to the offline ``evaluate_ensemble``
batch path for the same checkpoints and months (asserted in tier-1).
"""

from .batcher import MicroBatcher, QueueFull
from .engine import (
    InferenceEngine,
    InferenceRequest,
    InferenceResult,
    bucket_for,
)
from .loadgen import bench_serving, run_loadgen
from .server import LRUCache, ServingService, make_server

__all__ = [
    "InferenceEngine",
    "InferenceRequest",
    "InferenceResult",
    "LRUCache",
    "MicroBatcher",
    "QueueFull",
    "ServingService",
    "bench_serving",
    "bucket_for",
    "make_server",
    "run_loadgen",
]
