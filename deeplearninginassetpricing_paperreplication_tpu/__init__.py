"""TPU-native deep-learning asset-pricing framework.

A from-scratch JAX/XLA rebuild of the capabilities of
``omroot/DeepLearningInAssetPricing_PaperReplication`` (Chen–Pelger–Zhu
GAN-SDF). Implemented so far: panel data core, synthetic data generator,
Flax SDF/Moment networks with torch-compatible parameterization, and the
fused moment-condition losses. The on-device 3-phase trainer, stock-axis
sharding, and vmapped ensembles/sweeps live in ``training/`` and
``parallel/`` as they land.

Public API mirrors the reference's ``src/__init__.py`` exports where a
counterpart exists.
"""

__version__ = "0.1.0"

from .data.panel import PanelDataset, load_panel, load_splits
from .data.synthetic import generate_all_splits, generate_dataset
from .models.gan import GAN
from .models.networks import AssetPricingModule, MomentNet, SDFNet, SimpleSDF
from .ops.losses import (
    conditional_loss,
    portfolio_returns,
    residual_loss,
    unconditional_loss,
)
from .ops.metrics import max_drawdown, normalize_weights_abs, sharpe
from .utils.config import GANConfig, TrainConfig

__all__ = [
    "PanelDataset",
    "load_panel",
    "load_splits",
    "generate_all_splits",
    "generate_dataset",
    "GAN",
    "AssetPricingModule",
    "SDFNet",
    "MomentNet",
    "SimpleSDF",
    "GANConfig",
    "TrainConfig",
    "conditional_loss",
    "unconditional_loss",
    "residual_loss",
    "portfolio_returns",
    "sharpe",
    "max_drawdown",
    "normalize_weights_abs",
    "__version__",
]
