"""Multi-seed ensembles as a vmapped axis — train 9 models in ONE program.

The reference trains its 9-seed ensemble serially (~6 h CPU,
``demo_full.ipynb`` cell 22) and evaluates it with a serial per-model loop
(``/root/reference/src/evaluate_ensemble.py:112-131``). Here the seed axis is
a `jax.vmap` axis over the whole 3-phase compiled trainer: one XLA program
trains every member simultaneously (the per-member matmuls batch onto the
MXU), and the same axis can be laid out over a ('batch', 'stocks') device
mesh so members and panel shards ride separate mesh dimensions.

Evaluation replicates the paper's protocol exactly
(evaluate_ensemble.py:137-171): average the members' abs-sum-normalized
weights, re-normalize per period, compute portfolio returns, and report the
Sharpe of the NEGATED return series with numpy (ddof=0) std.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gan import GAN
from ..observability.logging import get_run_logger
from ..ops.metrics import (
    cross_sectional_r2,
    explained_variation,
    factor_betas,
    sharpe,
)
from ..utils.config import ExecutionConfig, GANConfig, TrainConfig
from ..utils.rng import train_base_key
from ..training.trainer import build_phase_scan, fresh_best
from ..training.steps import make_optimizer, trainable_key

Params = jax.Array
Batch = Dict[str, jax.Array]

# Cap epochs per DEVICE DISPATCH for the vmapped phase programs. One
# uninterrupted multi-member phase-3 execution at the real shape runs for
# minutes, and >~2 min single dispatches have crashed the remote-attached
# TPU worker ("kernel fault" from the tunnel; 9 members × 1024 epochs at
# hidden=(128,128) reproduces it, shorter dispatches of the same program
# never do). Segments share ONE compiled program (the epoch offset is a
# traced scalar, so absolute epoch indices — dropout streams, ignore_epoch
# eligibility — match the unsegmented scan exactly), and history is fetched
# once per phase, so the overhead is a few host round-trips.
DISPATCH_EPOCHS = 256


def phase_donate_argnums() -> tuple:
    """Donated argnums for the chunked vmapped phase programs (ensemble and
    sweep-bucket): the `(opt state, best tracker)` carry — arguments 1 and
    2 of ``run(params, opt, best, train, valid, test, keys, e0)``. Each
    segment dispatch then recycles the carry's device buffers into its
    outputs instead of double-buffering them for the whole dispatch.

    Params (arg 0) are NOT donated: callers alias the phase-1 best
    selection across later phase dispatches (``params_phase1_best`` feeds
    the final reload chain after phase 3), and donating would delete those
    buffers under the alias. Batches and the per-phase key vector are
    reused across segments and phases, so they are never donated either.

    Resolved OFF on the CPU backend, where XLA cannot donate and warns
    "donated buffers were not usable" per dispatch — the same guard
    ``serving/engine.py`` applies to its AOT bucket programs.
    """
    return (1, 2) if jax.default_backend() != "cpu" else ()


def _segment_lens(num_epochs: int, chunk: int = DISPATCH_EPOCHS):
    """The segment lengths a chunked phase dispatch uses — THE single
    definition of the chunking policy. _run_phase_chunked dispatches these
    sizes; the sweep's warm-ahead compiler (parallel.sweep) compiles exactly
    them, so warmed programs can never drift from dispatched ones."""
    sizes, e = [], 0
    while e < num_epochs:
        k = min(chunk, num_epochs - e)
        sizes.append(k)
        e += k
    return sizes or [0]  # [0]: zero-epoch phase, one empty scan


def _run_phase_chunked(make_vmapped, num_epochs, params, opt, best, batches,
                       keys, chunk=DISPATCH_EPOCHS):
    """Dispatch a vmapped phase scan in `chunk`-epoch segments.

    `make_vmapped(seg_len)` builds the jitted vmapped program for one
    segment length (called at most twice: the chunk size and a remainder).
    Returns (params, opt, best, history) with per-segment histories
    concatenated on the epoch axis (axis 1 of [S, E, ...]) in ONE batched
    device fetch.

    Segment sizes come from _segment_lens — the ONE definition of the
    chunking policy, shared with the sweep's warm-ahead compiler so warmed
    programs always match dispatched ones.
    """
    sizes = _segment_lens(num_epochs, chunk)  # [0] for a zero-epoch phase
    progs: Dict[int, Any] = {}
    hists = []
    e = 0
    for k in sizes:
        if k not in progs:
            progs[k] = make_vmapped(k)
        params, opt, best, h = progs[k](
            params, opt, best, *batches, keys, jnp.int32(e)
        )
        hists.append(h)
        e += k
    hists = jax.device_get(hists)
    if len(hists) == 1:
        return params, opt, best, hists[0]
    cat = {
        key: np.concatenate([np.asarray(h[key]) for h in hists], axis=1)
        for key in hists[0]
    }
    return params, opt, best, cat


def init_ensemble_params(gan: GAN, seeds: Sequence[int]):
    """Stack per-seed init params along a leading ensemble axis [S, ...]."""
    keys = jnp.stack([jax.random.key(int(s)) for s in seeds])
    return jax.vmap(lambda k: gan.init(k))(keys)


def run_member_chunks(run_one, items, chunk):
    """Run `run_one(sub_items)` over `items` split into `chunk`-sized groups
    and concatenate the resulting pytrees of arrays along axis 0.

    THE member-chunking primitive shared by the ensemble and sweep engines:
    caps a vmapped program's member axis so the XLA route's ~2.1 GB/member
    activations (real panel shape) fit the device. Chunks re-trace their
    programs, but equal-size chunks hit the persistent XLA compilation
    cache, so only the first chunk pays a real compile.
    """
    parts = [run_one(items[i:i + chunk]) for i in range(0, len(items), chunk)]

    def cat(*xs):
        if isinstance(xs[0], np.ndarray):
            return np.concatenate(xs, axis=0)
        return jnp.concatenate(xs, axis=0)

    return jax.tree.map(cat, *parts)


def train_ensemble(
    config: GANConfig,
    train_batch: Batch,
    valid_batch: Batch,
    test_batch: Optional[Batch] = None,
    seeds: Sequence[int] = (42, 123, 456, 789, 1000, 2000, 3000, 4000, 5000),
    tcfg: Optional[TrainConfig] = None,
    member_sharding=None,
    verbose: bool = True,
    member_chunk: Optional[int] = None,
    exec_cfg: Optional[ExecutionConfig] = None,
    heartbeat=None,
) -> Tuple[GAN, Params, Dict[str, np.ndarray]]:
    """Train len(seeds) models with the full 3-phase schedule, vmapped.

    The member axis vmaps straight through the MEMBER-FUSED Pallas kernels
    (ops/pallas_ffn.py, ops/pallas_moment.py): the fused ops' custom
    batching rules keep every member's weights resident in VMEM and loop
    members over each resident panel tile, so the panel streams from HBM
    once per pass regardless of the member count. Measured at the real
    shape (T=240, N=10k, 9 members, one v5e chip): 3.5 ms per member-epoch
    — vs 6.24 on round 3's grid-prepend batching (which re-read the panel
    per member) and 24.2 on the vmapped plain-XLA route — at ~0.1 GB per
    member vs the XLA route's ~2.1 GB; see docs/ARCHITECTURE.md "member
    fusion" and "compute floor" for why ~3.5 ms is the floor for distinct
    12k-param members on one chip.

    `member_sharding`: optional sharding (``partition.member_sharding(mesh)``
    — the member axis over the mesh's stack dimension) to lay the
    ensemble axis over a mesh dimension — each device group trains its
    members while the panel stays sharded/replicated per the batch arrays.

    `member_chunk`: train at most this many members per vmapped program,
    running chunks sequentially and concatenating. Needed mostly for the
    plain-XLA route (exec_cfg pallas off / non-TPU backends) where
    activations are ~2.1 GB/member at the real panel shape. Chunks of
    equal size reuse one compiled program.

    `exec_cfg`: execution route for every member (default: auto — fused
    kernels on TPU, plain XLA elsewhere).

    `heartbeat`: optional observability.Heartbeat — stamped at every phase
    entry so a supervising watchdog sees liveness advance through a
    multi-minute ensemble instead of one stale pre-training beat.

    Returns (gan, stacked final params [S, ...], history dict [S, E]).
    """
    tcfg = tcfg or TrainConfig()
    if member_chunk is not None and 0 < member_chunk < len(seeds):
        gan_box = []

        def run_one(seed_group):
            gan, vparams, history = train_ensemble(
                config, train_batch, valid_batch, test_batch,
                seeds=seed_group, tcfg=tcfg,
                member_sharding=member_sharding, verbose=verbose,
                exec_cfg=exec_cfg, heartbeat=heartbeat,
            )
            gan_box.append(gan)
            return {"params": vparams, "history": history}

        out = run_member_chunks(run_one, list(seeds), member_chunk)
        return gan_box[0], out["params"], out["history"]
    gan = GAN(config, exec_cfg or ExecutionConfig())
    S = len(seeds)
    has_test = test_batch is not None
    # Derived arrays for the kernel route (feature-major panel), hoisted out
    # of the vmapped programs — shared by every member. Prepare BEFORE
    # aliasing test:=valid so the placeholder shares valid's individual_t
    # buffer instead of materializing a duplicate panel transpose.
    train_batch = gan.prepare_batch(train_batch)
    valid_batch = gan.prepare_batch(valid_batch)
    test_batch = (
        gan.prepare_batch(test_batch) if has_test else valid_batch
    )

    vparams = init_ensemble_params(gan, seeds)
    if member_sharding is not None:
        vparams = jax.device_put(vparams, member_sharding)
    tx_sdf = make_optimizer(tcfg.lr, tcfg.grad_clip)
    tx_moment = make_optimizer(tcfg.lr, tcfg.grad_clip)
    base_keys = jnp.stack([train_base_key(s) for s in seeds])
    phase_keys = jax.vmap(lambda k: jax.random.split(k, 3))(base_keys)  # [S, 3]

    opt_sdf = jax.vmap(tx_sdf.init)(vparams[trainable_key("unconditional")])
    opt_moment = jax.vmap(tx_moment.init)(vparams[trainable_key("moment")])

    def vrun(phase, tx, num_epochs, params, opt, best, key_idx):
        if heartbeat is not None:
            heartbeat.beat(f"ensemble_{phase}", memory=True)

        def make_vmapped(seg_len):
            run = build_phase_scan(
                gan, phase, tx, seg_len, tcfg.ignore_epoch, has_test)
            return jax.jit(
                jax.vmap(run, in_axes=(0, 0, 0, None, None, None, 0, None)),
                donate_argnums=phase_donate_argnums(),
            )

        return _run_phase_chunked(
            make_vmapped, num_epochs, params, opt, best,
            (train_batch, valid_batch, test_batch), phase_keys[:, key_idx],
        )

    # structured logger: human lines from process 0 only (multihost workers
    # keep their copy in their own events.jsonl instead of spamming stdout)
    logger = get_run_logger()

    def log(msg):
        logger.info(msg, verbose=verbose)

    log(f"Ensemble: {S} seeds × ({tcfg.num_epochs_unc}+{tcfg.num_epochs_moment}"
        f"+{tcfg.num_epochs}) epochs, one vmapped program per phase")

    # Phase 1
    best1 = jax.vmap(fresh_best)(vparams)
    vparams, opt_sdf, best1, h1 = vrun(
        "unconditional", tx_sdf, tcfg.num_epochs_unc, vparams, opt_sdf, best1, 0
    )
    vparams = _vselect(best1["updated_sharpe"], best1["params_sharpe"], vparams)
    params_phase1_best = vparams

    # Phase 2
    if tcfg.num_epochs_moment > 0:
        best2 = jax.vmap(partial(fresh_best, for_moment=True))(vparams)
        vparams, opt_moment, best2, _h2 = vrun(
            "moment", tx_moment, tcfg.num_epochs_moment, vparams, opt_moment, best2, 1
        )

    # Phase 3
    best3 = jax.vmap(fresh_best)(vparams)
    vparams, opt_sdf, best3, h3 = vrun(
        "conditional", tx_sdf, tcfg.num_epochs, vparams, opt_sdf, best3, 2
    )
    final = _vselect(
        best3["updated_sharpe"], best3["params_sharpe"],
        _vselect(best1["updated_sharpe"], params_phase1_best, vparams),
    )

    history = {
        k: np.concatenate([np.asarray(h1[k]), np.asarray(h3[k])], axis=1)
        for k in h1
    }
    log("Ensemble training complete")
    return gan, final, history


class QuorumError(RuntimeError):
    """Fewer ensemble members survived than the quorum requires."""


def member_validity(vparams) -> np.ndarray:
    """[S] bool: is every parameter of member s finite? A diverged member
    (NaN/Inf anywhere in its tree) would poison the weight-averaged
    ensemble — one bad seed's NaN weights make the whole averaged matrix
    NaN — so this is the drop criterion quorum semantics filter on."""
    host = jax.device_get(vparams)
    leaves = jax.tree.leaves(host)
    ok = np.ones(np.shape(leaves[0])[0], dtype=bool)
    for leaf in leaves:
        arr = np.asarray(leaf, np.float32)
        ok &= np.isfinite(arr.reshape(arr.shape[0], -1)).all(axis=1)
    return ok


def apply_quorum(
    vparams,
    seeds: Sequence[int],
    quorum: int,
) -> Tuple[Any, List[int], List[int]]:
    """Quorum semantics for a trained ensemble: drop non-finite members and
    proceed when at least `quorum` survive.

    Returns ``(surviving vparams, kept seeds, dropped seeds)`` — the member
    axis is filtered, so every downstream consumer (metrics, weight
    averaging, checkpoint saving) sees only survivors. Raises
    :class:`QuorumError` (naming the dropped seeds) when survivors fall
    below the quorum: shipping a 2-of-9 "ensemble" silently would
    misrepresent the protocol. With all members finite this is a no-op
    pass-through, bit-identical to no quorum at all."""
    seeds = [int(s) for s in seeds]
    ok = member_validity(vparams)
    if ok.all():
        return vparams, seeds, []
    kept = [s for s, good in zip(seeds, ok) if good]
    dropped = [s for s, good in zip(seeds, ok) if not good]
    if len(kept) < quorum:
        raise QuorumError(
            f"only {len(kept)} of {len(seeds)} ensemble members survived "
            f"(non-finite params in seeds {dropped}); quorum is {quorum}"
        )
    idx = jnp.asarray(np.flatnonzero(ok))
    return jax.tree.map(lambda x: x[idx], vparams), kept, dropped


def _vselect(pred_vec, new_tree, old_tree):
    """Per-member select: pred [S] broadcast against leading axis of leaves."""
    def sel(a, b):
        pred = pred_vec.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(pred, a, b)

    return jax.tree.map(sel, new_tree, old_tree)


# -- paper-protocol ensemble evaluation -------------------------------------


def member_weights(gan: GAN, vparams, batch: Batch) -> jax.Array:
    """[S, T, N] abs-sum-normalized weights for every member, one vmap.

    The fused kernels vmap over the member axis (pallas_call's batching rule
    adds a grid dimension), so evaluation rides the same fast route as
    training — but with an f32 panel: reported paper-protocol metrics should
    not depend on the bf16_panel TRAINING optimization (the same checkpoint
    must evaluate identically whether it was trained on TPU or loaded on a
    CPU host, up to matmul precision class). This is the single place the
    member-eval route decision lives.
    """
    if gan.exec_cfg.bf16_panel:
        import dataclasses as _dc

        gan = GAN(gan.cfg, _dc.replace(gan.exec_cfg, bf16_panel=False))
    if batch.get("individual_t") is not None and (
        batch["individual_t"].dtype == jnp.bfloat16
    ):  # pre-prepared training batch: re-derive the panel at f32
        batch = {k: v for k, v in batch.items() if k != "individual_t"}
    batch = gan.prepare_batch(batch)
    return jax.vmap(lambda p: gan.normalized_weights(p, batch))(vparams)


def ensemble_metrics(
    gan: GAN, vparams, batch: Batch
) -> Dict[str, np.ndarray]:
    """The reference's ensemble math (evaluate_ensemble.py:137-171), fused:

    mean member weights → re-normalize |w| to 1 per period (only where the
    abs-sum exceeds 1e-8, matching the reference's guard) → portfolio
    returns → Sharpe of the NEGATED series, ddof=0.

    Also returns each member's individual (negated) Sharpe.
    """

    @jax.jit
    def compute(vparams, batch):
        w = member_weights(gan, vparams, batch)  # [S, T, N]
        return _ensemble_math(w, batch)

    out = compute(vparams, batch)
    return {k: np.asarray(v) for k, v in out.items()}


def _ensemble_math(w: jnp.ndarray, batch: Batch) -> Dict[str, jnp.ndarray]:
    """The shared paper-protocol reduction from stacked member weights
    [S, T, N]: mean → re-normalize (guarded, evaluate_ensemble.py:142-157) →
    portfolio returns → negated ddof=0 Sharpe, plus the paper's Table-1
    EV / XS-R² companions the reference's evaluator lacks."""
    mask, returns = batch["mask"], batch["returns"]
    indiv_port = (w * returns * mask).sum(axis=2)  # [S, T]
    indiv_sharpe = jax.vmap(lambda r: sharpe(-r, ddof=0))(indiv_port)

    avg = w.mean(axis=0)  # [T, N]
    abs_sum = (jnp.abs(avg) * mask).sum(axis=1, keepdims=True)
    avg = jnp.where(abs_sum > 1e-8, avg / abs_sum, avg)
    port = (avg * returns * mask).sum(axis=1)  # [T]
    betas = factor_betas(returns, port, mask)
    return {
        "ensemble_sharpe": sharpe(-port, ddof=0),
        "ensemble_port_returns": port,
        "individual_sharpes": indiv_sharpe,
        "avg_weights": avg,
        "explained_variation": explained_variation(returns, port, mask, betas),
        "cross_sectional_r2": cross_sectional_r2(returns, port, mask, betas),
    }


_jitted_ensemble_math = jax.jit(_ensemble_math)


def ensemble_metrics_from_weights(
    member_w: jnp.ndarray, batch: Batch
) -> Dict[str, np.ndarray]:
    """Same paper-protocol math as :func:`ensemble_metrics`, but starting from
    stacked per-member normalized weights [S, T, N] instead of params.

    This is how members with DIFFERENT architectures ensemble (the reference
    averages [T, N] weight matrices, never params — evaluate_ensemble.py:
    137-139), e.g. the grand ensemble across the sweep's top-k configs.
    """
    out = _jitted_ensemble_math(jnp.asarray(member_w), batch)
    return {k: np.asarray(v) for k, v in out.items()}
