"""Model-health artifacts: ``health.json`` per run dir + threshold
classification + the diagnostics-overhead bench.

The diagnostic *kernels* live in :mod:`ops.diagnostics` (pure jittable
functions); this module is the host-side plumbing around them:

  * :func:`compute_health` — one jitted diagnostics pass over (params,
    batch) → plain-float health document (per-moment violation norms,
    SDF series stats, portfolio concentration/turnover, adversarial gap,
    divergence-guard trip count);
  * :func:`write_health` / :func:`read_health` — the verified
    ``health.json`` artifact every training run dir carries
    (``reliability.verified``: atomic write + sha256 sidecar; reads are
    tolerant — an old run dir without one reads as None, never a
    KeyError);
  * :func:`candidate_diagnostics` — the member-vmapped worst-case
    diagnostics the promotion gate thresholds (``moment_violation``);
  * :class:`HealthThresholds` — the configurable bars, with
    :meth:`~HealthThresholds.classify` returning stable reason slugs;
  * :func:`bench_health_overhead` — the ``bench.py --health`` measurement
    (diag stride on vs off, interleaved best-of-N, params bit-identity)
    behind ``BENCH_HEALTH.json``'s budget gate.

Module level stays jax-free (stdlib + the verified IO): the report CLI
reads ``health.json`` without paying a backend import; jax loads lazily
inside the compute functions.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

HEALTH_FILENAME = "health.json"

# default gate bars. moment_tolerance is deliberately generous: the point
# of the default is catching DEGENERATE candidates (NaN/Inf violations or
# order-of-magnitude blowups), not re-litigating the loss the trainer
# already minimized — operators tighten it per deployment.
DEFAULT_MOMENT_TOLERANCE = 1.0
DEFAULT_MIN_FINITE_FRACTION = 1.0


def _finite_or_none(x: Any) -> Optional[float]:
    try:
        v = float(x)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


@dataclasses.dataclass(frozen=True)
class HealthThresholds:
    """The configurable model-health bars (promotion gate + report)."""

    moment_tolerance: float = DEFAULT_MOMENT_TOLERANCE
    min_sdf_finite_fraction: float = DEFAULT_MIN_FINITE_FRACTION
    max_weight_hhi: Optional[float] = None  # None = not gated
    max_turnover: Optional[float] = None

    def classify(self, diagnostics: Dict[str, Any]) -> List[str]:
        """Stable violation slugs for one diagnostics dict (empty =
        healthy). Non-finite values always violate."""
        reasons: List[str] = []
        mv = diagnostics.get("moment_violation_max")
        if _finite_or_none(mv) is None or float(mv) > self.moment_tolerance:
            reasons.append("moment_violation")
        frac = diagnostics.get("sdf_finite_frac")
        if (_finite_or_none(frac) is None
                or float(frac) < self.min_sdf_finite_fraction):
            if "moment_violation" not in reasons:
                reasons.append("moment_violation")
        hhi = diagnostics.get("weight_hhi")
        if self.max_weight_hhi is not None and (
                _finite_or_none(hhi) is None
                or float(hhi) > self.max_weight_hhi):
            reasons.append("weight_concentration")
        to = diagnostics.get("turnover")
        if self.max_turnover is not None and (
                _finite_or_none(to) is None or float(to) > self.max_turnover):
            reasons.append("turnover")
        return reasons


# -- computing health (lazy jax) ---------------------------------------------


def compute_diagnostics_host(gan, params, batch) -> Dict[str, Any]:
    """One jitted :func:`ops.diagnostics.panel_diagnostics` pass → plain
    Python floats (``moment_violations`` as a list)."""
    import jax
    import numpy as np

    from ..ops.diagnostics import make_diag_fn

    out = jax.jit(make_diag_fn(gan))(
        params, {k: v for k, v in batch.items()})
    host = {k: np.asarray(v) for k, v in out.items()}
    result: Dict[str, Any] = {
        k: float(v) for k, v in host.items() if v.ndim == 0}
    result["moment_violations"] = [
        float(x) for x in host["moment_violations"]]
    return result


def candidate_diagnostics(gan, vparams, batch) -> Dict[str, Any]:
    """Member-vmapped diagnostics for a stacked candidate ensemble,
    reduced to the WORST case over members (the gate must reject if any
    member is degenerate): per-moment violations max over members, min
    finite fraction, max HHI/turnover. Adds ``per_member_violation_max``
    for the audit trail."""
    import jax
    import numpy as np

    from ..ops.diagnostics import make_diag_fn

    diag = make_diag_fn(gan)
    per = jax.jit(jax.vmap(lambda p: diag(p, batch)))(vparams)
    host = {k: np.asarray(v) for k, v in per.items()}
    worst_max = ("moment_violation_max", "unc_violation", "adv_gap",
                 "weight_hhi", "weight_max_abs", "short_fraction",
                 "turnover", "loss_unc", "loss_cond", "sdf_vol")
    out: Dict[str, Any] = {}
    for k in worst_max:
        out[k] = float(host[k].max())
    out["sdf_finite_frac"] = float(host["sdf_finite_frac"].min())
    out["sdf_mean"] = float(host["sdf_mean"].mean())
    out["sdf_min"] = float(host["sdf_min"].min())
    out["moment_violations"] = [
        float(x) for x in host["moment_violations"].max(axis=0)]
    out["per_member_violation_max"] = [
        float(x) for x in host["moment_violation_max"]]
    return out


def compute_health(
    gan,
    params,
    batch,
    history: Optional[Dict[str, Any]] = None,
    guard_trips: Optional[List] = None,
    split: str = "valid",
    diag_stride: Optional[int] = None,
) -> Dict[str, Any]:
    """The full ``health.json`` document for one trained model: final
    diagnostics on ``batch`` plus the training run's health counters
    (divergence-guard trips, last in-training diagnostic readings when the
    run trained with ``--diag_stride``)."""
    import numpy as np

    diagnostics = compute_diagnostics_host(gan, params, batch)
    finite = all(
        v is not None and math.isfinite(v)
        for v in diagnostics.values() if isinstance(v, float)
    ) and all(math.isfinite(x) for x in diagnostics["moment_violations"])
    doc: Dict[str, Any] = {
        "kind": "model_health",
        "schema": 1,
        "written_at": round(time.time(), 3),
        "split": split,
        "diag_stride": diag_stride,
        "diagnostics": diagnostics,
        "finite": bool(finite),
        "guard_trips": len(guard_trips or []),
        "divergence_trips": [[int(p), int(s), int(e)]
                             for p, s, e in (guard_trips or [])],
    }
    if history and "diag_computed" in history:
        # ONE epoch index for every series — the last stride epoch that
        # actually computed (the explicit diag_computed sentinel; a value
        # field can legitimately be 0.0 there) — so history_last is a
        # consistent end-of-training snapshot, never a per-key mix
        computed = np.nonzero(
            np.asarray(history["diag_computed"], np.float64))[0]
        if computed.size:
            idx = int(computed[-1])
            # history ROW, not absolute epoch: diag rows cover phases 1+3
            # only (phase 2 records none), so absolute epoch = row +
            # num_epochs_moment for phase-3 rows
            last: Dict[str, Any] = {"history_row": idx}
            for key, series in history.items():
                if (not key.startswith("diag_")
                        or key in ("diag_moment_violations",
                                   "diag_computed")):
                    continue
                arr = np.asarray(series, np.float64)
                if arr.ndim == 1 and arr.size > idx:
                    last[key] = float(arr[idx])
            doc["history_last"] = last
    return doc


# -- artifact IO -------------------------------------------------------------


def write_health(run_dir: Union[str, Path],
                 health: Dict[str, Any]) -> Path:
    """Verified write of ``health.json`` (non-finite floats serialized as
    null — the artifact must stay strict-JSON parseable everywhere)."""
    from ..reliability.verified import write_verified

    def sanitize(obj):
        if isinstance(obj, float) and not math.isfinite(obj):
            return None
        if isinstance(obj, dict):
            return {k: sanitize(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [sanitize(v) for v in obj]
        return obj

    path = Path(run_dir) / HEALTH_FILENAME
    write_verified(path, json.dumps(sanitize(health), indent=1).encode())
    return path


def read_health(run_dir: Union[str, Path]) -> Optional[Dict[str, Any]]:
    """Digest-verified read of a run dir's ``health.json`` (plain-file
    fallback for externally produced ones); None when absent or unusable.
    Old (pre-health-plane) run dirs read as None by construction — the
    report CLI renders the "(no health data)" placeholder, never a
    KeyError."""
    from ..reliability.verified import load_verified, verified_exists

    path = Path(run_dir) / HEALTH_FILENAME
    if not verified_exists(path):
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None
    try:
        doc, _ = load_verified(path, lambda b: json.loads(b.decode()))
    except (ValueError, OSError):
        return None
    return doc if isinstance(doc, dict) else None


# -- the diagnostics-overhead bench (bench.py --health) ----------------------


def bench_health_overhead(
    n_periods: int = 48,
    n_stocks: int = 128,
    n_features: int = 10,
    n_macro: int = 4,
    epochs: int = 64,
    diag_stride: int = 8,
    trials: int = 3,
    seed: int = 7,
) -> Dict[str, Any]:
    """Training throughput with the in-scan diagnostics ON (``diag_stride``)
    vs OFF, interleaved best-of-N, plus the observational-freeness check:
    the trained params of the two routes must be BIT-identical (the
    diagnostics read the carry, they never feed it). budgets.json gates
    ``throughput_ratio_on_off >= 0.95`` and ``params_bit_identical == 1``.

    Throughput is epochs / Σ phase-execute seconds (the compiled-scan
    windows the trainer already times) — compile time is excluded, the
    steady-state execute cost is the number that matters."""
    import jax
    import numpy as np

    from ..models.gan import GAN
    from ..training.trainer import Trainer
    from ..utils.config import GANConfig, TrainConfig

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features,
                    hidden_dim=(16, 16), num_units_rnn=(4,))
    tcfg = TrainConfig(num_epochs_unc=epochs, num_epochs_moment=max(
        2, epochs // 4), num_epochs=epochs, ignore_epoch=0)

    def batch(t):
        return {
            "macro": rng.standard_normal((t, n_macro)).astype(np.float32),
            "individual": rng.standard_normal(
                (t, n_stocks, n_features)).astype(np.float32),
            "returns": (rng.standard_normal(
                (t, n_stocks)) * 0.05).astype(np.float32),
            "mask": np.ones((t, n_stocks), np.float32),
        }

    train_b = batch(n_periods)
    valid_b = batch(max(8, n_periods // 4))
    test_b = batch(max(8, n_periods // 4))
    total_epochs = tcfg.num_epochs_unc + tcfg.num_epochs_moment \
        + tcfg.num_epochs

    def run_once(stride):
        gan = GAN(cfg)
        trainer = Trainer(gan, tcfg, diag_stride=stride)
        params = gan.init(jax.random.key(seed))
        final, history = trainer.train(
            params, train_b, valid_b, test_b, verbose=False, seed=seed)
        execute_s = sum(trainer.phase_seconds.values())
        return {
            "execute_s": round(execute_s, 4),
            "epochs_per_s": round(total_epochs / execute_s, 3)
            if execute_s else None,
            "final": final,
            "history": history,
        }

    runs: Dict[str, list] = {"off": [], "on": []}
    for _ in range(max(1, trials)):
        for mode, stride in (("off", None), ("on", diag_stride)):
            runs[mode].append(run_once(stride))

    def best(mode):
        return max(runs[mode], key=lambda r: r["epochs_per_s"] or 0)

    b_off, b_on = best("off"), best("on")
    # observational freeness: bit-identical trained params on both routes
    leaves_off = jax.tree.leaves(runs["off"][0]["final"])
    leaves_on = jax.tree.leaves(runs["on"][0]["final"])
    identical = all(
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        for a, b in zip(leaves_off, leaves_on))
    ratio = (b_on["epochs_per_s"] / b_off["epochs_per_s"]
             if b_off["epochs_per_s"] else None)
    hist_on = runs["on"][0]["history"]
    return {
        "shape": f"T={n_periods} N={n_stocks} F={n_features} "
                 f"M={n_macro} epochs={total_epochs}",
        "diag_stride": diag_stride,
        "trials": trials,
        "epochs_per_s_diag_off": b_off["epochs_per_s"],
        "epochs_per_s_diag_on": b_on["epochs_per_s"],
        "throughput_ratio_on_off": (round(ratio, 4)
                                    if ratio is not None else None),
        "params_bit_identical": int(identical),
        "diag_history_fields": sorted(
            k for k in hist_on if k.startswith("diag_")),
        "all_trials": {
            mode: [{"execute_s": r["execute_s"],
                    "epochs_per_s": r["epochs_per_s"]} for r in rs]
            for mode, rs in runs.items()},
        "note": "3-phase trains with in-scan diagnostics on "
                f"(stride {diag_stride}) vs off, interleaved best-of-"
                f"{trials} on execute seconds (compile excluded); "
                "budgets.json gates throughput_ratio_on_off >= 0.95 and "
                "params_bit_identical == 1 (diagnostics are "
                "observationally free)",
    }
