"""Fused SDF-FFN Pallas kernel: the panel MLP in one HBM pass.

The SDF network's hot path is a tiny MLP applied to every (period, stock)
row of the panel: relu(x@K1 + zp_t) -> relu(@K2 + b2) -> @K3 + b3 (reference
``/root/reference/src/model.py:207-268``). Under plain XLA each Dense layer
is its own fusion, so the [T*N, H] hidden activations round-trip through HBM
twice per layer — at the real workload (T=240, N=10k, H=64) that is ~2.5 GB
of intermediate traffic per forward, which dominates the epoch time (the
whole model is only 12k parameters; the epoch is HBM-bandwidth-bound).

This kernel computes the full MLP tile-by-tile in VMEM: the panel is read
ONCE, the [T, N] weight output written ONCE, and the hidden activations
never leave the chip. The backward pass (custom_vjp) recomputes activations
tile-wise from the same inputs — flash-attention-style rematerialization —
so training needs no stored activations either.

Layout: the kernel consumes the panel feature-major, ``x_t [T, F, N]`` (one
jnp.transpose of the batch's [T, N, F], hoisted outside the epoch scan).
Feature-major puts the long stock axis on the TPU lane dimension, so every
matmul in the kernel is [H, F] x [F, BN] with perfectly-tiled lanes and the
46-wide feature axis pays its <128 padding only once (on the tiny weights)
instead of on every panel row.

Per-period conditioning enters as ``zp [T, H1]`` — the first layer's
period-dependent bias ``macro_state @ K1_macro + b1`` computed in XLA (it is
[T, H1], tiny) — so the LSTM/macro path stays differentiable through zp.

Dropout (training) draws its masks from the TPU-native PRNG
(`pltpu.prng_random_bits`) seeded per (call, grid cell); forward and
backward regenerate identical masks from the same seed. The stream differs
from the XLA path's threefry/rbg dropout — same distribution, different
bits — which is irrelevant to training statistics but means pallas-on vs
pallas-off runs are only bit-identical with dropout disabled.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.extend import core as jex_core
from jax.interpreters import batching, mlir

# Static kernel configuration:
# (dropout_rate, block_stocks, interpret, compute_dtype_name, period_block).
Static = Tuple[float, int, bool, str, int]

_LANE = 128
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024  # conservative: leave room for buffers
_PERIOD_BLOCK_X_BYTES = 2_500_000  # x-tile budget for multi-period blocking


def choose_block_stocks(N: int, F: int, hidden: Sequence[int]) -> int:
    """Largest lane-aligned stock tile whose working set fits the VMEM budget.

    Working set per cell ≈ (F_pad + 3·max(H) + 8) · BN · 4 bytes, doubled for
    the pipeline's input double-buffering of x.
    """
    f_pad = -(-F // 8) * 8
    h = max(hidden) if hidden else 8
    bytes_per_stock = (2 * f_pad + 3 * h + 16) * 4
    bn = _VMEM_BUDGET_BYTES // bytes_per_stock
    bn = max(_LANE, (bn // _LANE) * _LANE)
    return min(bn, -(-N // _LANE) * _LANE)


def choose_period_block(T: int, F: int, bn: int, panel_bytes: int) -> int:
    """Periods per grid cell (Tb) for a FIXED stock tile `bn`: the largest
    divisor of T from {8, 6, 5, 4, 3, 2} whose x tile fits the ~2.5 MB
    budget, else 1. (choose_blocks below optimizes Tb and bn jointly.)"""
    f_pad = -(-F // 8) * 8
    for tb in (8, 6, 5, 4, 3, 2):
        if T % tb == 0 and tb * f_pad * bn * panel_bytes <= _PERIOD_BLOCK_X_BYTES:
            return tb
    return 1


def choose_blocks(T: int, N: int, F: int, hidden: Sequence[int],
                  panel_bytes: int) -> Tuple[int, int]:
    """(block_stocks, period_block) minimizing the GRID CELL COUNT.

    The epoch is per-cell-overhead-bound (measured ~1 µs fixed cost per
    Pallas grid cell — docs/ARCHITECTURE.md 'Bandwidth accounting'), so the
    objective is simply (T/Tb)·ceil(N/BN), subject to: Tb divides T, BN is
    lane-aligned, the per-stock working set fits choose_block_stocks'
    budget, and the (Tb, F, BN) x tile fits the ~2.5 MB double-buffered
    budget. At the real bf16 shape this lands Tb=5, BN=5120 — 96 cells per
    pass instead of the unblocked 480."""
    bn_max = choose_block_stocks(N, F, hidden)
    f_pad = -(-F // 8) * 8
    best_bn, best_tb = bn_max, 1
    best_cells = T * (-(-N // bn_max))
    for tb in (2, 3, 4, 5, 6, 8, 10):
        if T % tb:
            continue
        bn = min(bn_max,
                 _PERIOD_BLOCK_X_BYTES // (tb * f_pad * panel_bytes))
        bn = (bn // _LANE) * _LANE
        if bn < _LANE:
            continue
        bn = min(bn, -(-N // _LANE) * _LANE)
        cells = (T // tb) * (-(-N // bn))
        # fewer cells wins; ties prefer the larger stock tile (fewer ragged
        # edges, bigger matmuls)
        if cells < best_cells or (cells == best_cells and bn > best_bn):
            best_bn, best_tb, best_cells = bn, tb, cells
    return best_bn, best_tb


def _dot(a, b, ca: int, cb: int, cdtype=jnp.float32):
    """dot_general contracting a's dim `ca` with b's dim `cb`.

    Operands are cast to `cdtype` (bf16 by default in the kernels — the same
    precision class as JAX's default TPU matmul, which the XLA path and the
    recorded end-to-end parity runs use); accumulation is always f32.
    """
    return jax.lax.dot_general(
        a.astype(cdtype), b.astype(cdtype), (((ca,), (cb,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _row_to_col(row):
    """[1, H] -> [H, 1] via an identity contraction on the MXU (Mosaic cannot
    relayout a lane vector to sublanes with a plain transpose)."""
    h = row.shape[-1]
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (h, h), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (h, h), 1)
    ).astype(jnp.float32)
    return _dot(eye, row, 1, 1, jnp.float32)  # exact: 1.0 * x


def _dropout_mask(shape, rate: float):
    """Multiplicative inverted-dropout mask from the per-core PRNG (must be
    seeded first). Drawn in a fixed order so fwd and bwd see identical masks."""
    bits = pltpu.prng_random_bits(shape)
    threshold = np.uint32(round(rate * float(2**32)))
    keep = (bits.astype(jnp.uint32) >= threshold).astype(jnp.float32)
    return keep / (1.0 - rate)


def _seed_cell(seed_ref, t, nb, n_blocks: int):
    """Per-(period, stock-block) stream — `t` is the PERIOD index, explicit
    so multi-period cells reproduce the one-period cells' streams exactly.
    Wrapping int32 arithmetic is fine."""
    pltpu.prng_seed(
        seed_ref[0, 0]
        + (t * n_blocks + nb) * np.int32(2654435761 & 0x7FFFFFFF)
    )


def _forward_stack(x, zp_col, k1T, mids, rate: float, cdtype):
    """relu/dropout MLP through the hidden stack on one [F, BN] tile.

    The ONE copy of the layer loop, shared by the forward kernel and both
    backward recomputes (dropout masks are drawn in this fixed order, so
    every kernel seeing the same per-cell seed regenerates identical masks).
    Returns (acts, rmasks, dmasks): post-relu+dropout activations per layer,
    relu masks per layer, dropout masks per layer (empty when rate == 0).
    """
    acts, rmasks, dmasks = [], [], []
    h_pre = _dot(k1T, x, 1, 0, cdtype) + zp_col  # [H1, BN]
    for kT, b in [(None, None)] + list(mids):
        if kT is not None:
            h_pre = _dot(kT, acts[-1], 1, 0, cdtype) + b  # [H_i, BN]
        rmasks.append((h_pre > 0.0).astype(jnp.float32))
        h = jnp.maximum(h_pre, 0.0)
        if rate > 0.0:
            dm = _dropout_mask(h.shape, rate)
            h = h * dm
            dmasks.append(dm)
        acts.append(h)
    return acts, rmasks, dmasks


def _forward_tile(x, zp_col, k1T, mids, rate: float, cdtype):
    """Last hidden activation h_Ld [H_L, BN]; caller applies output proj."""
    acts, _, _ = _forward_stack(x, zp_col, k1T, mids, rate, cdtype)
    return acts[-1]


def _fwd_kernel(seed_ref, x_ref, zp_ref, k1T_ref, *rest, n_mids: int,
                rate: float, n_blocks: int, tb: int, cdtype=jnp.bfloat16):
    """One (Tb-period, stock-block) cell: the full MLP on `tb` consecutive
    period tiles, amortizing the fixed per-cell cost (choose_period_block).
    Dropout streams are per PERIOD, identical to one-period cells."""
    *mid_refs, kout_ref, bout_ref, w_ref = rest
    tbi, nb = pl.program_id(0), pl.program_id(1)
    mids = [(mid_refs[2 * i][:], mid_refs[2 * i + 1][:]) for i in range(n_mids)]
    for tp in range(tb):
        if rate > 0.0:
            _seed_cell(seed_ref, tbi * tb + tp, nb, n_blocks)
        x = x_ref[tp]  # [F, BN]
        zp_col = _row_to_col(zp_ref[tp])  # [H1, 1] broadcasts over lanes
        h = _forward_tile(x, zp_col, k1T_ref[:], mids, rate, cdtype)
        w_ref[tp] = _dot(kout_ref[:], h, 0, 0, cdtype) + bout_ref[0, 0]


def _bwd_kernel(seed_ref, nvalid_ref, x_ref, zp_ref, k1T_ref, *rest,
                n_mids: int, rate: float, n_blocks: int, tb: int,
                cdtype=jnp.bfloat16):
    """Recompute-and-accumulate backward for one (Tb-period, stock) cell.

    Emits, accumulated across the sequential grid: dzp (per-period rows),
    dk1T [H1, F], (dkT_i [H_i, H_in], db_i [H_i, 1]) per mid layer,
    dkout [H_L, 1], dbout [1, 1]. The Tb periods of one cell accumulate
    into LOCAL values first (one ref add per cell, not per period);
    stock-lane masking keeps ragged edge blocks exact.
    """
    mid_refs = rest[: 2 * n_mids]
    kout_ref, g_ref = rest[2 * n_mids], rest[2 * n_mids + 1]
    out_refs = rest[2 * n_mids + 2:]
    dzp_ref, dk1T_ref = out_refs[0], out_refs[1]
    dmid_refs = out_refs[2: 2 + 2 * n_mids]
    dkout_ref, dbout_ref = out_refs[2 + 2 * n_mids], out_refs[3 + 2 * n_mids]

    tbi, nb = pl.program_id(0), pl.program_id(1)
    first = (tbi == 0) & (nb == 0)

    bn = x_ref.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
    valid = (lane + nb * bn) < nvalid_ref[0]  # [1, BN]

    k1T = k1T_ref[:]
    mids = [(mid_refs[2 * i][:], mid_refs[2 * i + 1][:]) for i in range(n_mids)]
    ones = jnp.ones((1, bn), jnp.float32)

    for tp in range(tb):
        # per-PERIOD ref accumulation, exactly the one-period kernel's
        # pattern: each lane reduction keeps its constant-zero accumulator
        # and the cross-period add goes through the ref inside pl.when.
        # (A register-local `loc += contrib` chain canonicalizes into
        # reduction-with-accumulator ops Mosaic rejects — "only constant
        # accumulators supported".)
        def _acc(ref, val, tp=tp):
            if tp == 0:
                @pl.when(first)
                def _():
                    ref[:] = val

                @pl.when(jnp.logical_not(first))
                def _():
                    ref[:] = ref[:] + val
            else:
                ref[:] = ref[:] + val

        if rate > 0.0:
            _seed_cell(seed_ref, tbi * tb + tp, nb, n_blocks)
        x = jnp.where(valid, x_ref[tp], 0.0)  # zero ragged-edge lanes
        g = jnp.where(valid, g_ref[tp], 0.0)  # [1, BN]
        zp_col = _row_to_col(zp_ref[tp])

        # -- recompute forward, keeping relu + dropout masks per layer ------
        acts, rmasks, dmasks = _forward_stack(x, zp_col, k1T, mids, rate,
                                              cdtype)

        # -- backward through the output projection -------------------------
        # f32: Mosaic mis-lowers bf16 lane contractions vs a 1-row operand
        _acc(dkout_ref, _dot(acts[-1], g, 1, 1, jnp.float32))  # [H_L, 1]
        _acc(dbout_ref, jnp.sum(g, keepdims=True))  # [1, 1]
        dh = _dot(kout_ref[:], g, 1, 0, cdtype)  # [H_L, BN]

        # -- backward through the mid layers (reverse order) ----------------
        for i in range(n_mids - 1, -1, -1):
            kT, _b = mids[i]
            if rate > 0.0:
                dh = dh * dmasks[i + 1]
            dh_pre = dh * rmasks[i + 1]  # [H_{i+1}, BN]
            _acc(dmid_refs[2 * i], _dot(dh_pre, acts[i], 1, 1, cdtype))
            _acc(dmid_refs[2 * i + 1],
                 jnp.sum(dh_pre, axis=1, keepdims=True))
            dh = _dot(kT, dh_pre, 0, 0, cdtype)  # [H_i, BN]

        # -- backward through the first (split) layer -----------------------
        if rate > 0.0:
            dh = dh * dmasks[0]
        dh1_pre = dh * rmasks[0]  # [H1, BN]
        _acc(dk1T_ref, _dot(dh1_pre, x, 1, 1, cdtype))  # [H1, F]

        # dzp: per-PERIOD row of the (Tb, 1, H1) block, accumulated over the
        # inner (nb) grid dim. The [H1] row comes from a ones-contraction
        # (MXU) — cheaper than a sublane→lane transpose of the column sum.
        dzp_row = _dot(ones, dh1_pre, 1, 1, jnp.float32)  # [1, H1]

        @pl.when(nb == 0)
        def _(tp=tp, dzp_row=dzp_row):
            dzp_ref[tp] = dzp_row

        @pl.when(nb != 0)
        def _(tp=tp, dzp_row=dzp_row):
            dzp_ref[tp] = dzp_ref[tp] + dzp_row


def _dx_kernel(seed_ref, nvalid_ref, x_ref, zp_ref, k1T_ref, *rest,
               n_mids: int, rate: float, n_blocks: int, tb: int,
               cdtype=jnp.bfloat16):
    """Cotangent w.r.t. the panel itself (dx_t [T, F, N]).

    The panel is data, so this is traced but dead-code-eliminated in every
    training/eval path; it exists so `jax.grad` w.r.t. inputs stays correct
    for anyone differentiating through the features (e.g. sensitivities).
    """
    mid_refs = rest[: 2 * n_mids]
    kout_ref, g_ref, dx_ref = rest[2 * n_mids], rest[2 * n_mids + 1], rest[-1]
    tbi, nb = pl.program_id(0), pl.program_id(1)

    bn = x_ref.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
    valid = (lane + nb * bn) < nvalid_ref[0]
    mids = [(mid_refs[2 * i][:], mid_refs[2 * i + 1][:]) for i in range(n_mids)]

    for tp in range(tb):
        if rate > 0.0:
            _seed_cell(seed_ref, tbi * tb + tp, nb, n_blocks)
        x = jnp.where(valid, x_ref[tp], 0.0)
        g = jnp.where(valid, g_ref[tp], 0.0)
        zp_col = _row_to_col(zp_ref[tp])

        _, rmasks, dmasks = _forward_stack(x, zp_col, k1T_ref[:], mids, rate,
                                           cdtype)

        dh = _dot(kout_ref[:], g, 1, 0, cdtype)
        for i in range(n_mids - 1, -1, -1):
            if rate > 0.0:
                dh = dh * dmasks[i + 1]
            dh_pre = dh * rmasks[i + 1]
            dh = _dot(mids[i][0], dh_pre, 0, 0, cdtype)
        if rate > 0.0:
            dh = dh * dmasks[0]
        dh1_pre = dh * rmasks[0]
        dx_ref[tp] = _dot(k1T_ref[:], dh1_pre, 0, 0,
                          cdtype).astype(dx_ref.dtype)  # [F, BN]


def _specs(T: int, F: int, N: int, bn: int, tb: int, n_mids: int, h1: int):
    """Common (grid, in_specs) for the three kernels, minus per-kernel
    extras. The grid is (T//Tb, stock-blocks); every per-period operand
    carries Tb rows per cell."""
    n_blocks = -(-N // bn)
    grid = (T // tb, n_blocks)
    vmem = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # seed (1, 1)
        vmem((tb, F, bn), lambda t, nb: (t, 0, nb)),  # x_t
        vmem((tb, 1, h1), lambda t, nb: (t, 0, 0)),  # zp rows for the cell
        vmem(),  # k1T
    ]
    for _ in range(n_mids):
        in_specs += [vmem(), vmem()]  # kT_i, b_i
    in_specs.append(vmem())  # kout
    return grid, in_specs, vmem, n_blocks


def _fwd_call(static: Static, seed, x_t, zp3, k1T, mids, kout, bout):
    rate, bn, interpret, cdtype_name, tb = static
    cdtype = jnp.dtype(cdtype_name)
    T, F, N = x_t.shape
    h1 = k1T.shape[0]
    n_mids = len(mids)
    grid, in_specs, vmem, n_blocks = _specs(T, F, N, bn, tb, n_mids, h1)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))  # bout (1, 1)
    kernel = functools.partial(
        _fwd_kernel, n_mids=n_mids, rate=rate, n_blocks=n_blocks, tb=tb,
        cdtype=cdtype,
    )
    flat_mids = [a for kb in mids for a in kb]
    w3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=vmem((tb, 1, bn), lambda t, nb: (t, 0, nb)),
        out_shape=jax.ShapeDtypeStruct((T, 1, N), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(seed, x_t, zp3, k1T, *flat_mids, kout, bout)
    return w3[:, 0, :]


def _bwd_call(static: Static, seed, x_t, zp3, k1T, mids, kout, g):
    rate, bn, interpret, cdtype_name, tb = static
    cdtype = jnp.dtype(cdtype_name)
    T, F, N = x_t.shape
    h1 = k1T.shape[0]
    n_mids = len(mids)
    grid, in_specs, vmem, n_blocks = _specs(T, F, N, bn, tb, n_mids, h1)
    in_specs.insert(1, pl.BlockSpec(memory_space=pltpu.SMEM))  # nvalid (1,)
    in_specs.append(vmem((tb, 1, bn), lambda t, nb: (t, 0, nb)))  # g
    resident = lambda t, nb: (0, 0)
    out_specs = [
        vmem((tb, 1, h1), lambda t, nb: (t, 0, 0)),  # dzp, resident per cell
        vmem(k1T.shape, resident),
    ]
    out_shapes = [
        jax.ShapeDtypeStruct((T, 1, h1), jnp.float32),
        jax.ShapeDtypeStruct(k1T.shape, jnp.float32),
    ]
    for kT, b in mids:
        out_specs += [vmem(kT.shape, resident), vmem((kT.shape[0], 1), resident)]
        out_shapes += [
            jax.ShapeDtypeStruct(kT.shape, jnp.float32),
            jax.ShapeDtypeStruct((kT.shape[0], 1), jnp.float32),
        ]
    out_specs += [vmem(kout.shape, resident), vmem((1, 1), resident)]
    out_shapes += [
        jax.ShapeDtypeStruct(kout.shape, jnp.float32),
        jax.ShapeDtypeStruct((1, 1), jnp.float32),
    ]
    kernel = functools.partial(
        _bwd_kernel, n_mids=n_mids, rate=rate, n_blocks=n_blocks, tb=tb,
        cdtype=cdtype,
    )
    nvalid = jnp.asarray([N], jnp.int32)
    flat_mids = [a for kb in mids for a in kb]
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")  # sequential: accumulators
        ),
        interpret=interpret,
    )(seed, nvalid, x_t, zp3, k1T, *flat_mids, kout, g.reshape(T, 1, N))
    dzp, dk1T = outs[0][:, 0, :], outs[1]
    dmids = tuple(
        (outs[2 + 2 * i], outs[3 + 2 * i][:, 0]) for i in range(n_mids)
    )
    dkout, dbout = outs[2 + 2 * n_mids], outs[3 + 2 * n_mids]
    return dzp, dk1T, dmids, dkout, dbout


def _dx_call(static: Static, seed, x_t, zp3, k1T, mids, kout, g):
    rate, bn, interpret, cdtype_name, tb = static
    cdtype = jnp.dtype(cdtype_name)
    T, F, N = x_t.shape
    h1 = k1T.shape[0]
    n_mids = len(mids)
    grid, in_specs, vmem, n_blocks = _specs(T, F, N, bn, tb, n_mids, h1)
    in_specs.insert(1, pl.BlockSpec(memory_space=pltpu.SMEM))  # nvalid
    in_specs.append(vmem((tb, 1, bn), lambda t, nb: (t, 0, nb)))  # g
    kernel = functools.partial(
        _dx_kernel, n_mids=n_mids, rate=rate, n_blocks=n_blocks, tb=tb,
        cdtype=cdtype,
    )
    nvalid = jnp.asarray([N], jnp.int32)
    flat_mids = [a for kb in mids for a in kb]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=vmem((tb, F, bn), lambda t, nb: (t, 0, nb)),
        out_shape=jax.ShapeDtypeStruct((T, F, N), x_t.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(seed, nvalid, x_t, zp3, k1T, *flat_mids, kout, g.reshape(T, 1, N))


# ---------------------------------------------------------------------------
# Member-fused kernels: S models over ONE panel read
# ---------------------------------------------------------------------------
#
# vmap's default batching rule for pallas_call prepends a grid dimension, so
# an S-member ensemble re-reads the panel S times per pass — at the real
# shape the epoch is panel-read-bound, so S members cost ~S× one model
# (BENCH_r03: 6.24 ms/member-epoch ≈ the single-model epoch). These kernels
# instead keep ALL S members' weights resident in VMEM (S×12k params is
# nothing) and loop members over each resident panel tile: the panel is read
# ONCE per pass regardless of S. The loop is a static Python unroll (S is a
# trace-time constant), so Mosaic schedules the per-member matmuls back to
# back on the MXU while the next panel tile streams in.
#
# Wiring: vmap never sees pallas_call here. The single-member entry points
# bind custom JAX primitives whose registered batching rules dispatch to
# these member-fused kernels (exactly the mechanism pallas_call itself uses
# for its grid-prepend rule — and the only one that fires inside the
# custom_vjp backward under vmap(grad); jax.custom_batching.custom_vmap is
# silently bypassed there, measured on jax 0.9).
#
# Dropout streams are IDENTICAL to the serial single-member kernel: the same
# per-(member seed, grid cell) formula with the same block size, so a
# member-fused ensemble run is bit-identical to S serial runs even with
# dropout on (the batching rule keeps the single call's block_stocks unless
# the member working set would overflow VMEM).

_MEMBER_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def _member_block_stocks(bn: int, S: int, F: int, hidden: Sequence[int]) -> int:
    """Keep the single call's `bn` unless S members' blocks overflow VMEM.

    Per-stock bytes model (calibrated against a measured Mosaic scoped-vmem
    report: hidden=(64,64,64), S=4, bn=6784 peaked at 16.26 MB ≈ 2.4 kB per
    stock): the bf16 x tile double-buffered, the member backward's live
    acts/relu-masks/dropout-masks — THREE h-wide f32 rows per LAYER (this
    layer-count term is what the original model missed; the 3-hidden-layer
    sweep bucket overflowed the 16 MB scoped limit by 268 kB) — plus the
    chunk-stacked layer-1 rows and the S-wide w/g lane rows."""
    f_pad = -(-F // 8) * 8
    h = max(hidden) if hidden else 8
    h1 = hidden[0] if hidden else 8
    n_layers = max(len(hidden), 1)
    chunk = min(max(1, 128 // max(h1, 1)), S)
    per_stock = (4 * f_pad + 12 * n_layers * h + 4 * chunk * h1 + 8 * S + 32)
    fit = _MEMBER_VMEM_BUDGET_BYTES // per_stock
    fit = max(_LANE, (fit // _LANE) * _LANE)
    return min(bn, fit)


def _seed_member_cell(seed_ref, s: int, n_blocks: int):
    """Same stream formula as _seed_cell, per member s — bit-identical to a
    serial run of the single-member kernel with seed seed_ref[s, 0]."""
    t, nb = pl.program_id(0), pl.program_id(1)
    pltpu.prng_seed(
        seed_ref[s, 0]
        + (t * n_blocks + nb) * np.int32(2654435761 & 0x7FFFFFFF)
    )


def _stack_from_pre(h_pre, mids, rate: float, cdtype):
    """_forward_stack from a precomputed first pre-activation (the stacked
    member path computes layer 1 for ALL members in one matmul). Layer loop,
    relu/dropout order, and mask-draw order are identical to _forward_stack,
    so per-member dropout streams match the single-member kernel exactly."""
    acts, rmasks, dmasks = [], [], []
    for kT, b in [(None, None)] + list(mids):
        if kT is not None:
            h_pre = _dot(kT, acts[-1], 1, 0, cdtype) + b
        rmasks.append((h_pre > 0.0).astype(jnp.float32))
        h = jnp.maximum(h_pre, 0.0)
        if rate > 0.0:
            dm = _dropout_mask(h.shape, rate)
            h = h * dm
            dmasks.append(dm)
        acts.append(h)
    return acts, rmasks, dmasks


def _member_chunks(S: int, h1: int):
    """Member chunks whose stacked layer-1 rows fill the 128-row MXU.

    Stacking ALL S members at once would be fastest per-matmul but keeps an
    [S·H1, BN] f32 intermediate live — at S=9, H1=64, BN≈6.8k that alone is
    ~16 MB, over the v5e scoped-vmem limit. Chunks of 128//H1 members keep
    one full-row [128, BN] block live at a time: same MXU occupancy, bounded
    VMEM."""
    c = max(1, 128 // max(h1, 1))
    return [(s0, min(c, S - s0)) for s0 in range(0, S, c)]


def _fwd_kernel_members(seed_ref, x_ref, zpT_ref, k1Ts_ref, *rest, S: int,
                        h1: int, n_mids: int, rate: float, n_blocks: int,
                        cdtype=jnp.bfloat16):
    """One (t, stock-block) cell: the panel tile is read once; all S members'
    MLPs run on it back to back.

    Layer 1 is computed chunk-stacked — [C·H1, F] × [F, BN] with C·H1 = 128
    rows filling the MXU (a 64-row per-member matmul leaves half of it
    idle); stacked rows are bit-identical to per-member matmuls (same
    contraction order). zpT arrives period-leading [T, S, H1, 1] so the
    per-period bias is already a column: no in-kernel transpose."""
    *mid_refs, kout_ref, bout_ref, w_ref = rest
    x = x_ref[0]  # [F, BN] — shared by every member
    zp_cols = zpT_ref[0]  # (S, H1, 1)
    for s0, c in _member_chunks(S, h1):
        zp_chunk = zp_cols[s0:s0 + c].reshape(c * h1, 1)
        h1_pre = (_dot(k1Ts_ref[s0 * h1:(s0 + c) * h1], x, 1, 0, cdtype)
                  + zp_chunk)  # [C·H1, BN]
        for j in range(c):
            s = s0 + j
            if rate > 0.0:
                _seed_member_cell(seed_ref, s, n_blocks)
            mids = [(mid_refs[2 * i][s], mid_refs[2 * i + 1][s])
                    for i in range(n_mids)]
            acts, _, _ = _stack_from_pre(
                h1_pre[j * h1:(j + 1) * h1], mids, rate, cdtype)
            w_ref[s, 0] = (_dot(kout_ref[s], acts[-1], 0, 0, cdtype)
                           + bout_ref[s, 0])


def _bwd_kernel_members(seed_ref, nvalid_ref, x_ref, zpT_ref, k1Ts_ref,
                        *rest, S: int, h1: int, n_mids: int, rate: float,
                        n_blocks: int, cdtype=jnp.bfloat16):
    """Member-looped recompute-and-accumulate backward (cf. _bwd_kernel).

    Chunk-stacked member matmuls where rows concatenate cleanly (chunks of
    128//H1 members — see _member_chunks for the VMEM bound): the layer-1
    recompute, the layer-1 weight gradient ([C·H1, BN] ⋅ [F, BN] →
    [C·H1, F]), and the per-period bias gradient (lane row-sum columns).
    Mid/output layers stay per-member (block-diagonal across members —
    stacking would mix them)."""
    mid_refs = rest[: 2 * n_mids]
    kout_ref, g_ref = rest[2 * n_mids], rest[2 * n_mids + 1]
    out_refs = rest[2 * n_mids + 2:]
    dzpT_ref, dk1Ts_ref = out_refs[0], out_refs[1]
    dmid_refs = out_refs[2: 2 + 2 * n_mids]
    dkout_ref, dbout_ref = out_refs[2 + 2 * n_mids], out_refs[3 + 2 * n_mids]

    t, nb = pl.program_id(0), pl.program_id(1)
    first = (t == 0) & (nb == 0)

    bn = x_ref.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
    valid = (lane + nb * bn) < nvalid_ref[0]
    x = jnp.where(valid, x_ref[0], 0.0)  # shared across members

    def _accm(ref, s, val, pred):
        @pl.when(pred)
        def _():
            ref[s] = val

        @pl.when(jnp.logical_not(pred))
        def _():
            ref[s] = ref[s] + val

    def _acc_rows(ref, r0, r1, val, pred):
        @pl.when(pred)
        def _():
            ref[r0:r1] = val

        @pl.when(jnp.logical_not(pred))
        def _():
            ref[r0:r1] = ref[r0:r1] + val

    zp_cols = zpT_ref[0]  # (S, H1, 1)
    ones = jnp.ones((1, bn), jnp.float32)
    for s0, c in _member_chunks(S, h1):
        zp_chunk = zp_cols[s0:s0 + c].reshape(c * h1, 1)
        h1_pre = (_dot(k1Ts_ref[s0 * h1:(s0 + c) * h1], x, 1, 0, cdtype)
                  + zp_chunk)  # [C·H1, BN]
        dh1_slices = []
        for j in range(c):
            s = s0 + j
            if rate > 0.0:
                _seed_member_cell(seed_ref, s, n_blocks)
            g = jnp.where(valid, g_ref[s, 0], 0.0)  # [1, BN]
            mids = [(mid_refs[2 * i][s], mid_refs[2 * i + 1][s])
                    for i in range(n_mids)]

            acts, rmasks, dmasks = _stack_from_pre(
                h1_pre[j * h1:(j + 1) * h1], mids, rate, cdtype)

            # f32: Mosaic mis-lowers bf16 lane contractions vs a 1-row op
            _accm(dkout_ref, s, _dot(acts[-1], g, 1, 1, jnp.float32), first)
            _accm(dbout_ref, s, jnp.sum(g, keepdims=True), first)
            dh = _dot(kout_ref[s], g, 1, 0, cdtype)  # [H_L, BN]

            for i in range(n_mids - 1, -1, -1):
                kT, _b = mids[i]
                if rate > 0.0:
                    dh = dh * dmasks[i + 1]
                dh_pre = dh * rmasks[i + 1]
                _accm(dmid_refs[2 * i], s,
                      _dot(dh_pre, acts[i], 1, 1, cdtype), first)
                _accm(dmid_refs[2 * i + 1], s,
                      jnp.sum(dh_pre, axis=1, keepdims=True), first)
                dh = _dot(kT, dh_pre, 0, 0, cdtype)

            if rate > 0.0:
                dh = dh * dmasks[0]
            dh1_slices.append(dh * rmasks[0])  # [H1, BN]

        dh1_chunk = (jnp.concatenate(dh1_slices, axis=0)
                     if c > 1 else dh1_slices[0])  # [C·H1, BN]
        _acc_rows(dk1Ts_ref, s0 * h1, (s0 + c) * h1,
                  _dot(dh1_chunk, x, 1, 1, cdtype), first)
        # per-period bias gradient: lane row-sum column, period-leading block
        dzp_chunk = (_dot(dh1_chunk, ones, 1, 1, jnp.float32)
                     .reshape(c, h1, 1))

        @pl.when(nb == 0)
        def _(s0=s0, c=c, dzp_chunk=dzp_chunk):
            dzpT_ref[0, s0:s0 + c] = dzp_chunk

        @pl.when(nb != 0)
        def _(s0=s0, c=c, dzp_chunk=dzp_chunk):
            dzpT_ref[0, s0:s0 + c] = dzpT_ref[0, s0:s0 + c] + dzp_chunk


def _fwd_call_members(static: Static, S: int, seed, x_t, zpT, k1Ts, mids,
                      kout, bout):
    """seed [S,1] i32, x_t [T,F,N], zpT [T,S,H1,1] (period-leading columns),
    k1Ts [S·H1,F] (member-stacked), mids ([S,H,Hin],[S,H,1])…,
    kout [S,HL,1], bout [S,1] → w4 [S,T,1,N]."""
    rate, bn, interpret, cdtype_name, _tb = static  # members run Tb=1 semantics
    cdtype = jnp.dtype(cdtype_name)
    T, F, N = x_t.shape
    h1 = k1Ts.shape[0] // S
    n_mids = len(mids)
    bn = _member_block_stocks(bn, S, F, [h1] + [k.shape[1] for k, _ in mids])
    n_blocks = -(-N // bn)
    grid = (T, n_blocks)
    vmem = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # seed (S, 1)
        vmem((1, F, bn), lambda t, nb: (t, 0, nb)),  # x_t
        # period-LEADING so the block's last two dims equal the array's
        # (H1, 1) — a (S,H1,1)-of-[S,H1,T] block would slice the lane dim
        # by 1, which the TPU lowering rejects
        vmem((1, S, h1, 1), lambda t, nb: (t, 0, 0, 0)),  # zpT columns
        vmem(),  # k1Ts (all members resident, stacked)
    ]
    for _ in range(n_mids):
        in_specs += [vmem(), vmem()]
    in_specs += [vmem(), pl.BlockSpec(memory_space=pltpu.SMEM)]  # kout, bout
    kernel = functools.partial(
        _fwd_kernel_members, S=S, h1=h1, n_mids=n_mids, rate=rate,
        n_blocks=n_blocks, cdtype=cdtype,
    )
    flat_mids = [a for kb in mids for a in kb]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=vmem((S, 1, 1, bn), lambda t, nb: (0, t, 0, nb)),
        out_shape=jax.ShapeDtypeStruct((S, T, 1, N), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(seed, x_t, zpT, k1Ts, *flat_mids, kout, bout)


def _bwd_call_members(static: Static, S: int, seed, x_t, zpT, k1Ts, mids,
                      kout, g4):
    """g4 [S,T,1,N] → (dzpT [T,S,H1,1], dk1Ts [S·H1,F], (dkT,db)…,
    dkout [S,HL,1], dbout [S,1,1])."""
    rate, bn, interpret, cdtype_name, _tb = static  # members run Tb=1 semantics
    cdtype = jnp.dtype(cdtype_name)
    T, F, N = x_t.shape
    h1 = k1Ts.shape[0] // S
    n_mids = len(mids)
    bn = _member_block_stocks(bn, S, F, [h1] + [k.shape[1] for k, _ in mids])
    n_blocks = -(-N // bn)
    grid = (T, n_blocks)
    vmem = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # seed (S, 1)
        pl.BlockSpec(memory_space=pltpu.SMEM),  # nvalid (1,)
        vmem((1, F, bn), lambda t, nb: (t, 0, nb)),  # x_t
        vmem((1, S, h1, 1), lambda t, nb: (t, 0, 0, 0)),  # zpT columns
        vmem(),  # k1Ts
    ]
    for _ in range(n_mids):
        in_specs += [vmem(), vmem()]
    in_specs += [
        vmem(),  # kout
        vmem((S, 1, 1, bn), lambda t, nb: (0, t, 0, nb)),  # g
    ]
    resident3 = lambda t, nb: (0, 0, 0)
    out_specs = [
        vmem((1, S, h1, 1), lambda t, nb: (t, 0, 0, 0)),  # dzpT per t
        vmem(k1Ts.shape, lambda t, nb: (0, 0)),  # dk1Ts resident, stacked
    ]
    out_shapes = [
        jax.ShapeDtypeStruct((T, S, h1, 1), jnp.float32),
        jax.ShapeDtypeStruct(k1Ts.shape, jnp.float32),
    ]
    for kT, b in mids:
        out_specs += [vmem(kT.shape, resident3), vmem(b.shape, resident3)]
        out_shapes += [jax.ShapeDtypeStruct(kT.shape, jnp.float32),
                       jax.ShapeDtypeStruct(b.shape, jnp.float32)]
    out_specs += [vmem(kout.shape, resident3),
                  vmem((S, 1, 1), resident3)]
    out_shapes += [jax.ShapeDtypeStruct(kout.shape, jnp.float32),
                   jax.ShapeDtypeStruct((S, 1, 1), jnp.float32)]
    kernel = functools.partial(
        _bwd_kernel_members, S=S, h1=h1, n_mids=n_mids, rate=rate,
        n_blocks=n_blocks, cdtype=cdtype,
    )
    nvalid = jnp.asarray([N], jnp.int32)
    flat_mids = [a for kb in mids for a in kb]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")  # accumulators
        ),
        interpret=interpret,
    )(seed, nvalid, x_t, zpT, k1Ts, *flat_mids, kout, g4)


# ---------------------------------------------------------------------------
# Primitives: single-member calls with member-fused batching rules
# ---------------------------------------------------------------------------


def _flat_to_mids(flat, n_mids: int):
    return tuple((flat[2 * i], flat[2 * i + 1]) for i in range(n_mids))


def _bdim_to_front(a, d, S: int):
    if d is batching.not_mapped:
        return jnp.broadcast_to(a[None], (S,) + a.shape)
    return jnp.moveaxis(a, d, 0)


def _seq_fallback(fn, S: int, args, dims):
    """Sequential lax.map fallback (used only when the PANEL itself carries
    the batch axis — not an ensemble/sweep pattern; correctness backstop)."""
    stacked = tuple(_bdim_to_front(a, d, S) for a, d in zip(args, dims))
    return jax.lax.map(lambda xs: fn(*xs), stacked)


def _ffn_fwd_fn(seed, x_t, zp3, k1T, *rest, static: Static, n_mids: int):
    mids = _flat_to_mids(rest[:2 * n_mids], n_mids)
    kout, bout2 = rest[2 * n_mids], rest[2 * n_mids + 1]
    return _fwd_call(static, seed, x_t, zp3, k1T, mids, kout, bout2)


def _ffn_bwd_fn(seed, x_t, zp3, k1T, *rest, static: Static, n_mids: int):
    mids = _flat_to_mids(rest[:2 * n_mids], n_mids)
    kout, g = rest[2 * n_mids], rest[2 * n_mids + 1]
    dzp, dk1T, dmids, dkout, dbout = _bwd_call(
        static, seed, x_t, zp3, k1T, mids, kout, g
    )
    flat_dmids = [a for kb in dmids for a in kb]
    return (dzp, dk1T, *flat_dmids, dkout, dbout)


def _ffn_dx_fn(seed, x_t, zp3, k1T, *rest, static: Static, n_mids: int):
    mids = _flat_to_mids(rest[:2 * n_mids], n_mids)
    kout, g = rest[2 * n_mids], rest[2 * n_mids + 1]
    return _dx_call(static, seed, x_t, zp3, k1T, mids, kout, g)


def _make_prim(name, fn, multiple_results):
    prim = jex_core.Primitive(name)
    prim.multiple_results = multiple_results
    prim.def_impl(fn)

    def abstract_eval(*avals, **params):
        structs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in avals]
        out = jax.eval_shape(functools.partial(fn, **params), *structs)
        if multiple_results:
            return [jax.core.ShapedArray(o.shape, o.dtype) for o in out]
        return jax.core.ShapedArray(out.shape, out.dtype)

    prim.def_abstract_eval(abstract_eval)
    mlir.register_lowering(
        prim, mlir.lower_fun(fn, multiple_results=multiple_results)
    )
    return prim


_ffn_fwd_p = _make_prim("dlap_ffn_fwd", _ffn_fwd_fn, False)
_ffn_bwd_p = _make_prim("dlap_ffn_bwd", _ffn_bwd_fn, True)
_ffn_dx_p = _make_prim("dlap_ffn_dx", _ffn_dx_fn, False)


def _ffn_member_args(args, dims, S: int, n_mids: int):
    """The batched member-carried operands in the member kernels' layouts:
    seed [S,1], period-leading bias columns zpT [T,S,H1,1], member-stacked
    k1Ts [S·H1,F], mids, kout, and the final operand (bout2 in the forward,
    g in the backward). Batches ONLY the member-carried args — broadcasting
    the (unbatched, shared) panel would materialize S copies of the largest
    array."""
    x_t = args[1]
    b = [_bdim_to_front(a, d, S) for a, d in zip(args[2:], dims[2:])]
    seed_b = _bdim_to_front(args[0], dims[0], S).reshape(S, 1)
    h1 = b[1].shape[1]
    zpT = jnp.transpose(b[0][:, :, 0, :], (1, 0, 2))[..., None]
    k1Ts = b[1].reshape(S * h1, x_t.shape[1])
    mids_b = _flat_to_mids(b[2:2 + 2 * n_mids], n_mids)
    kout_b = b[2 + 2 * n_mids]
    last = b[3 + 2 * n_mids]
    return x_t, seed_b, zpT, k1Ts, mids_b, kout_b, last


def _ffn_fwd_batch(args, dims, *, static: Static, n_mids: int):
    S = next(a.shape[d] for a, d in zip(args, dims)
             if d is not batching.not_mapped)
    if dims[1] is not batching.not_mapped:  # panel batched: no shared read
        out = _seq_fallback(
            functools.partial(_ffn_fwd_fn, static=static, n_mids=n_mids),
            S, args, dims)
        return out, 0
    x_t, seed_b, zpT, k1Ts, mids_b, kout_b, bout2 = _ffn_member_args(
        args, dims, S, n_mids)
    out = _fwd_call_members(static, S, seed_b, x_t, zpT, k1Ts, mids_b,
                            kout_b, bout2.reshape(S, 1))
    return out[:, :, 0, :], 0  # [S, T, N] — matches the single call's [T, N]


def _ffn_bwd_batch(args, dims, *, static: Static, n_mids: int):
    S = next(a.shape[d] for a, d in zip(args, dims)
             if d is not batching.not_mapped)
    if dims[1] is not batching.not_mapped:
        outs = _seq_fallback(
            functools.partial(_ffn_bwd_fn, static=static, n_mids=n_mids),
            S, args, dims)
        return outs, (0,) * len(outs)
    x_t, seed_b, zpT, k1Ts, mids_b, kout_b, g = _ffn_member_args(
        args, dims, S, n_mids)
    h1 = zpT.shape[2]
    g4 = g.reshape(S, x_t.shape[0], 1, x_t.shape[2])
    raw = _bwd_call_members(static, S, seed_b, x_t, zpT, k1Ts, mids_b,
                            kout_b, g4)
    # match the single call's output ranks, with the member axis leading
    outs = [
        jnp.transpose(raw[0][..., 0], (1, 0, 2)),  # [T,S,H1,1] → [S,T,H1]
        raw[1].reshape(S, h1, x_t.shape[1]),  # dk1Ts stacked → [S,H1,F]
    ]
    for i in range(n_mids):
        outs += [raw[2 + 2 * i], raw[3 + 2 * i][:, :, 0]]  # dkT, db [S,H]
    outs += [raw[2 + 2 * n_mids], raw[3 + 2 * n_mids]]  # dkout, dbout
    return outs, (0,) * len(outs)


def _ffn_dx_batch(args, dims, *, static: Static, n_mids: int):
    # dx is the panel cotangent — dead code in every training path (the
    # panel is data); a sequential fallback keeps it correct if ever used
    S = next(a.shape[d] for a, d in zip(args, dims)
             if d is not batching.not_mapped)
    out = _seq_fallback(
        functools.partial(_ffn_dx_fn, static=static, n_mids=n_mids),
        S, args, dims)
    return out, 0


batching.primitive_batchers[_ffn_fwd_p] = _ffn_fwd_batch
batching.primitive_batchers[_ffn_bwd_p] = _ffn_bwd_batch
batching.primitive_batchers[_ffn_dx_p] = _ffn_dx_batch


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_ffn(static: Static, seed, x_t, zp, k1T, mids, kout, bout):
    # bind via the primitive so vmap takes the member-fused batching rule
    zp3 = zp[:, None, :]
    bout2 = bout.reshape(1, 1)
    mids2 = tuple((kT, b.reshape(-1, 1)) for kT, b in mids)
    flat = [a for kb in mids2 for a in kb]
    return _ffn_fwd_p.bind(seed, x_t, zp3, k1T, *flat, kout, bout2,
                           static=static, n_mids=len(mids2))


def _fused_ffn_fwd(static, seed, x_t, zp, k1T, mids, kout, bout):
    out = _fused_ffn(static, seed, x_t, zp, k1T, mids, kout, bout)
    return out, (seed, x_t, zp, k1T, mids, kout)


def _fused_ffn_bwd(static, res, g):
    seed, x_t, zp, k1T, mids, kout = res
    zp3 = zp[:, None, :]
    mids2 = tuple((kT, b.reshape(-1, 1)) for kT, b in mids)
    flat = [a for kb in mids2 for a in kb]
    n = len(mids2)
    outs = _ffn_bwd_p.bind(seed, x_t, zp3, k1T, *flat, kout, g,
                           static=static, n_mids=n)
    dzp, dk1T = outs[0], outs[1]
    dmids = tuple((outs[2 + 2 * i], outs[3 + 2 * i]) for i in range(n))
    dkout, dbout = outs[2 + 2 * n], outs[3 + 2 * n]
    # Panel cotangent: traced but DCE'd whenever x isn't differentiated
    # (always, in training — the panel is data).
    dx_t = _ffn_dx_p.bind(seed, x_t, zp3, k1T, *flat, kout, g,
                          static=static, n_mids=n)
    d_seed = np.zeros(seed.shape, jax.dtypes.float0)
    return (d_seed, dx_t, dzp, dk1T, dmids, dkout, dbout.reshape(1))


_fused_ffn.defvjp(_fused_ffn_fwd, _fused_ffn_bwd)


def fused_sdf_ffn(
    x_t: jnp.ndarray,  # [T, F, N] panel, feature-major
    zp: jnp.ndarray,  # [T, H1] per-period bias (macro @ K1_macro + b1)
    layers: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
    # layers[0] = (k1_stock [F, H1], None-bias-folded-into-zp) handled by caller:
    # pass k1_stock as layers[0][0]; subsequent (k_i [H_in, H_i], b_i [H_i]).
    out_kernel: jnp.ndarray,  # [H_L, 1]
    out_bias: jnp.ndarray,  # [1]
    *,
    dropout_rate: float = 0.0,
    seed: Any = None,
    block_stocks: int = 0,
    interpret: bool = False,
    compute_dtype: str = "bfloat16",
) -> jnp.ndarray:
    """Fused MLP over the panel: returns raw weights [T, N] (pre-mask).

    Gradients flow to zp (and through it to the macro path), to every kernel/
    bias, and — if requested — to the panel itself; the panel cotangent kernel
    is dead-code-eliminated otherwise.
    """
    k1_stock = layers[0][0]
    mids = tuple((kT.T, b) for kT, b in layers[1:])  # kernel wants [H_out, H_in]
    T, F, N = x_t.shape
    hidden = [k1_stock.shape[1]] + [k.shape[1] for k, _ in layers[1:]]
    itemsize = jnp.dtype(x_t.dtype).itemsize
    if block_stocks:
        bn, tb = block_stocks, choose_period_block(T, F, block_stocks,
                                                   itemsize)
    else:
        bn, tb = choose_blocks(T, N, F, hidden, itemsize)
    # (1, 1): rank-2 so a vmapped (batched) seed keeps its last two dims
    # intact under Pallas's batching rule (a (S, 1) SMEM operand would fail
    # the last-two-dims block constraint; (S, 1, 1) squeezes cleanly).
    if seed is None:
        seed = jnp.zeros((1, 1), jnp.int32)
    else:
        seed = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    static = (float(dropout_rate), int(bn), bool(interpret),
              str(compute_dtype), int(tb))
    return _fused_ffn(static, seed, x_t, zp, k1_stock.T, mids, out_kernel, out_bias)


# ---------------------------------------------------------------------------
# shard_map wrapper: the kernel over a stock-sharded panel
# ---------------------------------------------------------------------------


def fused_sdf_ffn_sharded(
    x_t: jnp.ndarray,  # [T, F, N] global, sharded along N
    zp: jnp.ndarray,
    layers,
    out_kernel: jnp.ndarray,
    out_bias: jnp.ndarray,
    mesh,
    axis_name: str,
    *,
    dropout_rate: float = 0.0,
    seed: Any = None,
    block_stocks: int = 0,
    interpret: bool = False,
    compute_dtype: str = "bfloat16",
) -> jnp.ndarray:
    """Run the fused kernel per-device on a stock-sharded panel.

    The MLP is row-local in stocks, so each device runs the kernel on its
    local N/D shard; shard_map's transpose rule inserts the psums that give
    replicated parameters their full gradients. The dropout stream folds in
    the device's axis index so shards draw independent masks.
    """
    from jax.sharding import PartitionSpec as P

    if seed is None:
        seed = jnp.zeros((), jnp.int32)
    seed = jnp.asarray(seed, jnp.int32).reshape(())

    def local(x_l, zp_, layers_, ko, bo, seed_):
        idx = jax.lax.axis_index(axis_name)
        return fused_sdf_ffn(
            x_l, zp_, layers_, ko, bo,
            dropout_rate=dropout_rate,
            seed=seed_ + idx * jnp.int32(40507),
            block_stocks=block_stocks,
            interpret=interpret,
            compute_dtype=compute_dtype,
        )

    rep = jax.tree.map(lambda _: P(), (zp, layers, out_kernel, out_bias, seed))
    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, None, axis_name),) + rep,
        out_specs=P(None, axis_name),
        # pallas_call's out_shape carries no varying-mesh-axes annotation in
        # this JAX version, so the vma checker cannot type the body
        check_vma=False,
    )
    return fn(x_t, zp, layers, out_kernel, out_bias, seed)
