from .gan import GAN
from .networks import AssetPricingModule, MomentNet, SDFNet, SimpleSDF
from .recurrent import TorchLSTM
