from .losses import conditional_loss, portfolio_returns, residual_loss, unconditional_loss
from .metrics import (
    cross_sectional_r2,
    explained_variation,
    factor_betas,
    max_drawdown,
    normalize_weights_abs,
    sharpe,
    sharpe_monitor,
)
