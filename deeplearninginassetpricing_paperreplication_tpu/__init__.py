"""TPU-native deep-learning asset-pricing framework.

A from-scratch JAX/XLA rebuild of the capabilities of
``omroot/DeepLearningInAssetPricing_PaperReplication`` (Chen–Pelger–Zhu
GAN-SDF): panel data core (+ native C++ codec), synthetic generator,
torch-parameterized Flax SDF/Moment networks with a fused Pallas FFN
execution route, fused moment-condition losses, the compiled on-device
3-phase trainer (``training/``), the joint 1-phase trainer, and the
distribution layer (``parallel/``: stock-axis GSPMD, vmapped ensembles and
the 384-config sweep, time-sharded sequence parallelism, multi-host DCN x
ICI meshes).

Public API mirrors the reference's ``src/__init__.py`` exports where a
counterpart exists.
"""

__version__ = "0.1.0"

from .data.panel import PanelDataset, load_panel, load_splits
from .data.pipeline import (
    StartupPipeline,
    load_splits_cached,
    load_splits_chunked,
    stream_batch,
    stream_batch_sharded,
)
from .data.synthetic import generate_all_splits, generate_dataset
from .models.gan import GAN
from .models.networks import AssetPricingModule, MomentNet, SDFNet, SimpleSDF
from .ops.losses import (
    conditional_loss,
    portfolio_returns,
    residual_loss,
    unconditional_loss,
)
from .ops.metrics import max_drawdown, normalize_weights_abs, sharpe
from .training.joint import joint_train, train_simple_sdf
from .training.trainer import Trainer, train_3phase
from .utils.config import ExecutionConfig, GANConfig, TrainConfig

__all__ = [
    "PanelDataset",
    "load_panel",
    "load_splits",
    "load_splits_cached",
    "load_splits_chunked",
    "StartupPipeline",
    "stream_batch",
    "stream_batch_sharded",
    "generate_all_splits",
    "generate_dataset",
    "GAN",
    "AssetPricingModule",
    "SDFNet",
    "MomentNet",
    "SimpleSDF",
    "GANConfig",
    "TrainConfig",
    "ExecutionConfig",
    "Trainer",
    "train_3phase",
    "joint_train",
    "train_simple_sdf",
    "conditional_loss",
    "unconditional_loss",
    "residual_loss",
    "portfolio_returns",
    "sharpe",
    "max_drawdown",
    "normalize_weights_abs",
    "__version__",
]
