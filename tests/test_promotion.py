"""Checkpoint promotion control plane tier-1 suite (CPU, loopback only).

Covers the ISSUE 9 acceptance criteria:
  * promotion is crash-consistent: a kill at EVERY fault site inside
    ``promote()`` leaves a pointer that parses, digest-verifies, and names
    either the old or the new generation — never a torn one;
  * corrupt, NaN-weights, and regressed-Sharpe candidates are rejected by
    the gate (and a candidate torn AFTER promotion is rolled back by the
    fleet's health-gated roll instead of half-swapping);
  * a supervised 2-replica fleet under open-loop load completes
    promote → rolling reload with ZERO unserved requests and both replicas
    converged on the promoted generation — including a replica SIGKILLed
    mid-reload that is restarted by its supervisor and converges to the
    pointer on boot;
  * ``InferenceEngine.reload()`` on a torn member falls back a checkpoint
    generation and keeps serving the old params bit-identically;
  * rolling refit buckets resume from the ledger after a worker kill with
    zero retrains and byte-identical candidate checkpoints;
plus the report CLI's promotion section, the BENCH_PROMOTION.json bars,
and the ruff lint gate over the new modules.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
from deeplearninginassetpricing_paperreplication_tpu.observability import (
    EventLog,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
    format_summary,
    load_run,
    summarize_run,
)
from deeplearninginassetpricing_paperreplication_tpu.reliability.promotion import (
    GateRejection,
    PromotionError,
    promote,
    read_pointer,
    rollback,
    verify_pointer_members,
    write_pointer,
)
from deeplearninginassetpricing_paperreplication_tpu.serving import (
    InferenceEngine,
    InferenceRequest,
    ServingService,
    pick_free_port,
    run_loadgen,
    server_child_argv,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.fleet import (
    ReplicaFleet,
    RollingUpdater,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.loadgen import (
    binary_payload_bytes,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.server import (
    BINARY_CONTENT_TYPE,
    build_arg_parser,
)
from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
    save_params,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
    GANConfig,
)

REPO = Path(__file__).resolve().parents[1]
PKG = "deeplearninginassetpricing_paperreplication_tpu"

T, N, F, M = 10, 32, 10, 6


def _make_cfg(**overrides):
    base = dict(macro_feature_dim=M, individual_feature_dim=F,
                hidden_dim=(8, 8), num_units_rnn=(4,))
    base.update(overrides)
    return GANConfig(**base)


def _write_member(d: Path, cfg: GANConfig, seed: int, nan: bool = False):
    d.mkdir(parents=True, exist_ok=True)
    cfg.save(d / "config.json")
    params = GAN(cfg).init(jax.random.key(seed))
    if nan:
        params = jax.tree.map(lambda x: x * np.nan, params)
    save_params(d / "best_model_sharpe.msgpack", params)
    return str(d)


@pytest.fixture(scope="module")
def gate_cfg():
    return _make_cfg()


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(11)
    return {
        "macro": rng.standard_normal((T, M)).astype(np.float32),
        "individual": rng.standard_normal((T, N, F)).astype(np.float32),
        "returns": (rng.standard_normal((T, N)) * 0.05).astype(np.float32),
        "mask": (rng.random((T, N)) > 0.1).astype(np.float32),
    }


def _members(root: Path, cfg, seeds):
    return [_write_member(root / f"m{s}", cfg, s) for s in seeds]


# --------------------------------------------------------------------------
# pointer mechanics: atomic advance, history, rollback
# --------------------------------------------------------------------------


def test_promote_advances_pointer_with_history_and_rollback(
        tmp_path, gate_cfg, panel):
    ctl = tmp_path / "ctl"
    v1 = _members(tmp_path / "v1", gate_cfg, (1, 2))
    v2 = _members(tmp_path / "v2", gate_cfg, (11, 12))

    with pytest.raises(PromotionError):
        rollback(ctl)  # nothing to roll back to yet
    assert read_pointer(ctl) is None

    p1 = promote(ctl, v1, valid_batch=panel, source="v1")
    assert p1["generation"] == 1 and p1["history"] == []
    assert p1["valid_sharpe"] is not None and np.isfinite(p1["valid_sharpe"])
    # every member's exact artifact digest is recorded for reload-time
    # verification
    assert len(p1["members"]) == 2
    assert verify_pointer_members(p1) == []

    p2 = promote(ctl, v2, valid_batch=panel, source="v2",
                 sharpe_tolerance=100.0)
    assert p2["generation"] == 2
    assert [h["source"] for h in p2["history"]] == ["v1"]
    assert p2["params_fingerprint"] != p1["params_fingerprint"]

    # the pointer artifact digest-verifies on read
    on_disk = read_pointer(ctl)
    assert on_disk["generation"] == 2
    assert on_disk["checkpoint_dirs"] == v2

    p3 = rollback(ctl, reason="test regression")
    assert p3["generation"] == 3
    assert p3["rolled_back_from"] == 2
    assert p3["params_fingerprint"] == p1["params_fingerprint"]
    assert p3["checkpoint_dirs"] == v1
    # the bad head joins the audit trail
    assert [h["source"] for h in p3["history"]] == ["v2", "v1"]

    with pytest.raises(PromotionError):
        rollback(tmp_path / "empty")


def test_gate_rejects_corrupt_nan_regressed_and_mismatched(
        tmp_path, gate_cfg, panel):
    ctl = tmp_path / "ctl"
    v1 = _members(tmp_path / "v1", gate_cfg, (1, 2))
    promote(ctl, v1, valid_batch=panel, source="v1")
    incumbent = read_pointer(ctl)

    # corrupt candidate: artifact bytes no longer match the sidecar
    bad = _members(tmp_path / "bad", gate_cfg, (21, 22))
    art = Path(bad[0]) / "best_model_sharpe.msgpack"
    art.write_bytes(art.read_bytes()[: art.stat().st_size // 2])
    with pytest.raises(GateRejection) as e:
        promote(ctl, bad, source="bad")
    assert e.value.reason == "digest_mismatch"

    # NaN-weights candidate
    nan = [_write_member(tmp_path / "nan" / "m1", gate_cfg, 31, nan=True),
           _write_member(tmp_path / "nan" / "m2", gate_cfg, 32, nan=True)]
    with pytest.raises(GateRejection) as e:
        promote(ctl, nan, source="nan")
    assert e.value.reason == "nonfinite_params"

    # regressed-Sharpe candidate: fake an incumbent with a huge Sharpe so
    # any real candidate trails it past the tolerance
    head = {k: incumbent[k] for k in incumbent
            if k not in ("kind", "generation", "history")}
    head["valid_sharpe"] = 999.0
    write_pointer(ctl, head)
    good = _members(tmp_path / "good", gate_cfg, (41, 42))
    with pytest.raises(GateRejection) as e:
        promote(ctl, good, valid_batch=panel, source="good",
                sharpe_tolerance=0.05)
    assert e.value.reason == "sharpe_regression"
    # tolerance None disables the regression gate
    promote(ctl, good, valid_batch=panel, source="good",
            sharpe_tolerance=None)

    # architecture mismatch against the serving config
    other = _members(tmp_path / "other", _make_cfg(hidden_dim=(16,)),
                     (51, 52))
    with pytest.raises(GateRejection) as e:
        promote(ctl, other, source="other")
    assert e.value.reason == "architecture_mismatch"

    # missing candidate
    with pytest.raises(GateRejection) as e:
        promote(ctl, [str(tmp_path / "nowhere")], source="missing")
    assert e.value.reason == "config_unreadable"

    # the pointer never moved past the explicit promotions
    final = read_pointer(ctl)
    assert final["source"] == "good"


def test_rejections_and_advances_are_countered(tmp_path, gate_cfg, panel):
    ctl = tmp_path / "ctl"
    run_dir = tmp_path / "run"
    events = EventLog(run_dir)
    v1 = _members(tmp_path / "v1", gate_cfg, (1,))
    v2 = _members(tmp_path / "v2", gate_cfg, (3,))
    promote(ctl, v1, source="v1", events=events)
    promote(ctl, v2, source="v2", sharpe_tolerance=None, events=events)
    bad = _members(tmp_path / "bad", gate_cfg, (2,))
    (Path(bad[0]) / "best_model_sharpe.msgpack").write_bytes(b"torn")
    with pytest.raises(GateRejection):
        promote(ctl, bad, source="bad", events=events)
    rollback(ctl, reason="r", events=events)
    events.close()
    rows = [json.loads(line) for line in
            (run_dir / "events.jsonl").read_text().splitlines()]
    names = [r["name"] for r in rows if r.get("kind") == "counter"]
    assert "promote/advance" in names
    assert "promote/reject" in names
    assert "promote/rollback" in names


# --------------------------------------------------------------------------
# crash consistency: kill at every fault site inside promote()
# --------------------------------------------------------------------------


PROMOTE_KILL_SITES = [
    ("promote/validate", None),
    ("promote/write", "serving_current"),
    ("checkpoint/save", "serving_current"),
    ("checkpoint/saved", "serving_current"),
]


@pytest.mark.parametrize("site,match", PROMOTE_KILL_SITES,
                         ids=[s for s, _ in PROMOTE_KILL_SITES])
def test_pointer_crash_consistent_at_every_site(
        tmp_path, gate_cfg, site, match):
    """SIGKILL the promote CLI at each fault site: the pointer afterwards
    always parses, digest-verifies, and names either the old or the new
    generation — never a torn state."""
    ctl = tmp_path / "ctl"
    v1 = _members(tmp_path / "v1", gate_cfg, (1,))
    v2 = _members(tmp_path / "v2", gate_cfg, (2,))
    old = promote(ctl, v1, source="v1")

    plan = [{"site": site, "action": "kill"}]
    if match:
        plan[0]["match"] = match
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLAP_FAULT_PLAN=json.dumps(plan),
               DLAP_FAULT_STATE=str(tmp_path / "fault_state.json"),
               DLAP_FAULT_EVENTS=str(tmp_path / "fault_events.jsonl"))
    proc = subprocess.run(
        [sys.executable, "-m", f"{PKG}.reliability.promotion", "promote",
         "--root", str(ctl), "--candidates", *v2,
         "--source", "v2", "--sharpe_tolerance", "-1"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert proc.returncode != 0, "the planned kill must have fired"
    assert (tmp_path / "fault_events.jsonl").exists()

    pointer = read_pointer(ctl)  # parses + digest-verifies or raises
    assert pointer is not None
    assert pointer["generation"] in (1, 2)
    assert pointer["checkpoint_dirs"] in (v1, v2)
    if pointer["generation"] == 1:
        assert pointer["params_fingerprint"] == old["params_fingerprint"]
    # whichever generation survived, its members still verify
    assert verify_pointer_members(pointer) == []
    # and the control plane is fully usable afterwards
    after = promote(ctl, v2, source="v2-after", sharpe_tolerance=None)
    assert after["checkpoint_dirs"] == v2


def test_promotion_cli_promote_show_reject(tmp_path, gate_cfg):
    ctl = tmp_path / "ctl"
    v1 = _members(tmp_path / "v1", gate_cfg, (1,))
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", f"{PKG}.reliability.promotion", *args],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=300)

    assert run("show", "--root", str(ctl)).returncode == 1  # no pointer yet
    out = run("promote", "--root", str(ctl), "--candidates", *v1)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout.splitlines()[-1])["generation"] == 1
    shown = run("show", "--root", str(ctl))
    assert shown.returncode == 0
    assert json.loads(shown.stdout)["generation"] == 1

    (Path(v1[0]) / "best_model_sharpe.msgpack").write_bytes(b"junk")
    rejected = run("promote", "--root", str(ctl), "--candidates", *v1)
    assert rejected.returncode == 1
    assert json.loads(
        rejected.stdout.splitlines()[-1])["rejected"] == "digest_mismatch"


# --------------------------------------------------------------------------
# engine reload: generation fallback, all-or-nothing, pointer verification
# --------------------------------------------------------------------------


@pytest.fixture()
def engine_pair(tmp_path, gate_cfg, panel):
    v1 = _members(tmp_path / "v1", gate_cfg, (1, 2))
    engine = InferenceEngine(v1, macro_history=panel["macro"],
                             stock_buckets=(N,), batch_buckets=(1,))
    return engine, v1


def test_reload_torn_member_falls_back_generation_bit_identical(
        engine_pair, gate_cfg, panel):
    """The satellite bugfix: params torn mid-write (the SIGKILL shape —
    new bytes partially on disk, digest mismatch) must fall back to the
    ``.g1`` generation ``load_checkpoint_dir`` already rotates, leaving
    the engine serving the OLD generation bit-identically instead of a
    partially re-stacked ensemble."""
    engine, v1 = engine_pair
    req = InferenceRequest(individual=panel["individual"][2],
                           mask=panel["mask"][2], month=2)
    before = engine.infer_one(req)
    fp, gen = engine.params_fingerprint, engine.params_generation
    compiles = engine.stats()["compiles"]

    # a refit starts writing new params into member 0: the old file
    # rotates to .g1, then the writer is SIGKILLed mid-write → torn bytes
    art = Path(v1[0]) / "best_model_sharpe.msgpack"
    save_params(art, GAN(gate_cfg).init(jax.random.key(99)))
    data = art.read_bytes()
    art.write_bytes(data[: len(data) // 3])  # torn: sidecar now mismatches

    with pytest.warns(UserWarning, match="fell back"):
        out = engine.reload()
    # the fallback generation IS the serving generation: no-op swap
    assert out["swapped"] is False
    assert engine.params_fingerprint == fp
    assert engine.params_generation == gen
    after = engine.infer_one(InferenceRequest(
        individual=panel["individual"][2], mask=panel["mask"][2], month=2))
    np.testing.assert_array_equal(before.weights, after.weights)
    np.testing.assert_array_equal(before.sdf, after.sdf)
    assert engine.stats()["compiles"] == compiles  # reload never recompiles


def test_reload_is_all_or_nothing(engine_pair, tmp_path, gate_cfg, panel):
    engine, v1 = engine_pair
    fp = engine.params_fingerprint
    # member-count change refuses
    with pytest.raises(ValueError, match="member"):
        engine.reload(checkpoint_dirs=v1 + v1)
    # architecture change refuses, engine untouched
    other = _members(tmp_path / "other", _make_cfg(hidden_dim=(16,)),
                     (7, 8))
    with pytest.raises(ValueError, match="architecture"):
        engine.reload(checkpoint_dirs=other)
    assert engine.params_fingerprint == fp
    # a real swap from explicit dirs: new fingerprint, +1 generation,
    # zero recompiles
    v2 = _members(tmp_path / "v2", gate_cfg, (11, 12))
    compiles = engine.stats()["compiles"]
    out = engine.reload(checkpoint_dirs=v2)
    assert out["swapped"] is True
    assert engine.params_fingerprint != fp
    assert engine.params_generation == 1
    assert engine.stats()["compiles"] == compiles


def test_service_reload_from_pointer_and_torn_member_5xx(
        tmp_path, gate_cfg, panel, engine_pair):
    """/v1/reload with a --pointer re-reads the pointer and verifies each
    member's on-disk bytes against the digests the gate recorded: a member
    torn AFTER promotion fails the WHOLE reload (5xx) and the engine keeps
    serving its current generation."""
    engine, v1 = engine_pair
    ctl = tmp_path / "ctl"
    promote(ctl, v1, source="v1")
    service = ServingService(engine, pointer_root=str(ctl))
    v2 = _members(tmp_path / "v2", gate_cfg, (11, 12))
    promote(ctl, v2, source="v2", sharpe_tolerance=None)

    st, body = service.handle("POST", "/v1/reload", {})
    assert st == 200, body
    assert body["swapped"] is True
    assert body["pointer_generation"] == 2
    assert body["converged"] is True
    fp = engine.params_fingerprint

    # tear a promoted member AFTER the gate: reload must refuse whole
    v3 = _members(tmp_path / "v3", gate_cfg, (21, 22))
    promote(ctl, v3, source="v3", sharpe_tolerance=None)
    art = Path(v3[1]) / "best_model_sharpe.msgpack"
    art.write_bytes(art.read_bytes() + b"x")
    st, body = service.handle("POST", "/v1/reload", {})
    assert st == 500
    assert "digest mismatch" in body["error"]
    assert engine.params_fingerprint == fp  # still serving v2
    service.close()


def test_mesh_engine_canary_revert_and_pointer_roll_without_recompile(
        tmp_path, gate_cfg, panel):
    """The PR-14 canary ring and PR-9 pointer machinery on a SHARDED
    engine (stocks=8 over the 8-device test mesh): pointer hot-swaps
    replay the canary ring, a non-finite candidate is reverted by the
    in-memory restore, the old generation keeps serving finite sharded
    outputs — and none of it compiles a single new program."""
    v1 = _members(tmp_path / "v1", gate_cfg, (1, 2))
    ctl = tmp_path / "ctl"
    promote(ctl, v1, source="v1")
    engine = InferenceEngine(v1, macro_history=panel["macro"],
                             stock_buckets=(N,), batch_buckets=(1,),
                             mesh="stocks=8")
    assert engine.stats()["stock_shards"] == 8
    assert engine.stats()["sharded_dispatch"] is True
    engine.warmup()
    compiles0 = engine.stats()["compiles"]
    service = ServingService(engine, pointer_root=str(ctl))
    try:
        # live traffic fills the canary ring with sharded-served inputs
        for t in range(3):
            st, _ = service.handle("POST", "/v1/weights", {
                "individual": panel["individual"][t].tolist(),
                "month": t})
            assert st == 200
        # healthy promote + pointer reload: the ring replays across the
        # swap on the SHARDED programs and the swap sticks
        v2 = _members(tmp_path / "v2", gate_cfg, (11, 12))
        promote(ctl, v2, source="v2", sharpe_tolerance=None)
        st, body = service.handle("POST", "/v1/reload", {})
        assert st == 200 and body["swapped"] is True
        assert body["canary"]["replayed"] > 0
        assert body["canary"]["finite"] is True
        fp = engine.params_fingerprint

        # a non-finite candidate's canary replay REVERTS the sharded swap
        vnan = [_write_member(tmp_path / "nan" / f"m{s}", gate_cfg,
                              s + 20, nan=True) for s in (1, 2)]
        st, body = service.handle("POST", "/v1/reload",
                                  {"checkpoint_dirs": vnan})
        assert st == 500
        assert "canary" in body["error"]
        assert engine.params_fingerprint == fp  # still serving v2
        res = engine.infer_one(InferenceRequest(
            individual=panel["individual"][0], month=0))
        assert np.isfinite(res.weights).all()
    finally:
        service.close()
    stats = engine.stats()
    assert stats["compiles"] == compiles0, (
        "canary replay, hot-swap and revert must not recompile")
    assert stats["steady_state_recompiles"] == 0
    assert stats["mesh"] == "stocks=8"


# --------------------------------------------------------------------------
# tier-1 fault matrix: 2-replica fleet, promote → rolling reload under load
# --------------------------------------------------------------------------


def _admin_metrics(url):
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        return json.loads(r.read())


def test_fleet_rolling_promote_kill_and_rollback_under_load(
        tmp_path, gate_cfg, panel):
    """THE acceptance run. A supervised 2-replica fleet boots from the
    promotion pointer; under open-loop load it goes through:

      1. promote v2 → health-gated rolling reload, with replica0 SIGKILLed
         mid-reload by the ``serve/reload`` fault site — its supervisor
         restarts it and it converges to the pointer on boot; ZERO
         unserved requests; both replicas on the promoted fingerprint;
      2. promote v3, then tear a v3 member on disk (corrupt AFTER the
         gate) → the roll fails, the pointer AUTO-ROLLS-BACK to v2, and
         both replicas converge back on the incumbent generation.
    """
    import dataclasses as dc

    from deeplearninginassetpricing_paperreplication_tpu.serving.fleet import (
        REPLICA_POLICY,
    )

    ctl = tmp_path / "ctl"
    v1 = _members(tmp_path / "v1", gate_cfg, (1, 2))
    v2 = _members(tmp_path / "v2", gate_cfg, (11, 12))
    v3 = _members(tmp_path / "v3", gate_cfg, (21, 22))
    run_dir = tmp_path / "fleet_run"
    events = EventLog(run_dir)
    p1 = promote(ctl, v1, source="v1", events=events)
    np.save(tmp_path / "macro.npy", panel["macro"])

    args = build_arg_parser().parse_args([
        "--pointer", str(ctl),
        "--macro_npy", str(tmp_path / "macro.npy"),
        "--stock_buckets", str(N), "--batch_buckets", "1,4",
        "--cache_size", "0",
        "--run_dir", str(run_dir)])
    port = pick_free_port()
    admin_ports = []
    for _ in range(2):
        ap = pick_free_port()
        while ap in admin_ports or ap == port:
            ap = pick_free_port()
        admin_ports.append(ap)
    argvs = [server_child_argv(args, i, run_dir / f"replica{i}", port,
                               admin_port=admin_ports[i])
             for i in range(2)]
    admin_urls = [f"http://127.0.0.1:{p}" for p in admin_ports]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # SIGKILL replica0 on its FIRST /v1/reload: mid-hot-swap death
    env["DLAP_FAULT_PLAN"] = json.dumps([{
        "site": "serve/reload", "action": "kill", "match": "replica0"}])
    policy = dc.replace(REPLICA_POLICY, backoff_base_s=0.2,
                        min_uptime_s=0.5, poll_s=0.2)
    fleet = ReplicaFleet(argvs, run_dir, policy=policy, env=env)
    fleet.start()
    try:
        fleet.wait_ready(timeout=300)
        url = f"http://127.0.0.1:{port}/v1/weights"
        body = binary_payload_bytes(panel["individual"][0], 0)
        load_out = {}

        def _drive():
            load_out.update(run_loadgen(
                url, lambda i: body, mode="open", rate_rps=20.0,
                n_requests=160, warmup_requests=0, retries=10,
                retry_backoff_s=0.3, timeout_s=30.0, open_workers=8,
                content_type=BINARY_CONTENT_TYPE))

        loader = threading.Thread(target=_drive)
        loader.start()
        time.sleep(1.0)

        # --- leg 1: promote v2, roll; replica0 dies mid-reload ----------
        p2 = promote(ctl, v2, source="v2", sharpe_tolerance=None,
                     events=events)
        updater = RollingUpdater(admin_urls, ctl, events=events,
                                 reload_timeout_s=240.0)
        roll = updater.roll()
        assert roll["status"] == "promoted", roll
        target = p2["params_fingerprint"][:16]
        for u in admin_urls:
            assert _admin_metrics(u)["engine"]["params_fingerprint"] == target

        loader.join()
        # THE bar: zero unserved requests through kill + rolling swap
        assert load_out["n_ok"] == load_out["n_requests"], load_out
        assert load_out["errors"] == {}

        # the kill really fired, exactly once, and was attributed
        fault_rows = [json.loads(line) for line in (
            run_dir / "events.faults.jsonl").read_text().splitlines()]
        assert [r["site"] for r in fault_rows] == ["serve/reload"]

        # --- leg 2: corrupt candidate → automatic rollback ---------------
        p3 = promote(ctl, v3, source="v3", sharpe_tolerance=None,
                     events=events)
        art = Path(v3[0]) / "best_model_sharpe.msgpack"
        art.write_bytes(art.read_bytes() + b"x")  # torn after promotion
        roll2 = updater.roll()
        assert roll2["status"] == "rolled_back", roll2
        pointer = read_pointer(ctl)
        assert pointer["rolled_back_from"] == p3["generation"]
        assert pointer["params_fingerprint"] == p2["params_fingerprint"]
        # the fleet converged BACK on the incumbent generation
        for u in admin_urls:
            assert _admin_metrics(u)["engine"]["params_fingerprint"] == target

        # NaN and regressed candidates never reach the fleet: gate-level
        # rejections (asserted in depth above) — the pointer is untouched
        nan = [_write_member(tmp_path / "nan" / "m1", gate_cfg, 31,
                             nan=True),
               _write_member(tmp_path / "nan" / "m2", gate_cfg, 32,
                             nan=True)]
        with pytest.raises(GateRejection):
            promote(ctl, nan, source="nan", events=events)
        assert read_pointer(ctl)["generation"] == pointer["generation"]

        # zero steady-state recompiles across every swap, on every replica
        for u in admin_urls:
            m = _admin_metrics(u)
            assert m["engine"]["steady_state_recompiles"] == 0
    finally:
        summaries = fleet.stop()
        events.close()
    # exactly one replica restart: the mid-reload kill
    assert sum((s or {}).get("restarts", 0) for s in summaries) == 1

    # every successful hot-swap replayed the canary ring: exactly one
    # serve/canary events row per swapped reload across the replica event
    # files (PR 14 model-health plane; a killed or refused reload swaps
    # nothing and therefore replays nothing)
    canary_rows, swapped_reloads = [], 0
    for ev_file in run_dir.glob("replica*/events*.jsonl"):
        for line in ev_file.read_text().splitlines():
            row = json.loads(line)
            if row.get("kind") != "counter":
                continue
            if row.get("name") == "serve/canary":
                canary_rows.append(row)
            elif row.get("name") == "serve/reload" and row.get("swapped"):
                swapped_reloads += 1
    assert swapped_reloads >= 1
    assert len(canary_rows) == swapped_reloads
    assert all(r.get("replayed") is not None for r in canary_rows)

    # the report CLI tells the whole promotion story from the run dir
    summary = summarize_run(load_run(run_dir))
    pm = summary["promotion"]
    assert pm["promotions"] == 3  # v1, v2, v3
    assert pm["pointer_rollbacks"] == 1
    assert pm["fleet_rollbacks"] == 1
    assert pm["fleet_converged"] == 1
    assert pm["rejections_by_reason"] == {"nonfinite_params": 1}
    assert set(pm["replica_timeline"]) == {"replica0", "replica1"}
    assert pm["converged"] is True
    # replica0's timeline includes its boot row (restart mid-promotion
    # converged to the pointer on boot)
    assert any(r["boot"] for r in pm["replica_timeline"]["replica0"])
    text = format_summary(summary)
    assert "promotion:" in text
    assert "CONVERGED" in text


# --------------------------------------------------------------------------
# rolling refit: ledger buckets, worker kill, zero retrains
# --------------------------------------------------------------------------

REFIT_ARGS = [
    "--months", "3", "4", "--seeds", "1",
    "--epochs_unc", "2", "--epochs_moment", "1", "--epochs", "3",
    "--ignore_epoch", "0", "--hidden_dim", "8", "--rnn_dim", "4",
    "--num_moments", "4", "--dropout", "0.0",
]


def _record_digests(run_dir):
    """{month: {artifact path: recorded sha256}} from the ledger records."""
    from deeplearninginassetpricing_paperreplication_tpu.reliability.ledger import (  # noqa: E501
        SweepLedger,
    )

    ledger = SweepLedger(Path(run_dir) / "sweep_ledger")
    out = {}
    for key in ledger.keys():
        rec = ledger.load(key)
        out[rec["month"]] = {
            str(Path(m["dir"]) / m["file"]): m["sha256"]
            for m in rec["members"]}
    return out


def _assert_checkpoints_match_records(run_dir):
    """Byte-identity evidence: every artifact's on-disk sha256 equals the
    digest its ledger record captured at train time."""
    import hashlib

    digests = _record_digests(run_dir)
    assert digests
    for per_month in digests.values():
        for path, sha in per_month.items():
            assert hashlib.sha256(
                Path(path).read_bytes()).hexdigest() == sha
    return digests


def test_refit_rolls_ledger_buckets_into_the_gate(tmp_path, synthetic_dir):
    """In-process rolling refit: every month trains as a ledger bucket,
    lands verified member checkpoints, and walks through the promotion
    gate in month order; a --resume-from-ledger re-run retrains NOTHING
    and re-promotes nothing (idempotent by source)."""
    from deeplearninginassetpricing_paperreplication_tpu import refit

    run_dir = tmp_path / "refit_run"
    rc = refit.main(["--data_dir", str(synthetic_dir),
                     "--run_dir", str(run_dir), *REFIT_ARGS])
    assert rc == 0
    digests = _assert_checkpoints_match_records(run_dir)
    assert set(digests) == {3, 4}
    pointer = read_pointer(run_dir)
    assert pointer is not None
    assert pointer["source"] in ("month0003", "month0004")
    assert pointer["generation"] >= 1
    # gate evidence in the events: one advance per promoted month
    rows = [json.loads(line) for line in
            (run_dir / "events.jsonl").read_text().splitlines()]
    advances = [r for r in rows if r.get("kind") == "counter"
                and r.get("name") == "promote/advance"]
    rejects = [r for r in rows if r.get("kind") == "counter"
               and r.get("name") == "promote/reject"]
    assert len(advances) + len(rejects) == 2
    assert len(advances) >= 1

    # resume: ledger hits for every month, checkpoints untouched,
    # promotion idempotent
    before = {p: Path(p).stat().st_mtime_ns
              for per in digests.values() for p in per}
    rc = refit.main(["--data_dir", str(synthetic_dir),
                     "--run_dir", str(run_dir), *REFIT_ARGS,
                     "--resume-from-ledger"])
    assert rc == 0
    after = {p: Path(p).stat().st_mtime_ns for p in before}
    assert after == before  # zero retrains: files never rewritten
    assert read_pointer(run_dir)["generation"] == pointer["generation"]
    _assert_checkpoints_match_records(run_dir)


def test_refit_worker_killed_resumes_with_zero_retrains(
        tmp_path, synthetic_dir):
    """The acceptance matrix: a supervised refit worker is SIGKILLed at
    its second bucket claim (month 3 already recorded). The supervisor
    restarts it with --resume-from-ledger; the restarted worker skips
    month 3 via the ledger (zero retrains — its checkpoints stay
    byte-identical to the pre-kill write) and completes month 4."""
    run_dir = tmp_path / "refit_run"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["DLAP_FAULT_PLAN"] = json.dumps([{
        "site": "sweep/claim", "action": "kill", "trigger_count": 2}])
    proc = subprocess.run(
        [sys.executable, "-m", f"{PKG}.refit",
         "--data_dir", str(synthetic_dir), "--run_dir", str(run_dir),
         *REFIT_ARGS, "--workers", "1", "--lease_timeout", "5",
         "--worker_min_uptime", "0.5", "--worker_backoff", "0.2"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]

    # both months recorded, artifacts byte-identical to their records
    digests = _assert_checkpoints_match_records(run_dir)
    assert set(digests) == {3, 4}

    # exactly one planned kill fired, at the second claim
    fault_rows = [json.loads(line) for line in (
        run_dir / "events.faults.jsonl").read_text().splitlines()]
    assert [r["site"] for r in fault_rows] == ["sweep/claim"]

    # zero retrains: each bucket was recorded exactly once, fleet-wide
    summary = summarize_run(load_run(run_dir))
    assert summary["elastic"]["buckets_completed"] == 2
    assert summary["reliability"]["restarts"] == 1
    # and the completed refits reached the gate
    assert summary["promotion"]["promotions"] >= 1
    assert read_pointer(run_dir) is not None


def test_promote_completed_skips_months_aged_out_of_history(tmp_path):
    """The pointer's embedded history is bounded (history_keep), so on a
    long rolling run old month sources age out of it — a restarted
    coordinator must STILL not re-promote them (the monotone month
    cutoff), else the pointer head would regress to a months-stale model
    and the next roll would hot-swap the fleet backwards."""
    from deeplearninginassetpricing_paperreplication_tpu.refit import (
        promote_completed,
    )

    ctl = tmp_path / "ctl"
    # the head names month0016 and every older source has aged out
    write_pointer(ctl, {"checkpoint_dirs": ["x"], "source": "month0016"})

    class _Ledger:
        @staticmethod
        def has(key):
            return True

        @staticmethod
        def load(key):
            raise AssertionError(
                "an already-promoted month reached the gate")

    class _Queue:
        ledger = _Ledger()

        @staticmethod
        def items():
            return [{"key": "k12", "index": 0, "month": 12},
                    {"key": "k16", "index": 1, "month": 16}]

    out = promote_completed(_Queue(), ctl, None, 0.05)
    assert out == {"promoted": [], "rejected": [], "skipped": [12, 16]}
    assert read_pointer(ctl)["source"] == "month0016"


def test_rolling_updater_rollback_failed_without_history(tmp_path):
    """A health-failed roll of the FIRST promoted generation has no
    incumbent to revert to: roll() must return a structured
    ``rollback_failed`` verdict (pointer untouched) instead of raising
    PromotionError past the caller with the fleet silently diverged."""
    ctl = tmp_path / "ctl"
    write_pointer(ctl, {"checkpoint_dirs": ["x"], "source": "g1",
                        "params_fingerprint": "f" * 64})
    updater = RollingUpdater(
        [f"http://127.0.0.1:{pick_free_port()}"], ctl,
        reload_timeout_s=0.4, health_interval_s=0.01, http_timeout_s=0.2)
    out = updater.roll()
    assert out["status"] == "rollback_failed"
    assert out["reason"] == "reload_timeout"
    assert out["swapped"] == []
    pointer = read_pointer(ctl)
    assert pointer["generation"] == 1 and pointer["source"] == "g1"


# --------------------------------------------------------------------------
# report section (synthetic events) + bench artifact + budgets
# --------------------------------------------------------------------------


def test_report_promotion_section_from_events(tmp_path, capsys):
    run_dir = tmp_path / "run"
    events = EventLog(run_dir)
    events.counter("promote/advance", generation=1, source="v1")
    events.counter("promote/advance", generation=2, source="v2")
    events.counter("promote/reject", reason="digest_mismatch", source="bad")
    events.counter("promote/reject", reason="sharpe_regression", source="s")
    events.counter("promote/reject", reason="sharpe_regression", source="t")
    events.counter("promote/rollback", generation=3, rolled_back_from=2)
    events.counter("promote/fleet_rollback", reason="health_fingerprint",
                   generation=3)
    events.counter("promote/fleet_converged", generation=2, replicas=2)
    for replica in ("replica0", "replica1"):
        events.counter("serve/generation", replica=replica,
                       fingerprint="aaaa", generation=0,
                       pointer_generation=1, boot=True)
        events.counter("serve/generation", replica=replica,
                       fingerprint="bbbb", generation=1,
                       pointer_generation=2)
    events.counter("serve/reload", generation=1, fingerprint="bbbb",
                   swapped=True)
    events.counter("serve/reload", generation=1, fingerprint="bbbb",
                   swapped=False)
    events.close()

    pm = summarize_run(load_run(run_dir))["promotion"]
    assert pm["promotions"] == 2
    assert pm["pointer_rollbacks"] == 1
    assert pm["fleet_rollbacks"] == 1
    assert pm["fleet_converged"] == 1
    assert pm["rejections_by_reason"] == {
        "digest_mismatch": 1, "sharpe_regression": 2}
    assert pm["reloads"] == {"swapped": 1, "noop": 1}
    assert pm["serving_fingerprints"] == {
        "replica0": "bbbb", "replica1": "bbbb"}
    assert pm["converged"] is True
    assert [r["fingerprint"]
            for r in pm["replica_timeline"]["replica0"]] == ["aaaa", "bbbb"]

    text = format_summary(summarize_run(load_run(run_dir)))
    assert "gate rejections: digest_mismatch:1  sharpe_regression:2" in text
    assert "replicas CONVERGED" in text

    # a run with no promotion events keeps the section out of the report
    empty = tmp_path / "empty"
    ev = EventLog(empty)
    ev.counter("unrelated")
    ev.close()
    assert summarize_run(load_run(empty))["promotion"] is None
    assert "promotion:" not in format_summary(summarize_run(load_run(empty)))


def test_bench_promotion_artifact_and_budgets():
    data = json.loads((REPO / "BENCH_PROMOTION.json").read_text())
    # the rolling-reload bars: no dropped traffic, no recompiles, both
    # replicas converged on the promoted fingerprint, no restarts
    assert data["roll_status"] == "promoted"
    assert data["dropped_requests"] == 0
    assert data["replicas"] >= 2
    assert all(v == 0 for v in data["steady_state_recompiles"].values())
    assert data["converged"] is True
    assert len(set(data["serving_fingerprints"].values())) == 1
    assert all(r == 0 for r in data["replica_restarts"])
    assert data["promoted_generation"] == data["incumbent_generation"] + 1

    budgets = json.loads((REPO / "budgets.json").read_text())
    names = {b["name"] for b in budgets["budgets"]}
    # the budget gate (validated against the checked-in artifact inside
    # tier-1 by test_telemetry's shipped-budgets test) covers the bars
    assert {"promotion_rolling_reload_dropped_requests",
            "promotion_steady_state_recompiles_replica0",
            "promotion_steady_state_recompiles_replica1"} <= names


# --------------------------------------------------------------------------
# fault-site registry + lint gate
# --------------------------------------------------------------------------


def test_new_fault_sites_registered():
    from deeplearninginassetpricing_paperreplication_tpu.reliability.faults import (  # noqa: E501
        SITES,
    )

    assert "promote/validate" in SITES
    assert "promote/write" in SITES
    assert "serve/reload" in SITES


def test_promotion_module_stays_stdlib_at_module_level():
    """The pointer is read by thin fleet parents and the report CLI — the
    MODULE level must stay stdlib-only (like ledger.py/verified.py; jax
    only inside the validation pass). Static check over the AST: no
    top-level jax/numpy/flax import."""
    import ast

    tree = ast.parse(
        (REPO / PKG / "reliability" / "promotion.py").read_text())
    heavy = {"jax", "numpy", "flax"}
    for node in tree.body:
        if isinstance(node, ast.Import):
            names = [a.name.split(".")[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            names = [(node.module or "").split(".")[0]]
        else:
            continue
        assert not (set(names) & heavy), (
            f"module-level heavy import in promotion.py: {names}")


def test_promotion_modules_lint_clean():
    targets = [
        REPO / PKG / "reliability" / "promotion.py",
        REPO / PKG / "reliability" / "faults.py",
        REPO / PKG / "refit.py",
        REPO / PKG / "serving" / "fleet.py",
        REPO / PKG / "serving" / "loadgen.py",
        REPO / PKG / "serving" / "server.py",
        REPO / PKG / "serving" / "aserver.py",
        REPO / PKG / "serving" / "engine.py",
        REPO / PKG / "observability" / "report.py",
        REPO / "bench.py",
        Path(__file__),
    ]
    try:
        import ruff  # noqa: F401
    except ImportError:
        from test_observability import _ast_unused_imports

        problems = {}
        for path in targets:
            unused = _ast_unused_imports(path)
            if unused:
                problems[path.name] = unused
        assert not problems, f"unused imports: {problems}"
        return
    out = subprocess.run(
        [sys.executable, "-m", "ruff", "check"] + [str(t) for t in targets],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
