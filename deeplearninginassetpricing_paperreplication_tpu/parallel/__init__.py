from .mesh import (BATCH_AXIS, STOCK_AXIS, batch_sharding, create_2d_mesh, create_mesh, replicate, shard_batch)
