"""HTTP serving layer: a stdlib ``ThreadingHTTPServer`` JSON API over the
:class:`~.engine.InferenceEngine`.

Endpoints::

    POST /v1/weights  {"individual": [[...]], "mask": [...]?, "month": t?}
                      → {"weights": [...], "month": t, "n": N, ...}
    POST /v1/sdf      same + {"returns": [...]} → {"sdf": F, "member_sdf": [..]}
    POST /v1/macro    {"macro": [...], "raw": false?} — O(1) incremental
                      macro-state advance; → {"month": new index}
    GET  /v1/models   ensemble manifest (members, config hash, buckets, ...)
    GET  /healthz     liveness; mirrors the run dir's heartbeat.json
    GET  /metrics     request counts, latency percentiles, cache, engine stats

Every request lifecycle emits ``observability`` spans/counters into the run
dir's ``events.jsonl`` (``serve/request`` spans carry the latency the report
CLI aggregates), liveness reuses the shared bench-format heartbeat writer,
and results are cached in an LRU keyed by (config hash, request
fingerprint) so identical queries skip the accelerator entirely. Request
execution goes through the :class:`~.batcher.MicroBatcher`; a full queue
surfaces as HTTP 503, not an unbounded backlog.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import EventLog, Heartbeat, read_state, write_manifest
from .batcher import MicroBatcher, QueueFull
from .engine import InferenceEngine, InferenceRequest, bucket_for

HEARTBEAT_INTERVAL_S = 5.0


class BadRequest(ValueError):
    """Client-side payload problem → HTTP 400."""


class LRUCache:
    """Tiny thread-safe LRU for response dicts."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                return self._d[key]
            self.misses += 1
            return None

    def put(self, key, value) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def __len__(self):
        with self._lock:
            return len(self._d)


def request_fingerprint(endpoint: str, payload: Dict[str, Any]) -> str:
    """Canonical-JSON sha256 of one request — the cache key's second half."""
    blob = json.dumps([endpoint, payload], sort_keys=True,
                      separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


class ServingService:
    """Engine + micro-batcher + LRU cache + telemetry, transport-agnostic.

    The HTTP handler below is a thin shim over :meth:`handle`; tests drive
    the service directly (loopback-only semantics, no sockets needed).
    """

    def __init__(
        self,
        engine: InferenceEngine,
        run_dir: Optional[str] = None,
        max_batch: Optional[int] = None,
        max_delay_s: float = 0.002,
        max_queue: int = 256,
        cache_size: int = 256,
        events: Optional[EventLog] = None,
    ):
        self.engine = engine
        if events is not None:
            self.events = events
        elif run_dir is not None:
            # a run dir implies a sink; rebind the engine too so its
            # compile/dispatch telemetry lands in the same events.jsonl
            # (construct the engine with events=EventLog(run_dir) to also
            # capture its load-time macro_scan/compile spans)
            self.events = EventLog(run_dir)
        else:
            self.events = engine.events
        engine.events = self.events
        self.run_dir = Path(run_dir) if run_dir else None
        self.heartbeat: Optional[Heartbeat] = None
        if self.run_dir is not None:
            self.heartbeat = Heartbeat(
                self.run_dir / "heartbeat.json", events=self.events)
            write_manifest(
                self.run_dir, "serve", events=self.events,
                config=engine.cfg,
                extra={
                    "checkpoint_dirs": engine.checkpoint_dirs,
                    "stock_buckets": list(engine.stock_buckets),
                    "batch_buckets": list(engine.batch_buckets),
                },
            )
            self.heartbeat.beat("serve/start")
        self.cache = LRUCache(cache_size)
        self.batcher = MicroBatcher(
            self._handle_batch,
            max_batch=(max(engine.batch_buckets) if max_batch is None
                       else max_batch),
            max_delay_s=max_delay_s,
            max_queue=max_queue,
        )
        self._lock = threading.Lock()
        self._latencies: deque = deque(maxlen=4096)  # seconds
        self._requests: Dict[Tuple[str, str], int] = {}
        self._started = time.monotonic()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if self.heartbeat is not None:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True, name="serving-heartbeat")
            self._hb_thread.start()

    # -- lifecycle -----------------------------------------------------------

    def _hb_loop(self):
        while not self._hb_stop.wait(HEARTBEAT_INTERVAL_S):
            self.heartbeat.beat("serve/idle")

    def warmup(self) -> int:
        n = self.engine.warmup()
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/ready")
        return n

    def close(self):
        self._hb_stop.set()
        self.batcher.close()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/stopped")

    # -- request plumbing ----------------------------------------------------

    def _handle_batch(self, bucket, items: List[InferenceRequest]):
        return self.engine.infer(items)

    def _record(self, endpoint: str, status: int, seconds: float) -> None:
        with self._lock:
            key = (endpoint, str(status))
            self._requests[key] = self._requests.get(key, 0) + 1
            if status == 200:
                self._latencies.append(seconds)
        self.events.counter("serve/requests", endpoint=endpoint,
                            status=status)

    def handle(self, method: str, path: str,
               payload: Optional[Dict[str, Any]],
               raw_body: Optional[bytes] = None) -> Tuple[int, Dict]:
        """One request → (http status, response dict). Never raises.
        `raw_body`: the undecoded request bytes when the caller has them
        (the HTTP shim does) — the cache then fingerprints those instead of
        re-serializing the multi-MB payload on the hot path."""
        t0 = time.monotonic()
        endpoint = path.split("?", 1)[0].rstrip("/") or "/"
        status, body = 500, {"error": "internal"}
        try:
            with self.events.span("serve/request", endpoint=endpoint,
                                  method=method):
                status, body = self._route(method, endpoint, payload,
                                           raw_body)
        except BadRequest as e:
            status, body = 400, {"error": str(e)}
        except QueueFull as e:
            status, body = 503, {"error": f"overloaded: {e}"}
        except Exception as e:  # a bad request must not kill the server
            status, body = 500, {"error": f"{type(e).__name__}: {e}"}
        self._record(endpoint, status, time.monotonic() - t0)
        return status, body

    def _route(self, method, endpoint, payload, raw_body) -> Tuple[int, Dict]:
        if endpoint == "/healthz":
            return 200, self.healthz()
        if endpoint == "/metrics":
            return 200, self.metrics()
        if endpoint == "/v1/models":
            return 200, self.models_info()
        if endpoint in ("/v1/weights", "/v1/sdf"):
            if method != "POST":
                return 405, {"error": "POST required"}
            return 200, self._infer_endpoint(endpoint, payload or {},
                                             raw_body)
        if endpoint == "/v1/macro":
            if method != "POST":
                return 405, {"error": "POST required"}
            return 200, self._macro_endpoint(payload or {})
        return 404, {"error": f"unknown endpoint {endpoint}"}

    # -- endpoints -----------------------------------------------------------

    def _parse_request(self, endpoint, payload) -> InferenceRequest:
        if "individual" not in payload:
            raise BadRequest("payload requires 'individual' ([N, F] floats)")
        try:
            individual = np.asarray(payload["individual"], np.float32)
        except (TypeError, ValueError) as e:
            raise BadRequest(f"bad 'individual': {e}") from e
        f = self.engine.cfg.individual_feature_dim
        if individual.ndim != 2 or individual.shape[1] != f:
            raise BadRequest(
                f"'individual' must be [N, {f}]; got {list(individual.shape)}")
        mask = payload.get("mask")
        if mask is not None:
            mask = np.asarray(mask, np.float32)
            if mask.shape != (individual.shape[0],):
                raise BadRequest("'mask' must be [N]")
        returns = payload.get("returns")
        if endpoint == "/v1/sdf" and returns is None:
            raise BadRequest("/v1/sdf requires 'returns' ([N] floats)")
        if returns is not None:
            returns = np.asarray(returns, np.float32)
            if returns.shape != (individual.shape[0],):
                raise BadRequest("'returns' must be [N]")
        month = int(payload.get("month", -1))
        return InferenceRequest(individual=individual, mask=mask,
                                returns=returns, month=month)

    def _infer_endpoint(self, endpoint, payload, raw_body=None
                        ) -> Dict[str, Any]:
        req = self._parse_request(endpoint, payload)
        # resolve a relative month BEFORE building the cache key: a cached
        # month=-1 answer must not outlive a /v1/macro append (the engine's
        # month count is part of the result's identity), and the engine is
        # handed the resolved index so key and computation cannot diverge
        if self.engine.state_dim > 0:
            months = self.engine.months
            resolved = req.month if req.month >= 0 else months + req.month
            if not 0 <= resolved < months:
                raise BadRequest(
                    f"month {req.month} outside the engine's {months} "
                    "macro months")
            req.month = resolved
        fp = (hashlib.sha256(raw_body).hexdigest() if raw_body is not None
              else request_fingerprint(endpoint, payload))
        key = (self.engine.config_hash, endpoint, req.month, fp)
        cached = self.cache.get(key)
        self.events.counter("serve/cache", hit=cached is not None,
                            endpoint=endpoint)
        if cached is not None:
            return dict(cached, cached=True)
        try:
            bucket = bucket_for(req.individual.shape[0],
                                self.engine.stock_buckets)
        except ValueError as e:
            raise BadRequest(str(e)) from e
        res = self.batcher.submit_wait(bucket, req, timeout=30.0)
        body: Dict[str, Any] = {
            "month": res.month, "n": res.n, "bucket": res.bucket,
            "n_members": self.engine.n_members,
            "config_hash": self.engine.config_hash,
        }
        if endpoint == "/v1/weights":
            body["weights"] = np.asarray(res.weights, np.float64).tolist()
        else:
            body["sdf"] = res.sdf
            body["member_sdf"] = np.asarray(
                res.member_sdf, np.float64).tolist()
        self.cache.put(key, body)
        return dict(body, cached=False)

    def _macro_endpoint(self, payload) -> Dict[str, Any]:
        if "macro" not in payload:
            raise BadRequest("payload requires 'macro' ([M] floats)")
        try:
            month = self.engine.append_month(
                np.asarray(payload["macro"], np.float32),
                raw=bool(payload.get("raw", False)))
        except ValueError as e:
            raise BadRequest(str(e)) from e
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/macro_append")
        return {"month": month, "months": self.engine.months}

    def models_info(self) -> Dict[str, Any]:
        return {
            "n_members": self.engine.n_members,
            "checkpoint_dirs": self.engine.checkpoint_dirs,
            "config_hash": self.engine.config_hash,
            "config": self.engine.cfg.to_dict(),
            "months": self.engine.months,
            "engine": self.engine.stats(),
        }

    def healthz(self) -> Dict[str, Any]:
        """Liveness + the run dir's on-disk heartbeat (the SAME file a
        bench-format watchdog supervises — the two must agree)."""
        out: Dict[str, Any] = {
            "ok": True,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "run_id": self.events.run_id,
        }
        if self.heartbeat is not None:
            out["heartbeat"] = (
                read_state(self.heartbeat.path).get("heartbeat"))
        return out

    def metrics(self) -> Dict[str, Any]:
        from ..observability.report import latency_percentiles_ms

        with self._lock:
            lat = list(self._latencies)
            requests = {f"{ep} {st}": n
                        for (ep, st), n in sorted(self._requests.items())}
        latency = latency_percentiles_ms(lat)
        if latency is not None:
            latency["mean_ms"] = round(sum(lat) / len(lat) * 1e3, 3)
        return {
            "requests": requests,
            "latency": latency,
            "cache": {"hits": self.cache.hits, "misses": self.cache.misses,
                      "size": len(self.cache)},
            "batcher": {"flushes": self.batcher.flushes,
                        "rejected": self.batcher.rejected,
                        "pending": self.batcher.pending()},
            "engine": self.engine.stats(),
        }


# -- HTTP shim ---------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    # the service is attached to the server object by make_server()
    def _respond(self, status: int, body: Dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _payload(self) -> Tuple[Optional[Dict], Optional[bytes]]:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return None, None
        raw = self.rfile.read(length)
        try:
            return json.loads(raw), raw
        except json.JSONDecodeError:
            return {"__invalid_json__": True}, raw

    def _dispatch(self, method: str) -> None:
        payload, raw = self._payload() if method == "POST" else (None, None)
        if payload is not None and "__invalid_json__" in payload:
            self._respond(400, {"error": "request body is not valid JSON"})
            return
        status, body = self.server.service.handle(
            method, self.path, payload, raw_body=raw)
        self._respond(status, body)

    def do_GET(self):  # noqa: N802 (stdlib handler API)
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def log_message(self, fmt, *args):  # stdout silence; events.jsonl has it
        pass


def make_server(service: ServingService, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Bind a ThreadingHTTPServer for `service`; port 0 picks a free port
    (``server.server_address[1]`` has the real one). Caller runs
    ``serve_forever()`` (typically on a thread) and ``shutdown()``s."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.service = service
    return httpd


# -- CLI ---------------------------------------------------------------------


def main(argv=None):
    from ..data.pipeline import load_splits_cached
    from ..observability import RunLogger, set_run_logger
    from ..utils.platform import apply_env_platforms

    apply_env_platforms()
    p = argparse.ArgumentParser(
        description="Serve an SDF checkpoint ensemble over HTTP")
    p.add_argument("--checkpoint_dirs", type=str, nargs="+", required=True)
    p.add_argument("--data_dir", type=str, required=True,
                   help="panel dir; the serving macro history comes from "
                        "--macro_split (normalized with train stats)")
    p.add_argument("--macro_split", type=str, default="test",
                   choices=("train", "valid", "test"))
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8787)
    p.add_argument("--run_dir", type=str, default=None,
                   help="telemetry dir (manifest/events/heartbeat)")
    p.add_argument("--max_delay_s", type=float, default=0.002)
    p.add_argument("--no_warmup", action="store_true",
                   help="skip AOT-compiling every bucket before accepting "
                        "traffic (first requests then pay compiles)")
    args = p.parse_args(argv)

    events = EventLog(args.run_dir) if args.run_dir else EventLog()
    set_run_logger(RunLogger(events=events))
    splits = dict(zip(("train", "valid", "test"),
                      load_splits_cached(args.data_dir, events=events)))
    ds = splits[args.macro_split]
    train = splits["train"]
    # cap the bucket ladder at the loaded panel's stock count: warmup then
    # compiles only programs this deployment can actually hit, instead of
    # the full default ladder up to 16k stocks
    from .engine import DEFAULT_STOCK_BUCKETS

    n_max = max(s.N for s in splits.values())
    top = bucket_for(n_max, DEFAULT_STOCK_BUCKETS)
    engine = InferenceEngine(
        args.checkpoint_dirs,
        macro_history=ds.macro,
        macro_stats=(train.mean_macro, train.std_macro),
        stock_buckets=tuple(b for b in DEFAULT_STOCK_BUCKETS if b <= top),
        events=events,
    )
    service = ServingService(
        engine, run_dir=args.run_dir, max_delay_s=args.max_delay_s,
        events=events)
    if not args.no_warmup:
        n = service.warmup()
        print(f"warmed {n} forward programs "
              f"(buckets {list(engine.stock_buckets)})")
    httpd = make_server(service, args.host, args.port)
    host, port = httpd.server_address[:2]
    print(f"serving {engine.n_members} members on http://{host}:{port} "
          f"(config {engine.config_hash[:12]})")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        service.close()
        events.close()


if __name__ == "__main__":
    main()
