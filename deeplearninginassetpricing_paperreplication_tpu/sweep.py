"""The paper protocol as ONE command: 384-config search → top-k × 9 seeds →
weight-averaged ensembles → test Sharpe report, checkpointing everything.

The reference has NO sweep code — its README (``/root/reference/README.md:
205-207``) and the paper (§II.E: "384 models … four best … 9 models") describe
the protocol but the repo leaves it to the reader (the ~6 h serial 9-seed loop
in ``demo_full.ipynb`` cell 22 is commented out). Here the whole protocol is
TPU-native: the search trains each architecture bucket's (lr × seed) grid as
one vmapped program, every winner's 9-seed ensemble is one vmapped program,
and evaluation follows ``evaluate_ensemble.py:137-171`` exactly (averaged
normalized weights, re-normalized, negated Sharpe, ddof=0).

    python -m deeplearninginassetpricing_paperreplication_tpu.sweep \
        --data_dir data/synthetic_data --save_dir ./sweep_run --quick

Artifacts in --save_dir:
    sweep_ranking.json                 — every (config, lr, seed) + valid Sharpe
    rank{r}_seed{s}/config.json        — per-member checkpoint dirs in the
    rank{r}_seed{s}/best_model_sharpe.msgpack  reference layout (consumable by
                                         evaluate_ensemble --checkpoint_dirs)
    report.json                        — per-winner + grand ensemble Sharpes
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .observability import (
    EventLog,
    Heartbeat,
    RunLogger,
    get_run_logger,
    set_run_logger,
    update_manifest,
    write_manifest,
)
from .parallel.ensemble import (
    apply_quorum,
    ensemble_metrics,
    ensemble_metrics_from_weights,
    member_weights,
    train_ensemble,
)
from .parallel.sweep import (
    architecture_signature,
    bucket_work_items,
    grid_configs,
    open_work_queue,
    ranking_from_ledger,
    run_sweep,
    run_sweep_worker,
)
from .reliability.ledger import LEDGER_DIRNAME, SweepLedger
from .reliability.verified import load_verified, write_verified
from .training.checkpoint import save_params
from .utils.config import GANConfig, TrainConfig

PAPER_SEEDS = (42, 123, 456, 789, 1000, 2000, 3000, 4000, 5000)

# the --quick smoke grid + schedules, as importable constants: tests (and
# tools) that need to predict a quick sweep's bucket keys — e.g. to aim a
# fault plan's `match` at one bucket — derive them from THE definition
# main() uses instead of copying literals that could drift
QUICK_GRID_KW = dict(
    hidden_dims=((64, 64), (32, 32)),
    rnn_units=((4,),),
    num_moments=(8,),
    dropouts=(0.05,),
    lrs=(1e-3, 5e-4),
)
QUICK_SEARCH_SCHEDULE = dict(
    num_epochs_unc=8, num_epochs_moment=4, num_epochs=16, ignore_epoch=2)
QUICK_ENSEMBLE_SCHEDULE = dict(
    num_epochs_unc=16, num_epochs_moment=8, num_epochs=32, ignore_epoch=4)


def _finite(x: float):
    """JSON-safe scalar: -inf (a grid point whose trackers never updated)
    would serialize as the non-standard '-Infinity' and break downstream
    parsers; map non-finite to None."""
    import math

    return x if math.isfinite(x) else None


def write_ranking(save_dir, ranked: Sequence[Dict],
                  coverage: Optional[Dict] = None) -> Path:
    """Write ``sweep_ranking.json`` (and, when the search completed
    DEGRADED, ``sweep_coverage.json``) through the verified path: atomic
    tmp+replace with a sha256 sidecar, so a mid-write kill can never leave
    a torn ranking for a resume to trust (these used to be plain
    ``json.dump`` writes). The coverage manifest is the explicit contract
    of a degraded completion: which buckets are missing from this ranking,
    why, and after how many attempts."""
    save_dir = Path(save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)
    rows = [
        {
            "rank": i,
            "config": r["config"].to_dict(),
            "lr": r["lr"],
            "seed": r["seed"],
            "valid_sharpe": _finite(r["valid_sharpe"]),
        }
        for i, r in enumerate(ranked)
    ]
    path = save_dir / "sweep_ranking.json"
    write_verified(path, json.dumps(rows, indent=2).encode())
    if coverage is not None:
        write_verified(save_dir / "sweep_coverage.json",
                       json.dumps(coverage, indent=2).encode())
    return path


def load_ranking(path) -> List[Dict]:
    """Parse a written sweep_ranking.json back into run_protocol's ranking
    rows (GANConfig round-trip; JSON null — a never-updated tracker — maps
    back to -inf so it sorts below every real Sharpe).

    Digest-verified: the ``.sha256`` sidecar (written by
    :func:`write_ranking`) is checked when present, and corruption — torn
    bytes, bit rot — raises a ``ValueError`` NAMING the offending file
    instead of resuming a multi-hour protocol from a silently wrong
    ranking."""
    path = Path(path)

    def parse(data: bytes) -> List[Dict]:
        try:
            return json.loads(data.decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(
                f"corrupt or truncated sweep ranking {path}: {e}") from e

    rows, _ = load_verified(path, parse)
    return [
        {
            "config": GANConfig.from_dict(r["config"]),
            "lr": r["lr"],
            "seed": r["seed"],
            "valid_sharpe": (
                r["valid_sharpe"] if r["valid_sharpe"] is not None
                else float("-inf")
            ),
        }
        for r in rows
    ]


def select_winners(ranked: List[Dict], top_k: int) -> List[Dict]:
    """Top-k DISTINCT (architecture, lr) combos from a ranked sweep result.

    The search grid ranks (config, lr, seed) points; the protocol's "best 4
    configs" are distinct hyperparameter settings, so multiple seeds of one
    setting collapse to its best-ranked entry."""
    winners, seen = [], set()
    for r in ranked:
        key = (architecture_signature(r["config"]), r["lr"])
        if key not in seen:
            seen.add(key)
            winners.append(r)
        if len(winners) == top_k:
            break
    return winners


def run_protocol(
    configs_and_lrs: Sequence[Tuple[GANConfig, float]],
    train_batch,
    valid_batch,
    test_batch,
    search_tcfg: TrainConfig,
    ensemble_tcfg: TrainConfig,
    search_seeds: Sequence[int] = (42,),
    ensemble_seeds: Sequence[int] = PAPER_SEEDS,
    top_k: int = 4,
    save_dir: Optional[str] = None,
    verbose: bool = True,
    member_chunk: Optional[int] = None,
    exec_cfg=None,
    ranking: Optional[List[Dict]] = None,
    diagnostic_top: int = 8,
    diagnostic_seeds: Sequence[int] = (42, 123, 456),
    heartbeat=None,
    quorum: Optional[int] = None,
    ledger: Optional[SweepLedger] = None,
    consult_ledger: bool = False,
    coverage: Optional[Dict] = None,
    grid_mesh=None,
) -> Dict:
    """Search → winners → per-winner vmapped 9-seed ensembles → report dict.

    `ranking`: a precomputed stage-1 result (the parsed sweep_ranking.json
    or a ledger-reconstructed elastic ranking) — skips the search so an
    interrupted protocol resumes at the ensemble stage instead of repaying
    the full 384-config search.

    `ledger` / `consult_ledger`: bucket-level durability for stage 1 (see
    run_sweep) — every completed bucket lands as a verified record, and a
    resumed search re-trains only unfinished buckets.

    `quorum`: ensemble quorum semantics — a winner's ensemble proceeds
    with ≥ quorum surviving (finite-params) seed members, DROPPING diverged
    members (recorded per winner as ``dropped_seeds`` and counted as
    ``sweep/quorum_drop``) instead of letting one bad seed poison the
    weight-averaged ensemble or fail the whole protocol; fewer survivors
    than the quorum raises :class:`parallel.ensemble.QuorumError`. None
    (default) keeps historical behavior (no check, no drops).

    `coverage`: a degraded elastic search's coverage manifest — shipped
    beside the ranking (``sweep_coverage.json``) and echoed in the report.

    `diagnostic_top` / `diagnostic_seeds`: the selection-noise diagnostic
    needs more than top_k pairs to mean anything (VERDICT r4 weak #5: a
    Spearman over n=4 is close to meaningless) — ranks top_k..diagnostic_top
    are ALSO retrained (full schedule, `diagnostic_seeds` members each,
    cheap under the member-fused kernels) purely to widen the
    search-vs-retrain rank comparison to ≥8 pairs. Set diagnostic_top ≤
    top_k to disable the extra retrains.
    """
    t0 = time.time()
    save_dir = Path(save_dir) if save_dir else None
    logger = get_run_logger()

    def log(msg):
        logger.info(msg, verbose=verbose)

    # ---- stage 1: hyperparameter search ----
    search_stats: Dict = {}
    if ranking is not None:
        log(f"[protocol] reusing precomputed search ranking "
            f"({len(ranking)} points)")
        ranked = ranking
    else:
        log(f"[protocol] search: {len(configs_and_lrs)} (config, lr) combos "
            f"× {len(search_seeds)} seeds")
        with logger.events.span("protocol/search",
                                n_combos=len(configs_and_lrs)):
            ranked = run_sweep(
                configs_and_lrs, search_seeds, train_batch, valid_batch,
                tcfg=search_tcfg, top_k=None, keep_params=False,
                verbose=verbose, member_chunk=member_chunk, exec_cfg=exec_cfg,
                stats_out=search_stats, heartbeat=heartbeat,
                ledger=ledger, consult_ledger=consult_ledger,
                grid_mesh=grid_mesh,
            )
    search_s = time.time() - t0
    if save_dir:  # also on resume: keep the artifact contract in save_dir
        write_ranking(save_dir, ranked, coverage)
    winners = select_winners(ranked, top_k)
    log(f"[protocol] search done in {search_s:.1f}s; top {len(winners)}:")
    for i, w in enumerate(winners):
        log(f"  #{i}: hidden={w['config'].hidden_dim} "
            f"rnn={w['config'].num_units_rnn} K={w['config'].num_condition_moment} "
            f"drop={w['config'].dropout} lr={w['lr']} "
            f"valid_sharpe={w['valid_sharpe']:.4f}")

    # ---- stage 2: per-winner 9-seed vmapped ensembles ----
    report = {
        "search_seconds": round(search_s, 1),
        "search_resumed_from_ranking": ranking is not None,
        "n_search_points": len(ranked),
        **({"search_stats": search_stats} if search_stats else {}),
        **({"search_coverage": coverage} if coverage is not None else {}),
        **({"quorum": quorum} if quorum is not None else {}),
        "winners": [],
    }
    all_test_weights = []  # [S, T, N] per winner, for the grand ensemble
    winner_vparams = []  # kept for the same-seed-count diagnostic below
    for rank, w in enumerate(winners):
        tcfg = dataclasses.replace(ensemble_tcfg, lr=w["lr"])
        log(f"[protocol] ensemble #{rank}: {len(ensemble_seeds)} seeds, "
            f"lr={w['lr']}")
        if heartbeat is not None:
            heartbeat.beat("winner_ensemble", rank=rank)
        with logger.events.span("protocol/ensemble", rank=rank,
                                n_seeds=len(ensemble_seeds)):
            gan, vparams, _hist = train_ensemble(
                w["config"], train_batch, valid_batch, test_batch,
                seeds=ensemble_seeds, tcfg=tcfg, verbose=verbose,
                member_chunk=member_chunk, exec_cfg=exec_cfg,
                heartbeat=heartbeat,
            )
        member_seeds = [int(s) for s in ensemble_seeds]
        dropped: List[int] = []
        if quorum is not None:
            # quorum semantics: drop diverged (non-finite) members and
            # proceed with the survivors instead of failing the protocol
            # on one bad seed — the drops are recorded, never silent
            vparams, member_seeds, dropped = apply_quorum(
                vparams, ensemble_seeds, quorum)
            for s in dropped:
                logger.events.counter("sweep/quorum_drop", rank=rank, seed=s)
            if dropped:
                logger.warning(
                    f"[protocol] ensemble #{rank}: dropped diverged members "
                    f"(seeds {dropped}); proceeding with "
                    f"{len(member_seeds)}/{len(ensemble_seeds)} "
                    f"(quorum {quorum})")
        splits = {
            "train": train_batch, "valid": valid_batch, "test": test_batch,
        }
        metrics = {
            name: ensemble_metrics(gan, vparams, b) for name, b in splits.items()
        }
        all_test_weights.append(member_weights(gan, vparams, test_batch))
        winner_vparams.append(
            {"gan": gan, "vparams": vparams, "seeds": member_seeds})

        if save_dir:
            for si, seed in enumerate(member_seeds):
                mdir = save_dir / f"rank{rank}_seed{seed}"
                mdir.mkdir(parents=True, exist_ok=True)
                w["config"].save(mdir / "config.json")
                save_params(
                    mdir / "best_model_sharpe.msgpack",
                    jax.tree.map(lambda x, i=si: x[i], vparams),
                )
        report["winners"].append({
            "rank": rank,
            "config": w["config"].to_dict(),
            "lr": w["lr"],
            "search_valid_sharpe": _finite(w["valid_sharpe"]),
            "seeds": member_seeds,
            "dropped_seeds": dropped,
            "ensemble_sharpe": {
                name: _finite(float(m["ensemble_sharpe"]))
                for name, m in metrics.items()
            },
            "individual_test_sharpes": [
                _finite(s) for s in metrics["test"]["individual_sharpes"].tolist()
            ],
        })
        log(f"  test ensemble sharpe: "
            f"{report['winners'][-1]['ensemble_sharpe']['test']:.4f}")

    # ---- selection-noise diagnostic: search Sharpe vs retrained ensemble --
    # The quick-schedule search Sharpe is a NOISY selector (r3: winners at
    # search valid ≈0.37 retrained to ensemble valid ≈−0.15 on synthetic
    # data). Record the rank agreement so the artifact carries the evidence
    # instead of a prose warning. Ranks beyond top_k are retrained with a
    # smaller seed set purely to make the comparison statistically real
    # (n ≥ 8 pairs instead of the winners' 4).
    # Every diagnostic point must use the SAME member count: a 9-seed
    # ensemble's valid Sharpe carries a level shift from extra averaging
    # that a 3-seed one doesn't, which would fake rank agreement between
    # the top_k and the extra retrains. The winners' points are therefore
    # re-evaluated on the diagnostic_seeds SUBSET of their already-trained
    # members (no extra training); if the subset isn't available, the full
    # ensemble value is used and n_seeds records the mismatch.
    diag_points = []
    for w, vp in zip(report["winners"], winner_vparams):
        # subset indices resolve against the winner's SURVIVING members —
        # quorum drops shift the member axis, and a dropped diagnostic seed
        # disables the subset for that winner rather than mis-indexing
        member_seeds = vp["seeds"]
        subset_idx = ([member_seeds.index(s) for s in diagnostic_seeds]
                      if set(diagnostic_seeds) <= set(member_seeds) else None)
        if subset_idx is not None:
            sub = jax.tree.map(
                lambda x, idx=subset_idx: x[jnp.asarray(idx)], vp["vparams"])
            val = _finite(float(ensemble_metrics(
                vp["gan"], sub, valid_batch)["ensemble_sharpe"]))
            n_seeds = len(subset_idx)
        else:
            val = w["ensemble_sharpe"]["valid"]
            n_seeds = len(member_seeds)
        diag_points.append({
            "rank": w["rank"],
            "search_valid_sharpe": w["search_valid_sharpe"],
            "ensemble_valid_sharpe": val,
            "n_seeds": n_seeds,
        })
    extra = (select_winners(ranked, diagnostic_top)[len(winners):]
             if diagnostic_top > len(winners) else [])
    for di, w in enumerate(extra):
        rank = len(winners) + di
        tcfg = dataclasses.replace(ensemble_tcfg, lr=w["lr"])
        log(f"[protocol] diagnostic retrain #{rank}: "
            f"{len(diagnostic_seeds)} seeds, lr={w['lr']}")
        if heartbeat is not None:
            heartbeat.beat("diagnostic_retrain", rank=rank)
        gan, vparams, _hist = train_ensemble(
            w["config"], train_batch, valid_batch, test_batch,
            seeds=diagnostic_seeds, tcfg=tcfg, verbose=False,
            member_chunk=member_chunk, exec_cfg=exec_cfg,
            heartbeat=heartbeat,
        )
        m = ensemble_metrics(gan, vparams, valid_batch)
        diag_points.append({
            "rank": rank,
            "search_valid_sharpe": _finite(w["valid_sharpe"]),
            "ensemble_valid_sharpe": _finite(float(m["ensemble_sharpe"])),
            "n_seeds": len(diagnostic_seeds),
        })
    if len(diag_points) >= 2:
        # None encodes a non-finite tracker (diverged member) — DROP those
        # pairs rather than coercing to 0.0, which would rank a diverged
        # model mid-pack and corrupt the very diagnostic this block records
        pairs = [
            (p["search_valid_sharpe"], p["ensemble_valid_sharpe"])
            for p in diag_points
            if p["search_valid_sharpe"] is not None
            and p["ensemble_valid_sharpe"] is not None
        ]
        spearman = None
        if len(pairs) >= 2:
            sv = np.asarray([p[0] for p in pairs])
            ev = np.asarray([p[1] for p in pairs])

            def _ranks(a):
                r = np.empty(len(a))
                r[np.argsort(a)] = np.arange(len(a))
                return r

            ra, rb = _ranks(sv), _ranks(ev)
            denom = float(np.std(ra) * np.std(rb))
            if denom > 0:
                spearman = float(
                    np.mean((ra - ra.mean()) * (rb - rb.mean())) / denom)
        report["search_vs_retrain"] = {
            "points": diag_points,
            "spearman_rank_correlation": spearman,
            "n_pairs_used": len(pairs),
            "note": "search-rank vs full-schedule-retrain rank agreement "
                    "over the top diagnostic_top distinct settings (the "
                    "winners' full ensembles plus smaller diagnostic "
                    "retrains — n_seeds per point; non-finite entries "
                    "dropped); a low/negative value means the "
                    "quick-schedule search Sharpe would mis-rank candidates "
                    "— on real data, widen the search schedule before "
                    "trusting selection",
        }

    # ---- stage 3: grand ensemble across all winners' members ----
    if heartbeat is not None:
        heartbeat.beat("grand_ensemble")
    grand = ensemble_metrics_from_weights(
        jnp.concatenate(all_test_weights, axis=0), test_batch
    )
    report["grand_ensemble_test_sharpe"] = float(grand["ensemble_sharpe"])
    report["grand_ensemble_test_ev"] = float(grand["explained_variation"])
    report["grand_ensemble_test_xs_r2"] = float(grand["cross_sectional_r2"])
    # actual surviving member count: quorum drops shrink winners' ensembles
    report["n_grand_members"] = int(
        sum(int(w.shape[0]) for w in all_test_weights))
    report["total_seconds"] = round(time.time() - t0, 1)
    if save_dir:
        # verified write (atomic + sha256 sidecar): a kill mid-write can
        # never leave a torn report.json in the artifact dir
        write_verified(save_dir / "report.json",
                       json.dumps(report, indent=2).encode())
    log(f"[protocol] grand ensemble ({report['n_grand_members']} members) "
        f"test sharpe: {report['grand_ensemble_test_sharpe']:.4f}")
    log(f"[protocol] total {report['total_seconds']:.1f}s")
    return report


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Paper protocol: config search → seed ensembles → report"
    )
    p.add_argument("--data_dir", type=str, required=True)
    p.add_argument("--save_dir", type=str, default="./sweep_results")
    p.add_argument("--small_sample", action="store_true")
    p.add_argument("--n_periods", type=int, default=100)
    p.add_argument("--n_stocks", type=int, default=500)

    # search grid (defaults give the paper's 384 combos; --quick shrinks)
    p.add_argument("--quick", action="store_true",
                   help="Tiny grid + short schedules (smoke/demo)")
    p.add_argument("--top_k", type=int, default=4)
    p.add_argument("--search_seeds", type=int, nargs="+", default=[42])
    p.add_argument("--ensemble_seeds", type=int, nargs="+",
                   default=list(PAPER_SEEDS))

    p.add_argument("--resume_ranking", type=str, default=None, metavar="JSON",
                   help="Path to a previously written sweep_ranking.json: "
                        "skip stage 1 (the 384-config search) and go "
                        "straight to the winner ensembles")

    # elastic execution (reliability/ledger.py + scheduler.py)
    p.add_argument("--workers", type=int, default=0, metavar="N",
                   help="Elastic search: run stage 1 as N supervise-wrapped "
                        "worker processes claiming architecture buckets "
                        "from a leased, ledger-backed work queue (dead "
                        "workers' leases expire and their buckets are "
                        "re-claimed; poison buckets quarantine). 0 "
                        "(default) trains buckets in this process")
    p.add_argument("--resume-from-ledger", action="store_true",
                   dest="resume_from_ledger",
                   help="Resume stage 1 from the run dir's bucket ledger: "
                        "completed buckets load from their verified "
                        "records instead of re-training (restart-from-zero "
                        "becomes restart-from-last-bucket; the supervisor "
                        "appends this automatically on sweep restarts)")
    p.add_argument("--metrics_port", type=int, default=None, metavar="PORT",
                   help="Serve live Prometheus metrics on "
                        "http://127.0.0.1:PORT/metrics while the sweep runs "
                        "(read-only stdlib sidecar over the coordinator's "
                        "counters/gauges/span histograms; port 0 picks a "
                        "free one, printed at startup)")
    p.add_argument("--search_only", action="store_true",
                   help="Stop after stage 1: write sweep_ranking.json "
                        "(plus sweep_coverage.json when degraded) and exit")
    p.add_argument("--quorum", type=int, default=None, metavar="Q",
                   help="Ensemble quorum: proceed with ≥Q surviving "
                        "(finite) seed members per winner, dropping "
                        "diverged members (recorded in the report and run "
                        "manifest) instead of failing the protocol on one "
                        "bad seed; fewer than Q survivors is an error")
    p.add_argument("--lease_timeout", type=float, default=120.0, metavar="S",
                   help="Elastic: lease staleness after which a worker's "
                        "claimed bucket is presumed dead and re-claimable")
    p.add_argument("--max_bucket_attempts", type=int, default=3, metavar="K",
                   help="Elastic: claims a bucket may consume without ever "
                        "completing before it is quarantined as poison")
    p.add_argument("--retry_backoff", type=float, default=2.0, metavar="S",
                   help="Elastic: per-bucket retry backoff base (doubles "
                        "per attempt — the supervisor's backoff curve)")
    p.add_argument("--device_slices", type=int, default=0, metavar="S",
                   help="Mesh-packed elastic search: partition the local "
                        "devices into S disjoint contiguous slices; each "
                        "worker leases ONE slice (scheduler device-slice "
                        "lease) and trains its buckets' (lr × seed) grids "
                        "vmapped + sharded over a ('grid',) mesh of that "
                        "slice's devices. 0 (default) = unpacked: workers "
                        "place on the default device as before. Results "
                        "are bit-identical either way")
    p.add_argument("--slice_width", type=int, default=None, metavar="W",
                   help="Devices per slice (default: local device count "
                        "// device_slices)")
    p.add_argument("--bucket_timeout", type=float, default=3600.0,
                   metavar="S",
                   help="Elastic: per-bucket wall budget. While a bucket "
                        "trains, the lease keeper beats the worker "
                        "heartbeat (so long buckets are NOT hang-killed); "
                        "past this budget it goes silent, the worker is "
                        "killed as hung, and the bucket is reclaimed — "
                        "repeated overruns quarantine it")
    p.add_argument("--worker", action="store_true",
                   help="Run as one elastic worker: claim buckets from the "
                        "save_dir's existing queue until drained (normally "
                        "spawned by --workers N, not by hand)")
    p.add_argument("--worker_id", type=str, default=None,
                   help="Stable worker name (events.<id>.jsonl, "
                        "heartbeat.<id>.json)")
    p.add_argument("--worker_heartbeat_timeout", type=float, default=300.0,
                   metavar="S",
                   help="Per-worker supervision: heartbeat staleness that "
                        "counts as a hang (the lease keeper beats through "
                        "a training bucket, so this need not exceed bucket "
                        "time — --bucket_timeout bounds that instead)")
    p.add_argument("--worker_min_uptime", type=float, default=5.0,
                   metavar="S")
    p.add_argument("--worker_max_restarts", type=int, default=5)
    p.add_argument("--worker_backoff", type=float, default=1.0, metavar="S")
    p.add_argument("--diagnostic_top", type=int, default=8,
                   help="Retrain the top-D distinct settings (winners plus "
                        "extra diagnostic retrains) so the search-vs-retrain "
                        "rank correlation has ≥8 pairs; ≤ top_k disables")
    p.add_argument("--diagnostic_seeds", type=int, nargs="+",
                   default=[42, 123, 456])

    # schedules
    p.add_argument("--member_chunk", type=int, default=None,
                   help="Cap members per vmapped program (sequential chunks). "
                        "Rarely needed on TPU — the fused-kernel route costs "
                        "~0.1 GB HBM/member at the real panel shape; the "
                        "plain-XLA route (CPU) needs ~2.1 GB/member")
    p.add_argument("--search_epochs_unc", type=int, default=64)
    p.add_argument("--search_epochs_moment", type=int, default=16)
    p.add_argument("--search_epochs", type=int, default=256)
    p.add_argument("--search_ignore_epoch", type=int, default=16)
    p.add_argument("--epochs_unc", type=int, default=256)
    p.add_argument("--epochs_moment", type=int, default=64)
    p.add_argument("--epochs", type=int, default=1024)
    p.add_argument("--ignore_epoch", type=int, default=64)
    return p


def _worker_main(args) -> int:
    """One elastic sweep worker: claim buckets from the save_dir's queue
    manifest until drained. Spawned (and supervised) by the coordinating
    ``--workers N`` process; everything that must be FLEET-consistent —
    schedule, seeds, grid, lease policy, wire format — comes from the
    manifest, so a worker takes no grid arguments at all."""
    save_dir = Path(args.save_dir)
    wid = args.worker_id or f"w{os.getpid()}"
    events = EventLog(save_dir, filename=f"events.{wid}.jsonl")
    hb = Heartbeat(save_dir / f"heartbeat.{wid}.json", events=events)
    logger = set_run_logger(RunLogger(events=events))
    hb.beat("setup")
    queue = open_work_queue(save_dir, events=events)
    meta = queue.load_manifest()
    logger.info(f"[sweep:{wid}] elastic worker up: "
                f"{len(queue.items())} buckets, devices {jax.devices()}")

    from .data.pipeline import load_splits_chunked
    from .data.transfer import device_put_batch

    with events.span("data/load"):
        train_ds, valid_ds, _test_ds = load_splits_chunked(
            meta.get("data_dir") or args.data_dir, events=events)
    if meta.get("small_sample"):
        train_ds = train_ds.subsample(meta["n_periods"], meta["n_stocks"])
        valid_ds = valid_ds.subsample(
            min(meta["n_periods"], valid_ds.T), meta["n_stocks"])
    bf16_wire = bool(meta.get("bf16_wire", False))
    train_b = device_put_batch(train_ds.full_batch(), bf16_wire=bf16_wire)
    valid_b = device_put_batch(valid_ds.full_batch(), bf16_wire=bf16_wire)

    hb.beat("sweep_wait")
    n = run_sweep_worker(queue, wid, train_b, valid_b, heartbeat=hb)
    hb.beat("done", memory=True)
    logger.info(f"[sweep:{wid}] queue drained; trained {n} buckets")
    events.close()
    return 0


def _prepare_queue(args, configs, search_tcfg, save_dir, events, logger,
                   bf16_wire):
    """The run dir's ledger + work manifest, shared by BOTH stage-1 modes
    (in-process and elastic): writing ``sweep_ledger/queue.json`` even for
    a single-process sweep is what lets a supervised restart detect the
    ledger and auto-append ``--resume-from-ledger``, and lets a later
    ``--workers N`` run adopt a partially completed single-process search.
    With ``--resume-from-ledger`` an existing manifest is kept only when it
    describes THIS sweep — same bucket keys in the same order (keys hash
    config+grid+seeds+schedule); anything else is reset, discarding
    completed records, rather than silently reusing foreign work."""
    from .reliability.scheduler import WorkQueue
    from .reliability.supervisor import RestartPolicy

    ledger = SweepLedger(save_dir / LEDGER_DIRNAME)
    queue = WorkQueue(
        save_dir / LEDGER_DIRNAME, ledger=ledger,
        lease_timeout_s=args.lease_timeout,
        max_attempts=args.max_bucket_attempts,
        backoff=RestartPolicy(backoff_base_s=args.retry_backoff,
                              backoff_max_s=max(30.0, args.retry_backoff)),
        events=events,
    )
    items = bucket_work_items(configs, args.search_seeds, search_tcfg)
    meta = {
        "kind": "sweep_queue",
        "tcfg": dataclasses.asdict(search_tcfg),
        "seeds": [int(s) for s in args.search_seeds],
        "member_chunk": args.member_chunk,
        "bf16_wire": bool(bf16_wire),
        "data_dir": args.data_dir,
        "small_sample": bool(args.small_sample),
        "n_periods": args.n_periods,
        "n_stocks": args.n_stocks,
        "lease_timeout_s": args.lease_timeout,
        "max_attempts": args.max_bucket_attempts,
        "retry_backoff_s": args.retry_backoff,
        "bucket_timeout_s": args.bucket_timeout,
        # mesh packing is FLEET-consistent state: every worker must agree
        # on the device partitioning, so it rides the manifest
        "device_slices": int(getattr(args, "device_slices", 0) or 0),
        "slice_width": getattr(args, "slice_width", None),
    }
    keep = False
    if args.resume_from_ledger and queue.queue_path().exists():
        try:
            old = queue.load_manifest()
            keep = ([it["key"] for it in old.get("items", [])]
                    == [it["key"] for it in items])
        except (ValueError, FileNotFoundError, KeyError):
            keep = False
        if not keep:
            logger.warning(
                "[sweep] existing ledger does not match this grid/schedule; "
                "resetting it (completed records discarded)")
    if not keep:
        ledger.reset()
    # write (or, on resume, REwrite) the manifest: the work list is
    # identical on a kept resume, but this invocation's fleet policy —
    # lease timeout, attempt budget, retry backoff — must win over the
    # stale one, or workers would apply settings the operator just changed
    # away from (records and quarantine markers are untouched either way)
    queue.write_manifest(items, meta)
    return ledger, queue


def _elastic_search(args, queue, save_dir, events, hb, logger):
    """Stage 1 as a supervised worker fleet: run N supervise-wrapped
    ``--worker`` children against the prepared work manifest, reconstruct
    the ranking (and its coverage manifest) from the ledger. Returns
    ``(ranked, coverage, worker summaries)``."""
    from .reliability.faults import ENV_EVENTS, ENV_PLAN, ENV_STATE
    from .reliability.scheduler import run_supervised_workers
    from .reliability.supervisor import RestartPolicy

    items = queue.items()
    status = queue.status()
    if status["completed"]:
        # the fleet-level ledger-hit evidence: this many buckets are being
        # reused from the ledger, not re-trained (workers skip them inside
        # claim(), which scans every item per call — a per-scan counter
        # there would inflate, so the coordinator records the truth once)
        events.counter("sweep/ledger_hit", value=status["completed"])
    logger.info(
        f"[sweep] elastic search: {len(items)} buckets × {args.workers} "
        f"workers (already completed: {status['completed']}, quarantined: "
        f"{status['quarantined']})")

    # fault-plan plumbing (mirrors the supervise CLI): a fleet sharing one
    # state file sees ONE hit stream, so a planned kill fires exactly once
    # across all workers and restarts
    env = dict(os.environ)
    if env.get(ENV_PLAN):
        env.setdefault(ENV_STATE, str(save_dir / "fault_state.json"))
        env.setdefault(ENV_EVENTS, str(save_dir / "events.faults.jsonl"))
    worker_cmds = {
        f"w{i}": [sys.executable, "-m", f"{__package__}.sweep", "--worker",
                  "--worker_id", f"w{i}", "--data_dir", args.data_dir,
                  "--save_dir", str(save_dir)]
        for i in range(args.workers)
    }
    policy = RestartPolicy(
        heartbeat_timeout_s=args.worker_heartbeat_timeout,
        min_uptime_s=args.worker_min_uptime,
        max_restarts=args.worker_max_restarts,
        backoff_base_s=args.worker_backoff,
    )
    summaries: Dict[str, Dict] = {}
    with events.span("sweep/fleet", workers=args.workers,
                     n_buckets=len(items)):
        fleet = threading.Thread(
            target=lambda: summaries.update(run_supervised_workers(
                save_dir, worker_cmds, policy=policy, env=env)),
            name="sweep-fleet")
        fleet.start()
        while fleet.is_alive():
            # the COORDINATOR's liveness: its own supervisor (if any) must
            # see progress while it blocks on the fleet
            hb.beat("sweep_fleet")
            fleet.join(timeout=2.0)
    for wid, summary in sorted(summaries.items()):
        line = (f"[sweep] worker {wid}: outcome={summary['outcome']} "
                f"restarts={summary['restarts']} "
                f"hang_kills={summary['hang_kills']}")
        if summary["outcome"] == "success":
            logger.info(line)
        else:
            logger.warning(line)
    ranked, coverage = ranking_from_ledger(queue)
    if not ranked:
        raise RuntimeError(
            "elastic search completed no buckets at all — see "
            f"{save_dir}/supervised.w*.log and the quarantine markers in "
            f"{save_dir}/{LEDGER_DIRNAME}/quarantine/")
    if not coverage["complete"]:
        logger.warning(
            f"[sweep] DEGRADED completion: {coverage['completed']}/"
            f"{coverage['n_buckets']} buckets "
            f"({len(coverage['quarantined'])} quarantined, "
            f"{len(coverage['missing'])} missing) — the ranking ships "
            "anyway; sweep_coverage.json is the explicit contract")
    return ranked, coverage, summaries


def main(argv=None):
    from .utils.platform import apply_env_platforms

    apply_env_platforms()
    from .utils.cache import enable_compilation_cache

    enable_compilation_cache()
    args = build_arg_parser().parse_args(argv)
    if args.worker:
        return _worker_main(args)

    save_dir = Path(args.save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)
    events = EventLog(save_dir)
    hb = Heartbeat(save_dir / "heartbeat.json", events=events)
    logger = set_run_logger(RunLogger(events=events))
    hb.beat("setup")

    sidecar = None
    if args.metrics_port is not None:
        from .observability import MetricsSidecar

        sidecar = MetricsSidecar([events.metrics], port=args.metrics_port)
        port = sidecar.start()
        logger.info(f"metrics sidecar: http://127.0.0.1:{port}/metrics "
                    "(Prometheus text)")

    logger.info("Paper-protocol sweep (TPU-native)")
    logger.info(f"Devices: {jax.devices()}")
    # cache-aware load through the CHUNKED panel store (data/diskcache.py
    # store_chunked): a re-run of the sweep (the common case while iterating
    # on grids) mmaps the per-shard decode instead of re-paying the npz
    # decompress + mask build, and a torn shard re-decodes alone
    # (bit-identical to load_splits either way)
    from .data.pipeline import load_splits_chunked

    with events.span("data/load"):
        train_ds, valid_ds, test_ds = load_splits_chunked(
            args.data_dir, events=events
        )
    if args.small_sample:
        train_ds = train_ds.subsample(args.n_periods, args.n_stocks)
        valid_ds = valid_ds.subsample(min(args.n_periods, valid_ds.T), args.n_stocks)
        test_ds = test_ds.subsample(min(args.n_periods, test_ds.T), args.n_stocks)

    from .data.transfer import device_put_batch
    from .utils.config import ExecutionConfig

    base = GANConfig(
        macro_feature_dim=train_ds.macro_feature_dim,
        individual_feature_dim=train_ds.individual_feature_dim,
    )
    # mask-packed transfer; bf16 wire when every panel consumer reads bf16
    # (ExecutionConfig.bf16_wire_ok). The paper grid varies hidden_dim/lr/
    # dropout/seed only, never hidden_dim_moment, so `base` decides for all
    # swept configs
    _ec = ExecutionConfig()
    bf16_wire = _ec.bf16_wire_ok(base)

    def batch(ds):
        return device_put_batch(ds.full_batch(), bf16_wire=bf16_wire)

    train_b, valid_b, test_b = batch(train_ds), batch(valid_ds), batch(test_ds)

    if args.quick:
        configs = grid_configs(base, **QUICK_GRID_KW)
        search_tcfg = TrainConfig(
            **QUICK_SEARCH_SCHEDULE, seed=args.search_seeds[0])
        ensemble_tcfg = TrainConfig(**QUICK_ENSEMBLE_SCHEDULE)
        if args.ensemble_seeds == list(PAPER_SEEDS):
            args.ensemble_seeds = [42, 123, 456]
        args.top_k = min(args.top_k, 2)
        args.diagnostic_top = args.top_k  # smoke mode: no extra retrains
    else:
        configs = grid_configs(base)  # the 384-combo paper grid
        search_tcfg = TrainConfig(
            num_epochs_unc=args.search_epochs_unc,
            num_epochs_moment=args.search_epochs_moment,
            num_epochs=args.search_epochs,
            ignore_epoch=args.search_ignore_epoch,
            seed=args.search_seeds[0],
        )
        ensemble_tcfg = TrainConfig(
            num_epochs_unc=args.epochs_unc,
            num_epochs_moment=args.epochs_moment,
            num_epochs=args.epochs,
            ignore_epoch=args.ignore_epoch,
        )

    ranking = load_ranking(args.resume_ranking) if args.resume_ranking else None

    # startup manifest: base config + both schedules + grid size, so the
    # sweep_results dir carries its own provenance
    write_manifest(
        save_dir, "sweep", events=events,
        config=base, tcfg=search_tcfg, seed=args.search_seeds[0],
        data_dir=args.data_dir, argv=argv,
        extra={
            "n_configs": len(configs),
            "quick": bool(args.quick),
            "top_k": args.top_k,
            "ensemble_seeds": list(args.ensemble_seeds),
            "ensemble_train_config": dataclasses.asdict(ensemble_tcfg),
            "resumed_from_ranking": args.resume_ranking,
            "workers": args.workers,
            "resume_from_ledger": bool(args.resume_from_ledger),
            "quorum": args.quorum,
            "device_slices": args.device_slices,
        },
    )
    hb.beat("protocol")

    # stage-1 durability: every completed bucket lands in the run dir's
    # ledger (and the work manifest is written up front), so any restart —
    # supervised auto --resume-from-ledger or manual — resumes from the
    # last completed bucket, not from zero
    if args.device_slices:
        # fail HERE, not as a per-worker crash-restart loop after slice
        # leases are already claimed — THE fit check is slice_devices
        # itself, so the pre-flight can never drift from what the workers
        # enforce
        from .parallel.partition import slice_devices

        try:
            slice_devices(0, args.device_slices, args.slice_width)
        except ValueError as e:
            raise SystemExit(
                f"--device_slices {args.device_slices}"
                + (f" --slice_width {args.slice_width}"
                   if args.slice_width else "")
                + f" does not fit the local devices: {e}") from e
        if args.workers > args.device_slices:
            # legal but worth saying out loud: a worker with no slice lease
            # polls until one frees, so the surplus act as HOT SPARES that
            # only train after another worker dies and its slice expires
            logger.warning(
                f"[sweep] --workers {args.workers} > --device_slices "
                f"{args.device_slices}: {args.workers - args.device_slices} "
                "worker(s) will idle as hot spares until a slice frees")

    coverage = None
    if ranking is None:
        ledger, queue = _prepare_queue(
            args, configs, search_tcfg, save_dir, events, logger, bf16_wire)
        if args.workers > 0:
            ranking, coverage, _summaries = _elastic_search(
                args, queue, save_dir, events, hb, logger)
    else:
        ledger = SweepLedger(save_dir / LEDGER_DIRNAME)

    # single-process mesh packing: one slice spanning the local devices —
    # every bucket's (lr × seed) grid trains vmapped + sharded over it
    # (bit-identical to unpacked; the elastic fleet instead leases one
    # slice per worker via the queue manifest's device_slices)
    grid_mesh = None
    if args.device_slices and args.workers == 0:
        from .parallel.partition import grid_slice_mesh

        grid_mesh = grid_slice_mesh(0, 1, width=args.slice_width)
        logger.info(f"[sweep] mesh-packed grids over "
                    f"{grid_mesh.devices.size} devices")

    if args.search_only:
        stats: Dict = {}
        if ranking is None:
            with events.span("protocol/search", n_combos=len(configs)):
                ranking = run_sweep(
                    configs, args.search_seeds, train_b, valid_b,
                    tcfg=search_tcfg, top_k=None, keep_params=False,
                    member_chunk=args.member_chunk, stats_out=stats,
                    heartbeat=hb, ledger=ledger,
                    consult_ledger=args.resume_from_ledger,
                    grid_mesh=grid_mesh,
                )
        path = write_ranking(save_dir, ranking, coverage)
        if coverage is not None:
            update_manifest(save_dir, search_coverage=coverage)
        if stats.get("program_analyses"):
            # the warmed bucket programs' XLA roofline, into the manifest
            # like the train CLI's phase programs
            update_manifest(save_dir,
                            xla_programs=stats["program_analyses"])
        hb.beat("done", memory=True)
        logger.info(f"[sweep] search-only: ranking ({len(ranking)} points) "
                    f"written to {path}")
        if sidecar is not None:
            sidecar.stop()
        events.close()
        return

    report = run_protocol(
        configs, train_b, valid_b, test_b,
        search_tcfg=search_tcfg, ensemble_tcfg=ensemble_tcfg,
        search_seeds=args.search_seeds,
        ensemble_seeds=args.ensemble_seeds,
        top_k=args.top_k, save_dir=args.save_dir,
        member_chunk=args.member_chunk,
        ranking=ranking,
        diagnostic_top=args.diagnostic_top,
        diagnostic_seeds=args.diagnostic_seeds,
        heartbeat=hb,
        quorum=args.quorum,
        ledger=ledger,
        consult_ledger=args.resume_from_ledger,
        coverage=coverage,
        grid_mesh=grid_mesh,
    )
    # late provenance into the manifest: quorum drops and degraded-search
    # coverage only exist after the protocol ran
    drops = {str(w["rank"]): w["dropped_seeds"]
             for w in report["winners"] if w.get("dropped_seeds")}
    patch = {}
    if drops:
        # distinct key: the startup manifest's "quorum" is the configured
        # int and must keep its type for any consumer reading it back
        patch["quorum_drops"] = {"quorum": args.quorum,
                                 "dropped_members": drops}
    if coverage is not None:
        patch["search_coverage"] = coverage
    progs = (report.get("search_stats") or {}).get("program_analyses")
    if progs:
        # same manifest contract as --search_only and the train CLI: the
        # warmed bucket programs' XLA roofline lands in xla_programs
        patch["xla_programs"] = progs
    if patch:
        update_manifest(save_dir, **patch)
    hb.beat("done", memory=True)
    logger.info(f"\nReport written to {save_dir / 'report.json'}")
    logger.info("Grand ensemble test Sharpe: "
                f"{report['grand_ensemble_test_sharpe']:.4f}")
    if sidecar is not None:
        sidecar.stop()
    events.close()


if __name__ == "__main__":
    main()
