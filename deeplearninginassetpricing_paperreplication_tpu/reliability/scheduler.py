"""Leased multi-worker execution of a sweep's bucket queue.

Classic elastic-training shape (TorchElastic-style leased work queues;
PAPERS.md): N independent worker processes claim buckets from a shared
file-backed queue under heartbeat-stamped leases. Every queue transition
(claim, renew, complete, fail) runs under an ``fcntl.flock`` on one lock
file, so concurrent workers on one host can never double-claim; a worker
that dies or hangs simply stops renewing, its lease expires, and the bucket
is re-claimed by any surviving worker (a **takeover**). A bucket whose
claims keep dying — it killed K consecutive workers — is **quarantined** as
poison instead of crash-looping the fleet, and per-bucket retry delay
follows the supervisor's exponential-backoff policy
(:class:`reliability.supervisor.RestartPolicy`), the same curve a restarted
child gets.

State lives beside the ledger under ``<run_dir>/sweep_ledger/``:

    queue.json            — the ordered work manifest (see ledger.py);
                            mesh-packed fleets carry ``device_slices`` /
                            ``slice_width`` here so every worker agrees on
                            the device partitioning
    leases/<key>.json     — ``{"worker", "ts"}``, atomically replaced on
                            renewal; staleness past ``lease_timeout_s``
                            makes the bucket claimable again
    attempts/<key>.json   — ``{"count", "next_eligible_ts", "history"}``;
                            the count is incremented AT CLAIM TIME so a
                            worker the bucket kills still leaves evidence
    slices/slice<i>.json  — DEVICE-SLICE leases: ``{"worker", "ts"}`` for
                            disjoint contiguous device slices
                            (``parallel.partition.slice_devices``); a
                            worker holds exactly one slice while training
                            and renews it with its bucket lease, so two
                            live workers can never train on the same
                            devices, and a dead worker's slice expires
                            back into the pool like any other lease

Fault sites (ISSUE 5): ``sweep/claim`` fires after a lease is written (a
kill there leaves an orphan lease → exercises expiry + takeover),
``sweep/lease_renew`` fires on every renewal.

IMPORTANT: module level must stay stdlib-only — the coordinating parent
(and tests) drive fleets without importing jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

try:
    import fcntl
except ImportError:  # non-POSIX: queue transitions fall back to atomicity only
    fcntl = None

from .faults import inject
from .ledger import QUEUE_FILENAME, SweepLedger
from .supervisor import RestartPolicy, Supervisor
from .verified import load_verified, write_verified


class LeaseLost(RuntimeError):
    """A renewal found the lease owned by someone else: the bucket was
    taken over (this worker was presumed dead). Abandon the bucket —
    the new owner's result is the one the ledger will record."""


def _read_json(path: Path) -> Optional[Dict[str, Any]]:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def _atomic_write_json(path: Path, obj: Any) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(obj))
    os.replace(tmp, path)


class WorkQueue:
    """The file-locked bucket queue one sweep's workers claim from.

    ``events`` (an ``observability.EventLog`` or anything with a
    ``counter(name, **attrs)`` method) receives the elastic telemetry the
    report CLI aggregates: ``sweep/claim``, ``sweep/retry``,
    ``sweep/lease_takeover``, ``sweep/quarantine``.
    """

    def __init__(
        self,
        root: Union[str, Path],
        ledger: Optional[SweepLedger] = None,
        lease_timeout_s: float = 60.0,
        max_attempts: int = 3,
        backoff: Optional[RestartPolicy] = None,
        events=None,
        self_reclaim_grace_s: float = 1.0,
    ):
        self.root = Path(root)
        self.ledger = ledger if ledger is not None else SweepLedger(self.root)
        self.lease_timeout_s = float(lease_timeout_s)
        # how long the PREVIOUS owner of an expired lease defers before
        # re-claiming its own bucket (see claim() for why)
        self.self_reclaim_grace_s = float(self_reclaim_grace_s)
        self.max_attempts = int(max_attempts)
        self.backoff = backoff if backoff is not None else RestartPolicy(
            backoff_base_s=1.0, backoff_max_s=30.0)
        self.events = events
        self.leases_dir = self.root / "leases"
        self.attempts_dir = self.root / "attempts"
        self.slices_dir = self.root / "slices"
        self._lock_path = self.root / "queue.lock"
        self._items: Optional[List[Dict[str, Any]]] = None

    # -- the work manifest ----------------------------------------------------

    def queue_path(self) -> Path:
        return self.root / QUEUE_FILENAME

    def write_manifest(self, items: Sequence[Dict[str, Any]],
                       meta: Optional[Dict[str, Any]] = None) -> None:
        """Verified write of the ordered work manifest. Every item needs a
        ``key`` (ledger.bucket_key); workers derive ALL work from this file
        so coordinator and fleet can never disagree on the bucket list."""
        manifest = dict(meta or {})
        manifest["items"] = list(items)
        write_verified(self.queue_path(),
                       json.dumps(manifest, indent=2).encode())
        self._items = list(items)

    def load_manifest(self) -> Dict[str, Any]:
        path = self.queue_path()

        def parse(data: bytes) -> Dict[str, Any]:
            try:
                return json.loads(data.decode())
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ValueError(f"corrupt sweep queue manifest {path}: {e}") from e

        manifest, _ = load_verified(path, parse)
        self._items = list(manifest["items"])
        return manifest

    def items(self) -> List[Dict[str, Any]]:
        if self._items is None:
            self.load_manifest()
        return self._items

    # -- locking --------------------------------------------------------------

    @contextmanager
    def _locked(self):
        """Exclusive inter-process lock over every queue transition. Held
        only for small file reads/writes — never across training. A dying
        holder's lock is released by the kernel with its fd (the property
        that makes kill-at-``sweep/claim`` recoverable)."""
        self.root.mkdir(parents=True, exist_ok=True)
        with open(self._lock_path, "w") as f:
            if fcntl is not None:
                fcntl.flock(f, fcntl.LOCK_EX)
            try:
                yield
            finally:
                if fcntl is not None:
                    fcntl.flock(f, fcntl.LOCK_UN)

    # -- lease / attempt files ------------------------------------------------

    def lease_path(self, key: str) -> Path:
        return self.leases_dir / f"{key}.json"

    def attempts_path(self, key: str) -> Path:
        return self.attempts_dir / f"{key}.json"

    def _lease_state(self, key: str, now: float) -> Tuple[Optional[str], bool]:
        """(owner, live) for a bucket's lease; (None, False) when unleased."""
        lease = _read_json(self.lease_path(key))
        if not lease:
            return None, False
        try:
            age = now - float(lease.get("ts", 0.0))
        except (TypeError, ValueError):
            return str(lease.get("worker")), False
        return str(lease.get("worker")), age <= self.lease_timeout_s

    def _counter(self, name: str, **attrs: Any) -> None:
        if self.events is not None:
            self.events.counter(name, **attrs)

    def next_wake_delay(self, default_s: float = 0.5,
                        min_s: float = 0.01,
                        worker: Optional[str] = None) -> float:
        """How long a ``"wait"``-ing worker should sleep before re-polling:
        the time to the NEAREST recovery deadline — a live lease's expiry,
        a retry-backoff window's end, or (for `worker`'s own expired
        leases) the end of its self-reclaim grace — capped at `default_s`.

        An idle worker then wakes within milliseconds of an orphaned lease
        expiring instead of up to a poll interval later, so lease-takeover
        latency is bounded by the claim scan, not the poll cadence — and
        the idle survivor reliably beats the dead owner's restarting
        process (which pays interpreter + data-load startup) to the
        expired lease."""
        now = time.time()
        deadline = None
        for item in self.items():
            key = item["key"]
            if self.ledger.has(key) or self.ledger.is_quarantined(key):
                continue
            lease = _read_json(self.lease_path(key))
            if lease:
                try:
                    exp = float(lease.get("ts", 0.0)) + self.lease_timeout_s
                except (TypeError, ValueError):
                    exp = now
                if exp <= now and worker is not None and (
                        str(lease.get("worker")) == worker):
                    # our own expired lease: claim() defers it until the
                    # self-reclaim grace elapses — that IS our deadline
                    exp = exp + self.self_reclaim_grace_s
                if exp > now:
                    deadline = exp if deadline is None else min(deadline, exp)
                    continue
            att = _read_json(self.attempts_path(key)) or {}
            try:
                ne = float(att.get("next_eligible_ts") or 0.0)
            except (TypeError, ValueError):
                ne = 0.0
            if ne > now:
                deadline = ne if deadline is None else min(deadline, ne)
        if deadline is None:
            return default_s
        return max(min_s, min(default_s, deadline - now + min_s))

    # -- the claim protocol ---------------------------------------------------

    def claim(self, worker: str) -> Tuple[str, Optional[Dict[str, Any]]]:
        """Try to claim the next runnable bucket for `worker`.

        Returns ``(status, item)`` where status is one of

          * ``"claimed"`` — `item` is the bucket to train (its lease is
            held; keep it renewed via :class:`LeaseKeeper`);
          * ``"wait"``    — nothing claimable NOW but live leases or
            backoff windows remain: poll again (a leased bucket may yet
            expire back into the pool);
          * ``"drained"`` — every bucket is completed or quarantined:
            exit cleanly.
        """
        now = time.time()
        with self._locked():
            pending = False
            for item in self.items():
                key = item["key"]
                if self.ledger.has(key) or self.ledger.is_quarantined(key):
                    continue
                owner, live = self._lease_state(key, now)
                if live:
                    pending = True
                    continue
                if owner is not None and owner == worker:
                    # the lease expired in THIS worker's hands — it died
                    # (and was restarted) or stalled past the timeout while
                    # holding the bucket. Defer one grace window past the
                    # expiry so a LIVE sibling takes the orphan over first:
                    # a crash-looping owner must not win the re-claim race
                    # against healthy workers simply because its restart
                    # lands at the expiry instant (the takeover path is the
                    # one that makes fleet progress when a bucket kills its
                    # owner deterministically). With no sibling interested,
                    # the owner claims as soon as the grace elapses.
                    lease = _read_json(self.lease_path(key)) or {}
                    try:
                        exp = float(lease.get("ts", 0.0)) + self.lease_timeout_s
                    except (TypeError, ValueError):
                        exp = now
                    if now < exp + self.self_reclaim_grace_s:
                        pending = True
                        continue
                att = _read_json(self.attempts_path(key)) or {
                    "count": 0, "next_eligible_ts": 0.0, "history": []}
                if int(att["count"]) >= self.max_attempts:
                    # this bucket has now consumed max_attempts claims
                    # without ever completing — poison: quarantine it so
                    # the fleet finishes degraded instead of crash-looping
                    self.ledger.quarantine(key, {
                        "index": item.get("index"),
                        "attempts": int(att["count"]),
                        "history": att.get("history", []),
                    })
                    self._counter("sweep/quarantine", path=key,
                                  bucket=item.get("index"),
                                  attempts=int(att["count"]))
                    continue
                if now < float(att.get("next_eligible_ts") or 0.0):
                    pending = True  # in its retry-backoff window
                    continue
                takeover = owner is not None and owner != worker
                attempt = int(att["count"]) + 1
                # stamp the attempt BEFORE the lease: a worker this bucket
                # kills mid-claim still leaves the evidence quarantine needs
                att["count"] = attempt
                att["next_eligible_ts"] = now + self.backoff.backoff_s(
                    attempt, rng=lambda: 0.0)
                att.setdefault("history", []).append({
                    "worker": worker, "ts": round(now, 3),
                    "takeover": takeover,
                })
                _atomic_write_json(self.attempts_path(key), att)
                _atomic_write_json(self.lease_path(key), {
                    "worker": worker, "ts": now, "attempt": attempt,
                })
                if takeover:
                    self._counter("sweep/lease_takeover", path=key,
                                  bucket=item.get("index"),
                                  from_worker=owner, worker=worker)
                if attempt > 1:
                    self._counter("sweep/retry", path=key,
                                  bucket=item.get("index"), attempt=attempt,
                                  worker=worker)
                self._counter("sweep/claim", path=key,
                              bucket=item.get("index"), worker=worker,
                              attempt=attempt)
                # the fault site fires WITH the lease already on disk: a
                # kill here orphans the lease, which must expire and be
                # taken over — the exact recovery path worth exercising
                inject("sweep/claim", path=key, worker=worker,
                       attempt=attempt)
                return "claimed", dict(item, attempt=attempt)
        return ("wait", None) if pending else ("drained", None)

    def renew(self, key: str, worker: str) -> None:
        """Refresh the lease heartbeat; raises :class:`LeaseLost` when the
        lease is gone or owned by another worker (takeover happened)."""
        inject("sweep/lease_renew", path=key, worker=worker)
        with self._locked():
            lease = _read_json(self.lease_path(key))
            if not lease or str(lease.get("worker")) != worker:
                raise LeaseLost(
                    f"bucket {key[:12]}… lease no longer held by {worker} "
                    f"(now {lease.get('worker') if lease else 'released'})"
                )
            lease["ts"] = time.time()
            _atomic_write_json(self.lease_path(key), lease)

    def complete(self, key: str, worker: str) -> None:
        """Release the lease after the ledger record landed. The attempts
        file is cleared — a completed bucket's history lives in its
        record, and stale failure counts must not poison a future resume."""
        with self._locked():
            lease = _read_json(self.lease_path(key))
            if lease and str(lease.get("worker")) == worker:
                self.lease_path(key).unlink(missing_ok=True)
            self.attempts_path(key).unlink(missing_ok=True)

    def fail(self, key: str, worker: str, error: str = "") -> None:
        """Release a failed claim: the bucket returns to the pool after its
        backoff window, and the error joins its history. The window is
        re-stamped HERE, from the failure time — the claim-time stamp
        (which covers workers that die without reaching fail()) has usually
        already elapsed by the time a slow failure surfaces, and the
        documented exponential retry delay must count from the failure."""
        now = time.time()
        with self._locked():
            lease = _read_json(self.lease_path(key))
            if lease and str(lease.get("worker")) == worker:
                self.lease_path(key).unlink(missing_ok=True)
            att = _read_json(self.attempts_path(key))
            if att is not None:
                hist = att.setdefault("history", [])
                if hist:
                    hist[-1]["error"] = error[:500]
                att["next_eligible_ts"] = now + self.backoff.backoff_s(
                    int(att.get("count") or 1), rng=lambda: 0.0)
                _atomic_write_json(self.attempts_path(key), att)

    # -- device-slice leases ----------------------------------------------------

    def slice_path(self, index: int) -> Path:
        return self.slices_dir / f"slice{int(index)}.json"

    def claim_device_slice(self, worker: str,
                           n_slices: int) -> Optional[int]:
        """Lease one of `n_slices` disjoint device slices for `worker`.

        A worker's mesh is built over the devices of its leased slice
        (``parallel.partition.slice_devices``), so holding the lease IS the
        exclusivity guarantee. Preference order under the queue lock:
        a slice already leased to this worker (a restarted worker reclaims
        its own slice — device state is per-process, so self-reclaim is
        safe here, unlike bucket leases), then the first free or expired
        slice (an expired takeover emits ``sweep/slice_takeover``).
        Returns the slice index, or ``None`` when every slice is held by a
        live worker — poll again; a dying fleet member frees one."""
        now = time.time()
        with self._locked():
            for idx in range(int(n_slices)):
                lease = _read_json(self.slice_path(idx))
                if lease and str(lease.get("worker")) == worker:
                    _atomic_write_json(self.slice_path(idx),
                                       {"worker": worker, "ts": now})
                    return idx
            for idx in range(int(n_slices)):
                lease = _read_json(self.slice_path(idx))
                if lease:
                    try:
                        live = (now - float(lease.get("ts", 0.0))
                                <= self.lease_timeout_s)
                    except (TypeError, ValueError):
                        live = False
                    if live:
                        continue
                    self._counter("sweep/slice_takeover", slice=idx,
                                  from_worker=str(lease.get("worker")),
                                  worker=worker)
                _atomic_write_json(self.slice_path(idx),
                                   {"worker": worker, "ts": now})
                self._counter("sweep/slice_claim", slice=idx, worker=worker)
                return idx
        return None

    def renew_device_slice(self, index: int, worker: str) -> None:
        """Refresh the slice lease; :class:`LeaseLost` when another worker
        took it over (this worker was presumed dead — it must stop
        dispatching onto the slice's devices and re-claim)."""
        with self._locked():
            lease = _read_json(self.slice_path(index))
            if not lease or str(lease.get("worker")) != worker:
                raise LeaseLost(
                    f"device slice {index} no longer held by {worker} "
                    f"(now {lease.get('worker') if lease else 'released'})")
            lease["ts"] = time.time()
            _atomic_write_json(self.slice_path(index), lease)

    def release_device_slice(self, index: int, worker: str) -> None:
        with self._locked():
            lease = _read_json(self.slice_path(index))
            if lease and str(lease.get("worker")) == worker:
                self.slice_path(index).unlink(missing_ok=True)

    # -- fleet-level status ---------------------------------------------------

    def status(self) -> Dict[str, Any]:
        done = quarantined = leased = pending = 0
        now = time.time()
        for item in self.items():
            key = item["key"]
            if self.ledger.has(key):
                done += 1
            elif self.ledger.is_quarantined(key):
                quarantined += 1
            elif self._lease_state(key, now)[1]:
                leased += 1
            else:
                pending += 1
        return {"total": len(self.items()), "completed": done,
                "quarantined": quarantined, "leased": leased,
                "pending": pending}


class LeaseKeeper:
    """Background renewal thread for one claimed bucket.

    Training a bucket is one blocking vmapped dispatch that can far outlive
    the lease timeout, so renewal cannot come from the training thread.
    The keeper renews every ``lease_timeout_s / 3``; on :class:`LeaseLost`
    it stops and flags ``lost`` for the worker to check. A SIGKILLed worker
    takes its keeper with it (same process) — renewals stop, the lease
    expires, and the bucket is taken over: exactly the recovery path.

    `heartbeat` (an ``observability.Heartbeat``): beaten after every
    successful renewal, so a supervising watchdog sees liveness THROUGH a
    bucket whose single dispatch outlives the heartbeat timeout — without
    it, a healthy worker training a long bucket would be hang-killed, its
    re-claims would burn the bucket's attempt budget, and a perfectly good
    bucket would quarantine. `max_lifetime_s` bounds that trust: past the
    per-bucket wall budget the keeper stops renewing AND beating, both
    signals go stale, and the supervisor/lease machinery reclaims the
    bucket — the only way a host can tell a long dispatch from a hung one.
    """

    def __init__(self, queue: WorkQueue, key: str, worker: str,
                 heartbeat=None, heartbeat_section: str = "sweep_bucket",
                 max_lifetime_s: Optional[float] = None,
                 slice_index: Optional[int] = None):
        self.queue = queue
        self.key = key
        self.worker = worker
        self.heartbeat = heartbeat
        self.heartbeat_section = heartbeat_section
        self.max_lifetime_s = max_lifetime_s
        # device-slice lease renewed alongside the bucket lease: a bucket's
        # single dispatch can outlive lease_timeout_s, and the slice must
        # stay held for exactly as long as the devices are in use
        self.slice_index = slice_index
        self.lost = False
        self.slice_lost = False
        self.expired = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"lease-{worker}", daemon=True)

    def _run(self) -> None:
        interval = max(0.05, self.queue.lease_timeout_s / 3.0)
        started = time.time()
        while not self._stop.wait(interval):
            if (self.max_lifetime_s is not None
                    and time.time() - started > self.max_lifetime_s):
                # bucket budget exhausted: presumed hung. Go silent so the
                # watchdog kills this worker and the lease expires.
                self.expired = True
                return
            try:
                self.queue.renew(self.key, self.worker)
            except LeaseLost:
                self.lost = True
                return
            except OSError:
                continue  # transient FS hiccup: retry next tick
            if self.slice_index is not None:
                try:
                    self.queue.renew_device_slice(self.slice_index,
                                                  self.worker)
                except LeaseLost:
                    # the slice was taken over (this worker was presumed
                    # dead). ONLY the slice is gone: the bucket lease is
                    # still validly held and the in-flight dispatch's
                    # result stays bit-identical (placement never changes
                    # values), so keep renewing the bucket lease and
                    # beating the heartbeat — stopping here would let a
                    # sibling re-train the bucket and the watchdog
                    # hang-kill a healthy worker. The worker re-leases a
                    # fresh slice before its next bucket (see
                    # run_sweep_worker's slice_lost handling).
                    self.slice_lost = True
                    self.slice_index = None
                except OSError:
                    pass  # transient; next tick retries
            if self.heartbeat is not None:
                try:
                    self.heartbeat.beat(self.heartbeat_section)
                except OSError:
                    pass  # liveness reporting must not kill the lease

    def __enter__(self) -> "LeaseKeeper":
        self._thread.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def run_supervised_workers(
    run_dir: Union[str, Path],
    worker_cmds: Dict[str, List[str]],
    policy: Optional[RestartPolicy] = None,
    env: Optional[Dict[str, str]] = None,
    events_filename: str = "events.supervisor.{worker}.jsonl",
) -> Dict[str, Dict[str, Any]]:
    """Run one :class:`Supervisor` per worker command, concurrently, and
    return each worker's supervise summary.

    This is the "supervise-wrapped children" layer of the elastic sweep:
    each worker process gets the full watchdog treatment — heartbeat hang
    detection against ``heartbeat.<worker>.json``, SIGKILL of its process
    group, restart with backoff and automatic ``--resume-from-ledger``
    (the supervisor detects the run dir's ledger), crash-loop policy — and
    its own ``events.supervisor.<worker>.jsonl`` so the report CLI counts
    restarts per worker. The fleet outlives any single worker: a
    crash-looped worker ends with outcome ``crash-loop`` while the others
    drain the queue.
    """
    from ..observability.events import EventLog

    run_dir = Path(run_dir)
    summaries: Dict[str, Dict[str, Any]] = {}
    threads = []
    for worker, cmd in worker_cmds.items():
        events = EventLog(run_dir, process_index=0,
                          filename=events_filename.format(worker=worker))
        sup = Supervisor(
            cmd,
            heartbeat_path=run_dir / f"heartbeat.{worker}.json",
            policy=policy,
            events=events,
            log_path=run_dir / f"supervised.{worker}.log",
            env=env,
        )

        def _run(worker=worker, sup=sup, events=events):
            try:
                summaries[worker] = sup.run()
            finally:
                events.close()

        t = threading.Thread(target=_run, name=f"supervise-{worker}")
        threads.append(t)
        t.start()
    for t in threads:
        t.join()
    return summaries
