"""Matmul shape-ceiling microbench: what the MXU sustains on OUR shapes.

The framework's hot matmuls are narrow — [64,46], [64,64], [1,64], [8,224]
rows×contract against a long lane (stock) axis — far from the 128×128 tiles
whose throughput the chip's 197 TFLOP/s bf16 peak is quoted at. Whether the
member-fused ensemble's ~45 achieved TFLOP/s is "50% waste" or "the ceiling
for these shapes" is an empirical property of the hardware (a hand-built
tile-padding model was falsified — see `ops/roofline.py`), so this measures
it: a Pallas kernel with everything VMEM-resident (weights for S members, a
[K, BN] operand tile, an [M, BN] accumulator; constant index maps, so after
the first grid step there is no HBM traffic to hide) that issues the same
member-loop matmul sequence the fused training kernels issue
(`ops/pallas_ffn.py` `_forward_stack`). Grid steps repeat the loop; elapsed
time over useful FLOPs is the sustained per-shape ceiling.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# (rows M, contract K) pairs: the FFN's three layers at paper shape, the
# moment net, and the 128×128 yardstick the chip's peak is quoted at
MODEL_MATMUL_SHAPES: Tuple[Tuple[int, int], ...] = (
    (64, 46), (64, 64), (8, 224), (128, 128),
)


def _ceiling_kernel(w_ref, x_ref, o_ref, *, n_members: int, repeats: int):
    """acc += w[s] @ x for every member, `repeats` times per grid step —
    the member-fused kernels' inner loop with zero memory traffic."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    x = x_ref[...]
    acc = o_ref[...]
    for _ in range(repeats):
        for s in range(n_members):
            acc += jax.lax.dot_general(
                w_ref[s], x, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    o_ref[...] = acc


def measure_matmul_ceiling(
    shapes: Sequence[Tuple[int, int]] = MODEL_MATMUL_SHAPES,
    bn: int = 2048,
    n_members: int = 9,
    repeats_per_step: int = 8,
    grid_steps: int = 64,
    timed_calls: int = 3,
    interpret: bool = False,
) -> Dict[str, Dict]:
    """Sustained bf16→f32 TFLOP/s per (M, K) shape, VMEM-resident.

    Returns {"MxK": {"tflops": ..., "seconds": ..., "flops": ...}} plus a
    "note". Useful FLOPs only (2·M·K·BN per matmul); the 128×128 row is the
    dense yardstick — narrow shapes' ceilings as a fraction of it quantify
    the tile-occupancy cost the model's own dimensions impose.
    """
    out: Dict[str, Dict] = {}
    for m, k in shapes:
        w = jnp.asarray(
            np.random.default_rng(0).standard_normal((n_members, m, k)),
            jnp.bfloat16)
        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((k, bn)), jnp.bfloat16)
        kernel = functools.partial(
            _ceiling_kernel, n_members=n_members, repeats=repeats_per_step)
        fn = jax.jit(pl.pallas_call(
            kernel,
            grid=(grid_steps,),
            in_specs=[
                pl.BlockSpec((n_members, m, k), lambda i: (0, 0, 0)),
                pl.BlockSpec((k, bn), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((m, bn), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((m, bn), jnp.float32),
            interpret=interpret,
        ))
        res = fn(w, x)
        jax.block_until_ready(res)
        np.asarray(res.sum())  # force completion through remote tunnels
        t0 = time.time()
        for _ in range(timed_calls):
            res = fn(w, x)
        np.asarray(res.sum())
        dt = (time.time() - t0) / timed_calls
        flops = 2.0 * m * k * bn * n_members * repeats_per_step * grid_steps
        out[f"{m}x{k}"] = {
            "tflops": round(flops / dt / 1e12, 2),
            "seconds": round(dt, 5),
            "gflops_per_call": round(flops / 1e9, 2),
        }
    dense = out.get("128x128", {}).get("tflops")
    if dense:
        for key, rec in out.items():
            rec["fraction_of_dense_128"] = round(rec["tflops"] / dense, 3)
    out["note"] = (
        f"S={n_members} member-loop matmuls on a VMEM-resident [K, {bn}] "
        "tile (constant index maps, no HBM traffic): the sustained MXU "
        "ceiling for each model matmul shape; 128x128 is the dense "
        "yardstick the chip peak is quoted at")
    return out


def model_shape_ceiling_tflops(ceiling: Dict[str, Dict],
                               F: int = 46,
                               hidden: Sequence[int] = (64, 64),
                               M: int = 178, K: int = 8) -> float:
    """FLOP-weighted harmonic ceiling for one fused FFN+moment forward:
    time = Σ flops_i/ceiling_i, so the blended ceiling is Σf / Σ(f/c).
    (The [1,64] output projection is folded into the [64,64] class — same
    row-padding regime, negligible FLOP share.)"""
    layers = [(h_out, h_in) for h_in, h_out in
              zip([F, *hidden], [*hidden, 1])]
    layers.append((K, F + M))  # moment net

    def rate(m, k):
        for key, rec in ceiling.items():
            if key == f"{m}x{k}":
                return rec["tflops"]
        # nearest measured class: match on contract dim regime
        return ceiling.get("64x64", {}).get("tflops", 50.0)

    total_f, total_t = 0.0, 0.0
    for m, k in layers:
        f = 2.0 * m * k
        total_f += f
        total_t += f / rate(m, k)
    return round(total_f / total_t, 2)
