"""Training-time RNG policy.

Param INIT uses JAX's default threefry keys (high-quality, stable across
versions — `GAN.init`). The TRAINING stream — which only feeds dropout
masks — uses the `rbg` implementation: on TPU, threefry generates bits in
software on the VPU and costs ~20 ms per epoch at the real panel scale
(two [240·10000, 64] bernoulli masks per step), while rbg rides the
hardware RNG at ~1/4 the cost. Dropout only needs i.i.d.-enough masks, not
cryptographic streams, so this is a free 1.7× on the full training loop.

Every code path that seeds a training run (trainer, ensemble, sweep) MUST
build its base key here so that serial/replayed runs stay bit-reproducible
against each other.

Caveat (documented upstream): rbg bit GENERATION is not vmap-invariant —
a vmapped bernoulli draws different bits than the same per-member call
unbatched. Serial-vs-vmapped runs of the SAME seed therefore see different
dropout masks (same distribution). Exact serial↔vmapped parity holds with
dropout=0 and is tested that way (tests/test_parallel.py).
"""

from __future__ import annotations

import jax

# flip to "threefry2x32" to restore the default stream (e.g. when comparing
# against a recorded r01 run)
TRAIN_RNG_IMPL = "rbg"


def train_base_key(seed: int) -> jax.Array:
    """The base training key for a run; all per-epoch dropout keys derive
    from it via `jax.random.split` / `jax.random.fold_in`."""
    return jax.random.key(int(seed), impl=TRAIN_RNG_IMPL)
