"""Device mesh + panel sharding: legacy façade over ``parallel.partition``.

The reference has NO distributed code at all (single device picked at
``/root/reference/src/train.py:193-194``; no torch.distributed/NCCL/MPI
anywhere — SURVEY §2b). The TPU-native replacement is GSPMD: annotate the
[T, N, F] panel's stock axis N with a stock-axis sharding over a 1-D mesh and
`jit` the existing steps unchanged — XLA inserts the `psum`s for the masked
cross-sectional reductions (Σ_i over N in the losses and weight
normalization), riding ICI. Params and macro series are tiny and replicated.

Every sharding here comes from :mod:`parallel.partition` — the single
rule-driven layer that supplies every ``NamedSharding`` in the codebase.
This module keeps the original call-site API (``create_mesh``,
``shard_batch``, ``replicate``) as thin delegates.

Axes:
    'stocks'  — shards N (panel data parallelism; the big arrays)
    'batch'   — legacy name for the member axis (parallel/ensemble.py);
                new code uses partition.MEMBER_AXIS / partition.GRID_AXIS

Multi-host: `jax.distributed.initialize()` + the same code — `jax.devices()`
spans all hosts and GSPMD splits collectives across ICI/DCN automatically.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from .partition import (  # noqa: F401 — re-exported call-site API
    BATCH_AXIS,
    STOCK_AXIS,
    batch_shardings,
    create_2d_mesh,
    create_mesh,
    replicated,
    shard_batch,
)

__all__ = [
    "BATCH_AXIS", "STOCK_AXIS", "batch_sharding", "batch_shardings",
    "create_2d_mesh", "create_mesh", "replicate", "replicated",
    "shard_batch",
]


def batch_sharding(mesh: Mesh, axis_name: str = STOCK_AXIS):
    """Per-field shardings for the canonical batch dict: N sharded, T and
    feature axes replicated, macro fully replicated (legacy name for
    :func:`parallel.partition.batch_shardings`)."""
    return batch_shardings(mesh, axis_name)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the mesh."""
    return jax.device_put(tree, replicated(mesh))
