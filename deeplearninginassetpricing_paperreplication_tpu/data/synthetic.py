"""Seeded synthetic panel generator — fixture factory for tests and benches.

Produces .npz files with the exact schema the loader expects (and the
reference ships: ``/root/reference/src/generate_synthetic_data.py``): a latent
factor model with predictive characteristics, AR(1) macro series, realistic
entry/exit/gap missingness, and the -99.99 sentinel. The implementation here
is vectorized NumPy (the reference loops in Python over t, stocks, features);
outputs are schema-compatible, not bit-identical.

Schema:
    char/Char_{split}.npz : data [T, N, 1+F] (returns in channel 0), date [T]
                            int YYYYMM, variable [1+F] str
    macro/macro_{split}.npz : data [T, M], date [T]
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import Dict, Tuple

import numpy as np

MISSING_VALUE = -99.99


def _ar1(rng: np.random.Generator, T: int, n: int, phi: np.ndarray, vol: np.ndarray) -> np.ndarray:
    """Vectorized AR(1): x_t = phi * x_{t-1} + vol * eps_t, x_0 = vol * eps_0."""
    eps = rng.standard_normal((T, n)) * vol
    out = np.empty((T, n))
    out[0] = eps[0]
    # scan over time (T is small; this loop is O(T) numpy ops, not O(T*n))
    for t in range(1, T):
        out[t] = phi * out[t - 1] + eps[t]
    return out


def _factor_returns(rng: np.random.Generator, T: int, n_factors: int, monthly_vol: float) -> np.ndarray:
    vols = monthly_vol * np.array([1.0, 0.6, 0.5, 0.7, 0.4])[:n_factors]
    return _ar1(rng, T, n_factors, np.full(n_factors, 0.1), vols)


def _loadings(rng: np.random.Generator, N: int, n_factors: int) -> np.ndarray:
    B = rng.standard_normal((N, n_factors))
    B[:, 0] = np.abs(B[:, 0]) + 0.5  # positive market beta
    return B


def _returns(rng: np.random.Generator, F: np.ndarray, B: np.ndarray, idio_vol: float) -> np.ndarray:
    T, N = F.shape[0], B.shape[0]
    idio = rng.standard_normal((T, N)) * (idio_vol * (0.5 + rng.random(N)))
    return F @ B.T + idio


def _characteristics(
    rng: np.random.Generator, T: int, N: int, n_feat: int, B: np.ndarray, noise: float
) -> np.ndarray:
    """Noisy proxies of loadings (predictive) + pure-noise features, then
    winsorized at [5, 95] pct and z-scored cross-sectionally per (t, feature)."""
    n_factors = B.shape[1]
    n_pred = min(n_factors * 2, n_feat // 2)
    chars = rng.standard_normal((T, N, n_feat))
    for i in range(n_pred):
        chars[:, :, i] = (
            B[None, :, i % n_factors]
            + rng.standard_normal((T, N)) * noise
            + rng.standard_normal((T, 1)) * 0.1
        )
    # winsorize + standardize, vectorized over (T, n_feat)
    lo = np.percentile(chars, 5, axis=1, keepdims=True)
    hi = np.percentile(chars, 95, axis=1, keepdims=True)
    chars = np.clip(chars, lo, hi)
    chars = (chars - chars.mean(axis=1, keepdims=True)) / (
        chars.std(axis=1, keepdims=True) + 1e-8
    )
    return chars


def _macro(rng: np.random.Generator, T: int, n_macro: int, F: np.ndarray) -> np.ndarray:
    phi = np.array([0.95, 0.90, 0.98, 0.85, 0.80, 0.92, 0.75, 0.70])
    phi = np.resize(phi, n_macro)
    m = _ar1(rng, T, n_macro, phi, np.full(n_macro, 0.1))
    # a few macro series lead the factors
    k = min(3, n_macro, F.shape[1])
    m[1:, :k] += 0.3 * F[:-1, :k]
    return m


def _missing_mask(
    rng: np.random.Generator, T: int, N: int, avg_coverage: float = 0.7, min_history: int = 12
) -> np.ndarray:
    """Entry/exit spans + random gaps + a per-period coverage floor."""
    max_start = max(0, T - min_history)
    starts = rng.integers(0, max_start + 1, size=N)
    ends = np.array(
        [rng.integers(min(T, s + min_history), T + 1) for s in starts]
    )
    t_idx = np.arange(T)[:, None]
    mask = (t_idx >= starts[None, :]) & (t_idx < ends[None, :])
    # random gaps for long-lived stocks
    for i in np.nonzero(ends - starts > 24)[0]:
        for _ in range(rng.integers(0, 3)):
            g0 = rng.integers(starts[i] + 6, ends[i] - 6)
            mask[g0 : min(g0 + rng.integers(1, 4), ends[i]), i] = False
    # coverage floor
    floor = avg_coverage * 0.5
    for t in range(T):
        short = int(N * floor - mask[t].sum())
        if short > 0:
            off = np.nonzero(~mask[t])[0]
            mask[t, rng.choice(off, min(short, off.size), replace=False)] = True
    return mask


def _dates(start_date: int, T: int) -> np.ndarray:
    year, month = divmod(start_date, 100)
    months = np.arange(T) + (month - 1)
    return (year + months // 12) * 100 + (months % 12 + 1)


def generate_dataset(
    n_periods: int,
    n_stocks: int,
    n_features: int = 46,
    n_macro: int = 8,
    n_factors: int = 5,
    seed: int = 42,
    start_date: int = 196703,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """One split's (char_dict, macro_dict), ready for np.savez."""
    rng = np.random.default_rng(seed)
    F = _factor_returns(rng, n_periods, n_factors, monthly_vol=0.02)
    B = _loadings(rng, n_stocks, n_factors)
    ret = _returns(rng, F, B, idio_vol=0.08)
    chars = _characteristics(rng, n_periods, n_stocks, n_features, B, noise=0.5)
    macro = _macro(rng, n_periods, n_macro, F)
    mask = _missing_mask(rng, n_periods, n_stocks)

    data = np.concatenate([ret[:, :, None], chars], axis=2).astype(np.float32)
    data = np.where(mask[:, :, None], data, np.float32(MISSING_VALUE))
    char_dict = {
        "data": data,
        "date": _dates(start_date, n_periods),
        "variable": np.array(["RET"] + [f"char_{i+1}" for i in range(n_features)]),
    }
    macro_dict = {"data": macro.astype(np.float32), "date": _dates(start_date, n_periods)}
    return char_dict, macro_dict


def generate_all_splits(
    output_dir,
    n_periods_train: int = 120,
    n_periods_valid: int = 30,
    n_periods_test: int = 60,
    n_stocks: int = 1000,
    n_features: int = 46,
    n_macro: int = 8,
    seed: int = 42,
    verbose: bool = True,
    compress: bool = True,
) -> Path:
    """Simulate ONE long panel and slice it into train/valid/test so the three
    splits share factors/loadings/missingness (reference
    generate_synthetic_data.py:482-531 does the same)."""
    output_dir = Path(output_dir)
    (output_dir / "char").mkdir(parents=True, exist_ok=True)
    (output_dir / "macro").mkdir(parents=True, exist_ok=True)

    T_total = n_periods_train + n_periods_valid + n_periods_test
    rng = np.random.default_rng(seed)
    F = _factor_returns(rng, T_total, 5, monthly_vol=0.02)
    B = _loadings(rng, n_stocks, 5)
    ret = _returns(rng, F, B, idio_vol=0.08)
    chars = _characteristics(rng, T_total, n_stocks, n_features, B, noise=0.5)
    macro = _macro(rng, T_total, n_macro, F)
    mask = _missing_mask(rng, T_total, n_stocks)

    bounds = {
        "train": (0, n_periods_train),
        "valid": (n_periods_train, n_periods_train + n_periods_valid),
        "test": (n_periods_train + n_periods_valid, T_total),
    }
    # compress=False writes plain .npz — at real-panel sizes (~0.5 GB/split)
    # single-core deflate dominates generation time for no benefit on a bench
    savez = np.savez_compressed if compress else np.savez
    for split, (a, b) in bounds.items():
        data = np.concatenate([ret[a:b, :, None], chars[a:b]], axis=2).astype(np.float32)
        data = np.where(mask[a:b, :, None], data, np.float32(MISSING_VALUE))
        start = int(_dates(196703, T_total)[a])
        savez(
            output_dir / "char" / f"Char_{split}.npz",
            data=data,
            date=_dates(start, b - a),
            variable=np.array(["RET"] + [f"char_{i+1}" for i in range(n_features)]),
        )
        savez(
            output_dir / "macro" / f"macro_{split}.npz",
            data=macro[a:b].astype(np.float32),
            date=_dates(start, b - a),
        )
        if verbose:
            print(f"  wrote {split}: T={b-a}, N={n_stocks}, F={n_features}, M={n_macro}")
    return output_dir


def generate_panel_split(
    output_dir,
    split: str = "train",
    *,
    n_periods: int,
    n_stocks: int,
    n_features: int = 46,
    n_macro: int = 8,
    seed: int = 42,
    compress: bool = False,
    verbose: bool = False,
) -> Path:
    """ONE split's npz pair at an arbitrary — possibly very large — N: the
    dataplane bench's fixture factory (a 100k-stock panel is ~0.5 GB; three
    shared-factor splits would triple the generation and disk cost for a
    bench that only loads one). Uncompressed by default: single-core
    deflate of hundreds of MB would dominate the bench setup for nothing."""
    output_dir = Path(output_dir)
    (output_dir / "char").mkdir(parents=True, exist_ok=True)
    (output_dir / "macro").mkdir(parents=True, exist_ok=True)
    char_dict, macro_dict = generate_dataset(
        n_periods, n_stocks, n_features, n_macro, seed=seed
    )
    savez = np.savez_compressed if compress else np.savez
    savez(output_dir / "char" / f"Char_{split}.npz", **char_dict)
    savez(output_dir / "macro" / f"macro_{split}.npz", **macro_dict)
    if verbose:
        print(f"  wrote {split}: T={n_periods}, N={n_stocks}, "
              f"F={n_features}, M={n_macro}")
    return output_dir


def main(argv=None):
    p = argparse.ArgumentParser(description="Generate synthetic asset-pricing panel data")
    p.add_argument("--output_dir", type=str, default="./synthetic_data")
    p.add_argument("--n_periods_train", type=int, default=120)
    p.add_argument("--n_periods_valid", type=int, default=30)
    p.add_argument("--n_periods_test", type=int, default=60)
    p.add_argument("--n_stocks", type=int, default=1000)
    p.add_argument("--n_features", type=int, default=46)
    p.add_argument("--n_macro", type=int, default=8)
    p.add_argument("--seed", type=int, default=42)
    args = p.parse_args(argv)
    out = generate_all_splits(
        args.output_dir,
        n_periods_train=args.n_periods_train,
        n_periods_valid=args.n_periods_valid,
        n_periods_test=args.n_periods_test,
        n_stocks=args.n_stocks,
        n_features=args.n_features,
        n_macro=args.n_macro,
        seed=args.seed,
    )
    print(f"Synthetic data written to {out.resolve()}")


if __name__ == "__main__":
    main()
