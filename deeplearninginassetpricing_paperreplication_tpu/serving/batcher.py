"""Micro-batching queue: deadline- and size-triggered, per-bucket lanes,
bounded backpressure.

Concurrent HTTP handler threads submit single month-queries; a dedicated
dispatcher thread coalesces them into per-bucket lanes and flushes a lane
when it reaches ``max_batch`` items (size trigger) OR its oldest item has
waited ``max_delay_s`` (deadline trigger) — so a burst rides one compiled
[B, Nb] program while a lone request never waits longer than the deadline.
Lanes are keyed by the engine's stock bucket: items in one flush share a
compiled program shape, which is what makes coalescing free.

Backpressure is bounded and loud: when ``max_queue`` items are pending
across all lanes, :meth:`submit` raises :class:`QueueFull` immediately
(the server maps it to HTTP 503) instead of growing an unbounded queue in
front of a saturated accelerator.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple


class QueueFull(RuntimeError):
    """Raised by submit() when the batcher's bounded queue is at capacity."""


class MicroBatcher:
    """Coalesce submit()ed items into handler(bucket, items) flushes.

    handler: called ON THE DISPATCHER THREAD with (bucket, [item, ...]) and
    must return one result per item, in order; results (or the raised
    exception) are delivered through each item's Future.
    """

    def __init__(
        self,
        handler: Callable[[Any, List[Any]], List[Any]],
        max_batch: int = 4,
        max_delay_s: float = 0.002,
        max_queue: int = 256,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._handler = handler
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # bucket -> list of (enqueue_monotonic, item, future)
        self._lanes: Dict[Any, List[Tuple[float, Any, Future]]] = {}
        self._pending = 0
        self._closed = False
        self.flushes = 0
        self.rejected = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serving-batcher")
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, bucket: Any, item: Any) -> Future:
        """Enqueue one item into `bucket`'s lane; returns its Future."""
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._pending >= self.max_queue:
                self.rejected += 1
                raise QueueFull(
                    f"{self._pending} requests pending (max_queue="
                    f"{self.max_queue})")
            self._lanes.setdefault(bucket, []).append(
                (time.monotonic(), item, fut))
            self._pending += 1
            self._cond.notify()
        return fut

    def submit_wait(self, bucket: Any, item: Any,
                    timeout: Optional[float] = None) -> Any:
        """submit() and block for the result (the HTTP handler's shape)."""
        return self.submit(bucket, item).result(timeout=timeout)

    # -- dispatcher ----------------------------------------------------------

    def _due_lanes(self, now: float):
        """(ready lanes, seconds until the next deadline or None)."""
        ready, next_deadline = [], None
        for bucket, lane in self._lanes.items():
            if not lane:
                continue
            oldest = lane[0][0]
            if len(lane) >= self.max_batch or now - oldest >= self.max_delay_s:
                ready.append(bucket)
            else:
                deadline = oldest + self.max_delay_s
                if next_deadline is None or deadline < next_deadline:
                    next_deadline = deadline
        return ready, (None if next_deadline is None
                       else max(0.0, next_deadline - now))

    def _run(self):
        while True:
            with self._cond:
                while True:
                    now = time.monotonic()
                    ready, wait = self._due_lanes(now)
                    if ready or (self._closed and self._pending == 0):
                        break
                    self._cond.wait(timeout=wait)
                if self._closed and self._pending == 0 and not ready:
                    return
                flushes = []
                for bucket in ready:
                    lane = self._lanes[bucket]
                    take, rest = lane[:self.max_batch], lane[self.max_batch:]
                    self._lanes[bucket] = rest
                    self._pending -= len(take)
                    flushes.append((bucket, take))
            for bucket, take in flushes:
                self._flush(bucket, take)

    def _flush(self, bucket, take):
        items = [item for _, item, _ in take]
        futures = [fut for _, _, fut in take]
        try:
            results = self._handler(bucket, items)
            if len(results) != len(items):
                raise RuntimeError(
                    f"handler returned {len(results)} results for "
                    f"{len(items)} items")
        except BaseException as e:
            for fut in futures:
                fut.set_exception(e)
            return
        finally:
            self.flushes += 1
        for fut, res in zip(futures, results):
            fut.set_result(res)

    # -- lifecycle -----------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting work, drain pending items, join the dispatcher."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=timeout)

    def pending(self) -> int:
        with self._lock:
            return self._pending
