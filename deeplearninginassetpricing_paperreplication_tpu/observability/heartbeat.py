"""Phase-tagged liveness files in ``bench.py``'s state-file format.

``bench.py``'s parent already solved death attribution for remote-attached
TPUs: the child writes ``{"heartbeat": {"section": <str>, "ts": <float>}}``
into an atomically-replaced JSON state file at every section entry, and the
parent times sections against it, SIGKILLs hangs, and attributes any death
mode (raise, OOM-kill, tunnel hang) to the section the last heartbeat names.
This module is the ONE implementation of that protocol — ``bench.py``
delegates here, and training runs / multihost workers write the same format
so the bench parent (or any watchdog) can supervise them unchanged.

IMPORTANT: module level must stay stdlib-only. ``bench.py``'s parent loads
this file by PATH (bypassing the package ``__init__`` and therefore jax/
flax) so the supervisor keeps its thin, cannot-hang import footprint; the
sibling-module imports below are deferred into the methods that need them.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import TYPE_CHECKING, Any, Dict, Optional

if TYPE_CHECKING:
    from .events import EventLog


def read_state(path) -> Dict[str, Any]:
    """Tolerant read: missing/partial files are an empty state, never a
    raise (the supervisor polls while the child may be mid-write)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def write_state(path, state: Dict[str, Any]) -> None:
    """Atomic tmp+rename: a polling reader never sees a partial write."""
    path = Path(path)
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(state))
    os.replace(tmp, path)


def beat(path, state: Dict[str, Any], section: str) -> Dict[str, Any]:
    """Stamp ``state["heartbeat"]`` for `section` and persist; returns the
    (mutated) state — the exact protocol ``bench.py``'s parent parses."""
    state["heartbeat"] = {"section": section, "ts": time.time()}
    write_state(path, state)
    return state


def last_beat(state: Dict[str, Any]) -> tuple:
    """(section, ts) of the last heartbeat in a state dict, or (None, None).
    Tolerant of malformed heartbeats (a supervisor must never crash on what
    a dying child managed to write)."""
    hb = (state or {}).get("heartbeat")
    if not isinstance(hb, dict):
        return None, None
    section = hb.get("section")
    try:
        ts = float(hb["ts"])
    except (KeyError, TypeError, ValueError):
        ts = None
    return section, ts


def staleness_s(state: Dict[str, Any], now: Optional[float] = None,
                floor_ts: Optional[float] = None) -> Optional[float]:
    """Seconds since the last beat — the supervisor's hang signal.

    `floor_ts` (typically the child's spawn time) bounds the age from below:
    a stale heartbeat inherited from a killed predecessor must not get a
    fresh child SIGKILLed before it can write its own (the same guard
    ``bench.py``'s parent applies). Returns None only when there is neither
    a heartbeat nor a floor to time against.
    """
    _, ts = last_beat(state)
    candidates = [t for t in (ts, floor_ts) if t is not None]
    if not candidates:
        return None
    if now is None:
        now = time.time()
    return max(0.0, now - max(candidates))


def is_stale(state: Dict[str, Any], timeout_s: float,
             now: Optional[float] = None,
             floor_ts: Optional[float] = None) -> bool:
    """True when the heartbeat is older than `timeout_s` (False when no age
    can be computed at all — absence of evidence is not a hang)."""
    age = staleness_s(state, now=now, floor_ts=floor_ts)
    return age is not None and age > timeout_s


class Heartbeat:
    """Periodic liveness writer for one run, bench-parser-compatible.

    Owns its state dict (merged over any existing file so a respawned
    process keeps prior keys) and optionally mirrors each beat — plus a
    device-memory snapshot — into an :class:`EventLog`.
    """

    def __init__(self, path, events: Optional[EventLog] = None):
        self.path = Path(path)
        self.events = events
        self.state = read_state(self.path)

    def beat(self, section: str, memory: bool = False, **extra: Any) -> None:
        """Record liveness in `section`; ``memory=True`` additionally
        snapshots aggregated device memory into the state file and the
        event log (host-side counter reads only — no device sync)."""
        if extra:
            self.state.update(extra)
        if memory:
            from .memory import log_memory  # deferred: see module docstring

            snap = log_memory(self.events, section=section)
            self.state["device_memory"] = {
                "n_devices": snap["n_devices"], "totals": snap["totals"],
            }
        beat(self.path, self.state, section)
        if self.events is not None:
            self.events.emit("heartbeat", section)

    @property
    def section(self) -> Optional[str]:
        return (self.state.get("heartbeat") or {}).get("section")
