from .panel import PanelDataset, load_panel, load_splits
from .pipeline import (
    StartupPipeline,
    load_splits_cached,
    load_splits_chunked,
    stream_batch,
    stream_batch_sharded,
)
from .synthetic import generate_all_splits, generate_dataset
