"""Device mesh + panel sharding: the framework's distribution layer.

The reference has NO distributed code at all (single device picked at
``/root/reference/src/train.py:193-194``; no torch.distributed/NCCL/MPI
anywhere — SURVEY §2b). The TPU-native replacement is GSPMD: annotate the
[T, N, F] panel's stock axis N with a `NamedSharding` over a 1-D mesh and
`jit` the existing steps unchanged — XLA inserts the `psum`s for the masked
cross-sectional reductions (Σ_i over N in the losses and weight
normalization), riding ICI. Params and macro series are tiny and replicated.

Axes:
    'stocks'  — shards N (panel data parallelism; the big arrays)
    'batch'   — shards ensemble seeds / sweep configs (parallel/ensemble.py)

Multi-host: `jax.distributed.initialize()` + the same code — `jax.devices()`
spans all hosts and GSPMD splits collectives across ICI/DCN automatically.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Batch = Dict[str, jax.Array]

STOCK_AXIS = "stocks"
BATCH_AXIS = "batch"


def create_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = STOCK_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over (up to) all local devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"create_mesh: requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def create_2d_mesh(
    n_batch: int,
    n_stocks: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """('batch', 'stocks') mesh: ensemble/sweep members × panel shards."""
    if devices is None:
        devices = jax.devices()
    total = len(devices)
    if n_stocks is None:
        n_stocks = total // n_batch
    if n_batch < 1 or n_stocks < 1 or n_batch * n_stocks > total:
        raise ValueError(
            f"mesh {n_batch}x{n_stocks} needs {max(n_batch, 1) * max(n_stocks, 1)} "
            f"devices, have {total}"
        )
    grid = np.array(devices[: n_batch * n_stocks]).reshape(n_batch, n_stocks)
    return Mesh(grid, (BATCH_AXIS, STOCK_AXIS))


def batch_sharding(mesh: Mesh, axis_name: str = STOCK_AXIS) -> Dict[str, NamedSharding]:
    """Per-field shardings for the canonical batch dict: N sharded, T and
    feature axes replicated, macro fully replicated."""
    return {
        "returns": NamedSharding(mesh, P(None, axis_name)),
        "mask": NamedSharding(mesh, P(None, axis_name)),
        "individual": NamedSharding(mesh, P(None, axis_name, None)),
        "individual_t": NamedSharding(mesh, P(None, None, axis_name)),
        "macro": NamedSharding(mesh, P(None, None)),
        "n_assets": NamedSharding(mesh, P()),
    }


def shard_batch(batch: Batch, mesh: Mesh, axis_name: str = STOCK_AXIS) -> Batch:
    """device_put each field with its stock-axis sharding. N must divide the
    mesh size — use PanelDataset.pad_stocks(mesh.devices.size) first."""
    sh = batch_sharding(mesh, axis_name)
    out = {}
    for k, v in batch.items():
        sharded_dim = {"returns": 1, "mask": 1, "individual": 1,
                       "individual_t": 2}.get(k)
        n = v.shape[sharded_dim] if sharded_dim is not None else None
        if n is not None and n % mesh.shape[axis_name] != 0:
            raise ValueError(
                f"batch[{k!r}] stock axis {n} not divisible by mesh axis "
                f"{mesh.shape[axis_name]}; pad with PanelDataset.pad_stocks()"
            )
        out[k] = jax.device_put(v, sh[k])
    return out


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the mesh."""
    sh = NamedSharding(mesh, P())
    return jax.device_put(tree, sh)
