"""Load-adaptive fleet tier-1 suite (CPU, loopback only).

Covers the PR-12 acceptance criteria:
  * deadline-aware admission with priority lanes: interactive admitted at
    queue depths where bulk is shed with 429 + ``Retry-After``, expired
    deadlines shed FIRST (at admission and at flush-take), interactive
    flushes preempt bulk lanes, and an interactive arrival at a full
    queue evicts the newest queued bulk item instead of 503ing;
  * single-flight request coalescing: concurrent identical (month,
    universe digest, params fingerprint) queries collapse onto ONE
    dispatch, waiters never observe a mixed-generation result across a
    concurrent ``/v1/reload`` hot-swap, and post-swap identical queries
    MISS the in-flight map (fingerprint-keyed);
  * the autoscaler control loop: hysteresis before a scale event, cooldown
    against flap, shed-rate and queue-depth triggers, min/max floors, the
    ``fleet/scale`` fault site, and the decisions ring riding
    FlightRecorder dumps (with 429s counting toward the burst trigger);
  * live fleet scaling: ``ReplicaFleet.add_replica`` + ``/v1/drain``
    graceful scale-down (clean rc-0 exit, supervisor outcome ``success``)
    with ``fleet.json`` atomically tracking the live layout;
  * the tier-1 fault matrix: a replica SIGKILLed mid-swing under a
    10x open-loop rate swing with the autoscaler live — zero interactive
    requests lost, the kill attributed, the replica replaced;
plus the loadgen's mid-run rate-swing schedule + per-priority-class
accounting, the report CLI's shed/coalesce/scale subsections, the
BENCH_LOADADAPT.json artifact bars, and the ruff lint gate.
"""

import asyncio
import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
from deeplearninginassetpricing_paperreplication_tpu.serving import (
    AutoscalePolicy,
    Autoscaler,
    ContinuousBatcher,
    FleetController,
    FlightRecorder,
    InferenceEngine,
    QueueFull,
    ReplicaFleet,
    ServingService,
    Shed,
    pick_free_port,
    priority_for,
    read_fleet_json,
    run_ladder,
    server_child_argv,
    write_fleet_json,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.fleet import (
    REPLICA_POLICY,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.loadgen import (
    binary_payload_bytes,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.server import (
    BINARY_CONTENT_TYPE,
    build_arg_parser,
    deadline_from_header,
)
from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
    save_params,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import GANConfig

REPO = Path(__file__).resolve().parents[1]
PKG = "deeplearninginassetpricing_paperreplication_tpu"

T, N, F, M = 12, 64, 10, 6


def _make_cfg(**overrides):
    base = dict(macro_feature_dim=M, individual_feature_dim=F,
                hidden_dim=(8, 8), num_units_rnn=(4,))
    base.update(overrides)
    return GANConfig(**base)


def _write_member(d: Path, cfg: GANConfig, seed: int):
    d.mkdir(parents=True, exist_ok=True)
    cfg.save(d / "config.json")
    save_params(d / "best_model_sharpe.msgpack",
                GAN(cfg).init(jax.random.key(seed)))
    return str(d)


@pytest.fixture(scope="module")
def serve_cfg():
    return _make_cfg()


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(11)
    return {
        "macro": rng.standard_normal((T, M)).astype(np.float32),
        "individual": rng.standard_normal((T, N, F)).astype(np.float32),
        "returns": (rng.standard_normal((T, N)) * 0.05).astype(np.float32),
        "mask": (rng.random((T, N)) > 0.15).astype(np.float32),
    }


def _run_async(coro):
    return asyncio.run(coro)


# --------------------------------------------------------------------------
# batcher admission: priority lanes, deadlines, DAGOR shedding
# --------------------------------------------------------------------------


def test_interactive_admitted_where_bulk_is_shed():
    """THE admission-order contract: at a queue depth past the bulk
    threshold, a bulk submit raises Shed (→ 429) while an interactive
    submit at the same depth is admitted and served."""
    gate = threading.Event()

    def handler(bucket, items):
        gate.wait(timeout=10)
        return list(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=1, max_queue=4,
                               bulk_threshold=0.5)  # bulk_max = 2
        first = asyncio.ensure_future(cb.submit("b", 0))
        await asyncio.sleep(0.1)  # flush #1 in flight, queue empty
        held = [asyncio.ensure_future(cb.submit("b", i)) for i in (1, 2)]
        await asyncio.sleep(0.05)  # pending == 2 == bulk_max
        with pytest.raises(Shed) as e:
            await cb.submit("b", 3, priority="bulk")
        assert e.value.reason == "bulk_shed"
        assert e.value.retry_after_s >= 1.0
        # interactive at the SAME depth is admitted
        ok = asyncio.ensure_future(cb.submit("b", 4))
        await asyncio.sleep(0.05)
        gate.set()
        out = await asyncio.gather(first, *held, ok)
        await cb.aclose()
        return out, cb

    out, cb = _run_async(body())
    assert out == [0, 1, 2, 4]
    assert cb.shed == {"bulk_shed": 1}


def test_interactive_preempts_bulk_lanes():
    """With both lanes non-empty, every interactive item flushes before
    any bulk item — even when the bulk item is OLDER."""
    gate = threading.Event()
    served = []

    def handler(bucket, items):
        served.extend(items)
        if len(served) == 1:
            gate.wait(timeout=10)
        return list(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=1, max_queue=16)
        warm = asyncio.ensure_future(cb.submit("b", "warm"))
        await asyncio.sleep(0.1)
        futs = [asyncio.ensure_future(
            cb.submit("b", "bulk0", priority="bulk"))]
        await asyncio.sleep(0.02)  # bulk enqueued FIRST (older head)
        futs += [asyncio.ensure_future(cb.submit("b", f"int{i}"))
                 for i in range(2)]
        await asyncio.sleep(0.02)
        gate.set()
        await asyncio.gather(warm, *futs)
        await cb.aclose()

    _run_async(body())
    assert served == ["warm", "int0", "int1", "bulk0"]


def test_expired_deadline_shed_not_served():
    """A queued item whose deadline passes while it waits is shed at
    flush-take (never dispatched); a dead-on-arrival deadline is shed at
    admission. Live items around it are served normally."""
    gate = threading.Event()
    served = []

    def handler(bucket, items):
        served.extend(items)
        if len(served) == 1:
            gate.wait(timeout=10)
        return list(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=4, max_queue=16)
        warm = asyncio.ensure_future(cb.submit("b", "warm"))
        await asyncio.sleep(0.1)
        # expires while the first flush is still on the device
        doomed = asyncio.ensure_future(cb.submit(
            "b", "doomed", deadline=time.monotonic() + 0.05))
        alive = asyncio.ensure_future(cb.submit(
            "b", "alive", deadline=time.monotonic() + 30.0))
        await asyncio.sleep(0.3)  # doomed's deadline passes in the queue
        with pytest.raises(Shed) as e:
            await cb.submit("b", "doa", deadline=time.monotonic() - 1.0)
        assert e.value.reason == "deadline_expired"
        gate.set()
        assert await warm == "warm"
        assert await alive == "alive"
        with pytest.raises(Shed) as e2:
            await doomed
        assert e2.value.reason == "deadline_expired"
        await cb.aclose()
        return cb

    cb = _run_async(body())
    assert "doomed" not in served  # never reached the handler
    assert cb.shed["deadline_expired"] == 2


def test_interactive_evicts_newest_bulk_at_full_queue():
    """An interactive arrival at a FULL queue sheds the newest queued
    bulk item to make room instead of 503ing; with no bulk to evict it
    still raises QueueFull."""
    gate = threading.Event()

    def handler(bucket, items):
        gate.wait(timeout=10)
        return list(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=1, max_queue=2,
                               bulk_threshold=1.0)  # bulk admitted to full
        first = asyncio.ensure_future(cb.submit("b", 0))
        await asyncio.sleep(0.1)
        kept_bulk = asyncio.ensure_future(
            cb.submit("b", "bulk_old", priority="bulk"))
        evicted = asyncio.ensure_future(
            cb.submit("b", "bulk_new", priority="bulk"))
        await asyncio.sleep(0.05)  # pending == 2 == max_queue
        winner = asyncio.ensure_future(cb.submit("b", "interactive"))
        await asyncio.sleep(0.05)
        with pytest.raises(Shed) as e:
            await evicted  # the NEWEST bulk item lost its slot
        assert e.value.reason == "bulk_evicted"
        # the next interactive at the full queue evicts the REMAINING
        # bulk item too; only then, with nothing left to shed, does an
        # interactive arrival get the flat QueueFull 503
        overflow = asyncio.ensure_future(cb.submit("b", "overflow"))
        await asyncio.sleep(0.05)
        with pytest.raises(Shed) as e2:
            await kept_bulk
        assert e2.value.reason == "bulk_evicted"
        with pytest.raises(QueueFull):
            await cb.submit("b", "overflow2")
        gate.set()
        out = await asyncio.gather(first, winner, overflow)
        await cb.aclose()
        return out, cb

    out, cb = _run_async(body())
    assert out == [0, "interactive", "overflow"]
    assert cb.shed == {"bulk_evicted": 2}
    assert cb.rejected == 1


# --------------------------------------------------------------------------
# the priority/deadline header contract
# --------------------------------------------------------------------------


def test_priority_header_contract():
    assert priority_for("/v1/weights", None) == "interactive"
    assert priority_for("/v1/weights", "bulk") == "bulk"
    assert priority_for("/v1/weights", "BULK ") == "bulk"
    assert priority_for("/v1/scenarios/grid", None) == "bulk"
    assert priority_for("/v1/bulk/backfill", None) == "bulk"
    # a typo falls back to the path default, never crashes
    assert priority_for("/v1/weights", "urgent!!") == "interactive"
    assert priority_for("/v1/scenarios", "nonsense") == "bulk"


def test_deadline_header_contract():
    t0 = 100.0
    assert deadline_from_header(None, t0) is None
    assert deadline_from_header("250", t0) == pytest.approx(100.25)
    assert deadline_from_header("0", t0) is None
    assert deadline_from_header("-5", t0) is None
    assert deadline_from_header("not-a-number", t0) is None


def test_http_shed_is_429_with_retry_after(tmp_path, serve_cfg, panel):
    """Through the real async HTTP front end: bulk past the threshold gets
    429 + Retry-After (header AND body), interactive at the same depth is
    served, and the shed tally reaches /metrics and the events plane."""
    from deeplearninginassetpricing_paperreplication_tpu.serving import (
        AsyncServerThread,
    )

    dirs = [_write_member(tmp_path / "m1", serve_cfg, 1)]
    eng = InferenceEngine(dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1,))
    svc = ServingService(eng, mode="async", max_queue=4, max_batch=1,
                         bulk_threshold=0.5, cache_size=0,
                         run_dir=str(tmp_path / "run"))
    gate = threading.Event()
    real = svc._handle_batch

    def slow(bucket, items):
        gate.wait(timeout=30)
        return real(bucket, items)

    svc._handle_batch = slow
    server = AsyncServerThread(svc)
    port = server.start()
    url = f"http://127.0.0.1:{port}/v1/weights"

    def post(i, pr):
        body = json.dumps({
            "individual": (panel["individual"][0] + i).tolist(),
            "month": 0}).encode()
        req = urllib.request.Request(url, data=body, headers={
            "Content-Type": "application/json",
            "x-dlap-priority": pr}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, dict(r.headers), json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())

    results = {}

    def worker(i, pr):
        results[i] = post(i, pr)

    threads = [threading.Thread(target=worker, args=(0, "interactive"))]
    threads[0].start()
    time.sleep(0.3)  # in flight; queue empty again
    for i in (1, 2):  # fill to bulk_max == 2
        t = threading.Thread(target=worker, args=(i, "interactive"))
        t.start()
        threads.append(t)
        time.sleep(0.1)
    t = threading.Thread(target=worker, args=(3, "bulk"))
    t.start()
    threads.append(t)
    t = threading.Thread(target=worker, args=(4, "interactive"))
    t.start()
    threads.append(t)
    time.sleep(0.3)
    gate.set()
    for t in threads:
        t.join()
    st, headers, body = results[3]
    assert st == 429
    assert body["reason"] == "bulk_shed"
    assert int(headers["Retry-After"]) >= 1
    assert body["retry_after_s"] >= 1
    assert "_retry_after" not in body  # transport hint never leaks
    for i in (0, 1, 2, 4):
        assert results[i][0] == 200, results[i]
    m = svc.metrics()
    assert m["batcher"]["shed"] == {"bulk_shed": 1}
    assert m["batcher"]["bulk_max"] == 2
    assert "429" in json.dumps(m["requests"])
    server.stop()
    svc.close()


# --------------------------------------------------------------------------
# single-flight coalescing
# --------------------------------------------------------------------------


def test_coalesce_concurrent_identical_one_dispatch(tmp_path, serve_cfg,
                                                    panel):
    """N concurrent identical queries -> ONE engine dispatch; every waiter
    gets the same bytes; distinct queries are not coalesced."""
    dirs = [_write_member(tmp_path / "m1", serve_cfg, 1)]
    eng = InferenceEngine(dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1, 2, 4))
    svc = ServingService(eng, mode="async", cache_size=0)
    gate = threading.Event()
    real = svc._handle_batch

    def slow(bucket, items):
        gate.wait(timeout=30)
        return real(bucket, items)

    svc._handle_batch = slow
    payload = {"individual": panel["individual"][2].tolist(), "month": 2}
    other = {"individual": panel["individual"][5].tolist(), "month": 5}

    async def body():
        svc.start_async()
        same = [asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights", payload)) for _ in range(5)]
        distinct = asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights", other))
        await asyncio.sleep(0.4)
        assert len(svc._inflight) == 2  # one per distinct key
        gate.set()
        out = await asyncio.gather(*same, distinct)
        await svc.cbatcher.aclose()
        return out

    out = _run_async(body())
    assert all(st == 200 for st, _ in out)
    weights = {json.dumps(b["weights"]) for _, b in out[:5]}
    assert len(weights) == 1  # every waiter shares the owner's result
    assert svc.coalesce_hits == 4
    assert svc.coalesce_dispatches == 2
    # only the TWO distinct items ever reached the batcher (they may ride
    # one batched flush together — batching composes with coalescing)
    assert svc.cbatcher.items_flushed == 2
    assert not svc._inflight  # flights retire with their dispatch
    svc.close()


def test_coalesce_waiters_never_mix_generations_across_hot_swap(
        tmp_path, serve_cfg, panel):
    """THE coalesce/hot-swap contract: waiters coalesced onto a flight
    that a /v1/reload overlaps all observe ONE consistent generation, a
    post-swap identical query can NEVER join the pre-swap flight (the
    fingerprint in the key rotated -> second in-flight entry), and after
    the flights retire a fresh identical query misses the map."""
    dirs = [_write_member(tmp_path / f"m{s}", serve_cfg, s) for s in (1, 2)]
    eng = InferenceEngine(dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1, 2, 4))
    svc = ServingService(eng, mode="async", cache_size=0)
    gate = threading.Event()
    real = svc._handle_batch

    def slow(bucket, items):
        gate.wait(timeout=30)
        return real(bucket, items)

    svc._handle_batch = slow
    payload = {"individual": panel["individual"][3].tolist(), "month": 3}
    fp_before = eng.params_fingerprint

    def do_reload():
        # rolling re-estimation lands a new checkpoint, then hot-swaps
        save_params(Path(dirs[0]) / "best_model_sharpe.msgpack",
                    GAN(serve_cfg).init(jax.random.key(77)))
        return svc._reload_endpoint({})

    async def body():
        svc.start_async()
        loop = asyncio.get_running_loop()
        pre = [asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights", payload)) for _ in range(4)]
        await asyncio.sleep(0.4)
        assert len(svc._inflight) == 1
        pre_key = next(iter(svc._inflight))
        # hot-swap WHILE the coalesced flight is gated mid-dispatch
        reload_out = await loop.run_in_executor(None, do_reload)
        assert reload_out["swapped"] is True
        # an identical query AFTER the swap: new fingerprint -> new key ->
        # it cannot join the pre-swap flight
        post = asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights", payload))
        await asyncio.sleep(0.3)
        assert len(svc._inflight) == 2
        post_key = [k for k in svc._inflight if k != pre_key][0]
        assert pre_key[1] == fp_before
        assert post_key[1] == eng.params_fingerprint != fp_before
        gate.set()
        out_pre = await asyncio.gather(*pre)
        out_post = await post
        # retired flights leave the map: a fresh identical query misses
        assert not svc._inflight
        d0 = eng.stats()["dispatches"]
        fresh = await svc.handle_async("POST", "/v1/weights", payload)
        assert eng.stats()["dispatches"] == d0 + 1
        await svc.cbatcher.aclose()
        return out_pre, out_post, fresh

    out_pre, out_post, fresh = _run_async(body())
    assert all(st == 200 for st, _ in out_pre)
    # every coalesced waiter observed the SAME generation's bytes
    pre_weights = {json.dumps(b["weights"]) for _, b in out_pre}
    assert len(pre_weights) == 1
    assert out_post[0] == 200 and fresh[0] == 200
    # post-swap queries agree with each other (the new generation)
    assert out_post[1]["weights"] == fresh[1]["weights"]
    assert svc.coalesce_hits == 3  # only the pre-swap twins coalesced
    svc.close()


def test_coalesce_waiter_not_shed_for_owners_admission_fate(
        tmp_path, serve_cfg, panel):
    """An owner shed on ITS admission identity (deadline expired in the
    queue) must not 429 its coalesced waiters: the waiter — which had no
    deadline — re-dispatches under its own identity and is served. Also:
    flights are priority-segregated (an interactive twin never joins a
    bulk flight)."""
    dirs = [_write_member(tmp_path / "m1", serve_cfg, 1)]
    eng = InferenceEngine(dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1,))
    svc = ServingService(eng, mode="async", cache_size=0, max_batch=1)
    gate = threading.Event()
    real = svc._handle_batch

    def slow(bucket, items):
        gate.wait(timeout=30)
        return real(bucket, items)

    svc._handle_batch = slow
    payload = {"individual": panel["individual"][1].tolist(), "month": 1}

    async def body():
        svc.start_async()
        warm = asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights",
            {"individual": panel["individual"][0].tolist(), "month": 0}))
        await asyncio.sleep(0.3)  # warm flush on the device, gated
        # owner: 80 ms deadline — it will expire while gated in the queue
        owner = asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights", payload, deadline_ms="80"))
        await asyncio.sleep(0.1)
        # same payload, NO deadline: coalesces onto the doomed flight
        waiter = asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights", payload))
        # a bulk twin must NOT join the interactive flight (segregation)
        bulk_twin = asyncio.ensure_future(svc.handle_async(
            "POST", "/v1/weights", payload, priority="bulk"))
        await asyncio.sleep(0.2)
        # 3 flights: warm's, the doomed interactive one, the bulk twin's
        # (priority-segregated — the bulk twin did NOT join the
        # interactive flight for the same payload)
        assert len(svc._inflight) == 3
        assert sorted(k[-1] for k in svc._inflight) == [
            "bulk", "interactive", "interactive"]
        gate.set()
        out = await asyncio.gather(warm, owner, waiter, bulk_twin)
        await svc.cbatcher.aclose()
        return out

    (st_w, _), (st_o, body_o), (st_wait, body_wait), (st_b, _) = \
        _run_async(body())
    assert st_w == 200
    assert st_o == 429 and body_o["reason"] == "deadline_expired"
    # THE contract: the no-deadline waiter was served, not 429'd
    assert st_wait == 200 and body_wait["n"] == N
    assert st_b == 200
    svc.close()


# --------------------------------------------------------------------------
# autoscaler control loop (fake controller: no processes)
# --------------------------------------------------------------------------


class FakeController:
    def __init__(self, n=1):
        self.n = n
        self.depth = 0.0
        self.requests = {}
        self.p99 = 5.0
        self.ups = 0
        self.downs = 0
        self.downed = []

    def replica_ids(self):
        return list(range(self.n))

    def metrics(self, rid):
        return {"batcher": {"pending": self.depth},
                "latency": {"p99_ms": self.p99},
                "requests": dict(self.requests)}

    def scale_up(self, ready_timeout_s=0.0):
        self.n += 1
        self.ups += 1
        return self.n - 1

    def scale_down(self, rid, drain_timeout_s=0.0):
        self.n -= 1
        self.downs += 1
        self.downed.append(rid)
        return "drained"


def _policy(**kw):
    base = dict(min_replicas=1, max_replicas=3, up_queue_depth=8.0,
                up_shed_rate=0.02, down_queue_depth=1.0, up_hysteresis=2,
                down_hysteresis=3, cooldown_s=0.0)
    base.update(kw)
    return AutoscalePolicy(**base)


def test_autoscaler_hysteresis_and_floors():
    f = FakeController()
    a = Autoscaler(f, _policy())
    for _ in range(6):  # quiet at the floor: never below min
        assert a.tick()["action"] == "hold"
    assert f.n == 1
    f.depth = 20.0
    assert a.tick()["action"] == "hold"  # first over-tick: hysteresis
    d = a.tick()
    assert d["action"] == "up" and d["reason"].startswith("queue_depth")
    a.tick()
    assert a.tick()["action"] == "up"
    assert f.n == 3
    for _ in range(4):  # at max: no more ups
        a.tick()
    assert f.n == 3
    f.depth = 0.0
    acts = [a.tick()["action"] for _ in range(8)]
    assert f.n == 1 and acts.count("down") == 2
    # scale-down removes the HIGHEST live id first
    assert f.downed == [2, 1]
    assert a.scale_ups == 2 and a.scale_downs == 2


def test_autoscaler_shed_rate_trigger_and_counter_deltas():
    f = FakeController()
    a = Autoscaler(f, _policy())
    f.requests = {"/v1/weights 200": 100}
    a.tick()  # establishes the per-replica baseline
    f.requests = {"/v1/weights 200": 150, "/v1/weights 429": 10}
    d = a.tick()
    assert d["shed_delta"] == 10 and d["shed_rate"] > 0.02
    f.requests = {"/v1/weights 200": 160, "/v1/weights 429": 30}
    d = a.tick()
    assert d["action"] == "up" and d["reason"].startswith("shed_rate")
    # a restarted replica's counter RESET must not read as negative load
    f.requests = {"/v1/weights 200": 5}
    d = a.tick()
    assert d["shed_delta"] == 0 and d["request_delta"] >= 0


def test_autoscaler_cooldown_blocks_flapping():
    f = FakeController()
    a = Autoscaler(f, _policy(cooldown_s=60.0, up_hysteresis=1))
    f.depth = 50.0
    assert a.tick()["action"] == "up"
    for _ in range(5):  # still overloaded, but inside the cooldown
        d = a.tick()
        assert d["action"] == "hold" and d.get("cooldown")
    assert f.n == 2


def test_autoscaler_fault_site_fails_one_event_not_the_loop(monkeypatch):
    monkeypatch.setenv("DLAP_FAULT_PLAN", json.dumps([
        {"site": "fleet/scale", "action": "raise", "trigger_count": 1}]))
    from deeplearninginassetpricing_paperreplication_tpu.reliability import (
        faults,
    )

    faults.reset_injector()
    try:
        f = FakeController()
        a = Autoscaler(f, _policy(up_hysteresis=1))
        f.depth = 50.0
        d = a.tick()
        assert d["action"] == "up_failed" and "FaultInjected" in d["error"]
        assert f.n == 1  # the fleet never mutated
        d = a.tick()  # the loop survives and retries
        assert d["action"] == "up" and f.n == 2
    finally:
        monkeypatch.delenv("DLAP_FAULT_PLAN")
        faults.reset_injector()


def test_autoscaler_decisions_ride_flightrecorder_dump(tmp_path):
    fr = FlightRecorder(run_dir=tmp_path)
    f = FakeController()
    a = Autoscaler(f, _policy(up_hysteresis=1), flight=fr)
    f.depth = 50.0
    a.tick()
    f.depth = 0.0
    a.tick()
    # shed 429s count toward the burst trigger (overload storms dump)
    for _ in range(8):
        tok = fr.begin_request("t" * 32, "/v1/weights")
        fr.end_request(tok, {"status": 429})
    assert fr.error_burst()
    path = fr.dump("error_burst")
    snap = json.loads(path.read_text())
    assert snap["reason"] == "error_burst"
    decisions = snap["autoscaler_decisions"]
    assert len(decisions) == 2
    assert decisions[0]["action"] == "up"
    assert decisions[0]["mean_queue_depth"] == 50.0


def test_fleet_json_atomic_roundtrip(tmp_path):
    layout = {"host": "h", "port": 1, "replicas": 2, "replica_ids": [0, 1]}
    write_fleet_json(tmp_path, layout)
    assert read_fleet_json(tmp_path) == layout
    write_fleet_json(tmp_path, dict(layout, replicas=1))
    assert read_fleet_json(tmp_path)["replicas"] == 1
    assert read_fleet_json(tmp_path / "nope") is None
    # no tmp litter left behind
    assert [p.name for p in tmp_path.iterdir()] == ["fleet.json"]


# --------------------------------------------------------------------------
# loadgen: mid-run rate swings + per-class accounting (stub server)
# --------------------------------------------------------------------------


def test_loadgen_swing_schedule_and_class_accounting():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    seen = {"bulk": 0, "interactive": 0}
    lock = threading.Lock()

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            self.rfile.read(n)
            pr = self.headers.get("x-dlap-priority") or "interactive"
            with lock:
                seen[pr] += 1
            if pr == "bulk":  # the server sheds every bulk request
                body = b'{"error": "shed", "reason": "bulk_shed"}'
                self.send_response(429)
                self.send_header("Retry-After", "1")
            else:
                body = b'{"ok": true}'
                self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}/v1/weights"
    out = run_ladder(
        url, {"x": 1}, rates=[20.0, 200.0, 20.0],
        durations=[0.5, 0.5, 0.5],
        class_of=lambda i: "bulk" if i % 5 == 0 else "interactive")
    httpd.shutdown()
    assert out["swing"] is True
    steps = out["steps"]
    assert [s["offered_rate_rps"] for s in steps] == [20.0, 200.0, 20.0]
    # the 10x middle step really carries 10x the requests of the edges
    assert steps[1]["n_requests"] == 10 * steps[0]["n_requests"]
    run = out["run"]
    assert run["n_requests"] == sum(s["n_requests"] for s in steps)
    bc = run["by_class"]
    assert set(bc) == {"bulk", "interactive"}
    assert bc["interactive"]["dropped"] == 0
    assert bc["interactive"]["n_shed_429"] == 0
    # every bulk request was shed and accounted as 429, not silently lost
    assert bc["bulk"]["n_shed_429"] == bc["bulk"]["n_requests"] > 0
    assert seen["bulk"] == bc["bulk"]["n_requests"]
    # per-step error accounting sums to the run's
    assert sum(s["errors"].get("429", 0) for s in steps) \
        == bc["bulk"]["n_shed_429"]
    assert out["max_clean_rate_rps"] is None  # every step had sheds


def test_loadgen_swing_rejects_mismatched_durations():
    with pytest.raises(ValueError, match="durations"):
        run_ladder("http://127.0.0.1:1/x", {}, rates=[1.0, 2.0],
                   durations=[1.0])


# --------------------------------------------------------------------------
# report CLI: shed / coalesce / scale subsections
# --------------------------------------------------------------------------


def test_report_shed_coalesce_scale_sections(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.observability import (
        EventLog,
    )
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        format_summary,
        load_run,
        summarize_run,
    )

    ev = EventLog(tmp_path)
    for i in range(6):
        ev.emit("span_end", "serve/request", duration_s=0.002,
                endpoint="/v1/weights", method="POST", status=200,
                priority="interactive")
    ev.emit("span_end", "serve/request", duration_s=0.09,
            endpoint="/v1/weights", method="POST", status=429,
            priority="bulk")
    ev.counter("serve/requests", endpoint="/v1/weights", status=200)
    for reason, pri in (("bulk_shed", "bulk"), ("bulk_shed", "bulk"),
                        ("deadline_expired", "interactive")):
        ev.counter("serve/shed", reason=reason, priority=pri,
                   queue_depth=9)
    ev.counter("serve/coalesce", hit=False)
    for _ in range(3):
        ev.counter("serve/coalesce", hit=True)
    ev.counter("fleet/scale", direction="up", action="up", replica=1,
               replicas=2, reason="queue_depth 12.0")
    ev.gauge("fleet/replicas", 2)
    ev.counter("fleet/scale", direction="down", action="down", replica=1,
               replicas=1, reason="quiet")
    ev.gauge("fleet/replicas", 1)
    ev.counter("serve/drain", pending=0, replica="replica1")
    ev.close()

    sv = summarize_run(load_run(tmp_path))["serving"]
    assert sv["shed"] == {
        "total": 3,
        "by_reason": {"bulk_shed": 2, "deadline_expired": 1},
        "by_priority": {"bulk": 2, "interactive": 1},
    }
    assert sv["coalesce"]["hits"] == 3
    assert sv["coalesce"]["dispatches"] == 1
    assert sv["coalesce"]["hit_rate"] == 0.75
    assert sv["coalesce"]["dispatch_ratio"] == 0.25
    assert sv["autoscale"]["scale_ups"] == 1
    assert sv["autoscale"]["scale_downs"] == 1
    assert sv["autoscale"]["replicas_final"] == 1
    assert sv["drains"] == 1
    assert sv["latency_by_priority"]["interactive"]["count"] == 6
    assert sv["latency_by_priority"]["bulk"]["count"] == 1
    text = format_summary(summarize_run(load_run(tmp_path)))
    assert "shed (429): 3" in text
    assert "coalescing: 3 hits / 1 dispatches" in text
    assert "autoscale: 1 up / 1 down" in text
    assert "graceful drains: 1" in text


# --------------------------------------------------------------------------
# live fleet scaling: add_replica + /v1/drain scale-down, fleet.json
# --------------------------------------------------------------------------


def _fleet_args(tmp_path, dirs, run_dir):
    return build_arg_parser().parse_args([
        "--checkpoint_dirs", *dirs,
        "--macro_npy", str(tmp_path / "macro.npy"),
        "--stock_buckets", "64", "--batch_buckets", "1,4",
        "--max_queue", "32", "--cache_size", "0",
        "--run_dir", str(run_dir)])


def test_fleet_scale_up_and_graceful_drain_down(tmp_path, serve_cfg, panel):
    """A live 1-replica fleet grows to 2 through FleetController.scale_up
    (new supervised process, serve/accepting heartbeat) and shrinks back
    through /v1/drain — the victim exits rc 0 (supervisor outcome
    'success', NOT a death), and fleet.json atomically tracks the live
    layout at every step."""
    dirs = [_write_member(tmp_path / "m1", serve_cfg, 1)]
    np.save(tmp_path / "macro.npy", panel["macro"])
    run_dir = tmp_path / "fleet_run"
    args = _fleet_args(tmp_path, dirs, run_dir)
    port = pick_free_port()
    admin0 = pick_free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def make_argv(rid, admin_port):
        return server_child_argv(args, rid, run_dir / f"replica{rid}",
                                 port, admin_port=admin_port)

    fleet = ReplicaFleet([make_argv(0, admin0)], run_dir, env=env)
    ctl = FleetController(fleet, make_argv, "127.0.0.1", port,
                          admin_ports={0: admin0})
    try:
        fleet.start()
        fleet.wait_ready(timeout=300)
        ctl.publish_layout()
        assert read_fleet_json(run_dir)["replicas"] == 1
        rid = ctl.scale_up(ready_timeout_s=300)
        assert rid == 1 and fleet.live_ids() == [0, 1]
        layout = read_fleet_json(run_dir)
        assert layout["replicas"] == 2
        assert layout["replica_ids"] == [0, 1]
        assert str(rid) in layout["admin_ports"]
        # the new replica really serves on the shared port
        body = binary_payload_bytes(panel["individual"][0], 0)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/weights", data=body,
            headers={"Content-Type": BINARY_CONTENT_TYPE}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
        outcome = ctl.scale_down(rid, drain_timeout_s=10)
        assert outcome == "drained"
        assert fleet.live_ids() == [0]
        assert read_fleet_json(run_dir)["replicas"] == 1
        # graceful: the drained replica EXITED cleanly, it was not killed
        assert (fleet.summaries[rid] or {}).get("outcome") == "success"
        assert (fleet.summaries[rid] or {}).get("restarts") == 0
        # drain left its mark in the victim's events
        rows = [json.loads(line) for line in
                (run_dir / f"replica{rid}" / "events.jsonl"
                 ).read_text().splitlines()]
        assert any(r.get("name") == "serve/drain" for r in rows)
    finally:
        fleet.stop()


# --------------------------------------------------------------------------
# tier-1 fault matrix: replica SIGKILLed mid-swing, autoscaler live
# --------------------------------------------------------------------------


def test_replica_killed_mid_swing_no_interactive_lost(tmp_path, serve_cfg,
                                                      panel):
    """A supervised fleet with the autoscaler LIVE is driven through a 10x
    open-loop rate swing of mixed-priority traffic; a fault plan SIGKILLs
    replica0 mid-swing with requests in the air. The supervisor replaces
    it, retries land on the survivor, and ZERO interactive requests are
    lost; the kill is attributed and the autoscaler's decision ring shows
    the loop was watching the whole time."""
    dirs = [_write_member(tmp_path / f"m{s}", serve_cfg, s) for s in (1, 2)]
    np.save(tmp_path / "macro.npy", panel["macro"])
    run_dir = tmp_path / "fleet_run"
    args = _fleet_args(tmp_path, dirs, run_dir)
    port = pick_free_port()
    admin_ports = {}
    for i in range(2):
        p = pick_free_port()
        while p == port or p in admin_ports.values():
            p = pick_free_port()
        admin_ports[i] = p
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["DLAP_FAULT_PLAN"] = json.dumps([{
        "site": "serve/replica_kill", "action": "kill",
        "match": "replica0", "trigger_count": 25}])
    policy = dataclasses.replace(
        REPLICA_POLICY, backoff_base_s=0.2, min_uptime_s=0.5, poll_s=0.2)

    def make_argv(rid, admin_port):
        return server_child_argv(args, rid, run_dir / f"replica{rid}",
                                 port, admin_port=admin_port)

    fleet = ReplicaFleet([make_argv(i, admin_ports[i]) for i in range(2)],
                         run_dir, policy=policy, env=env)
    ctl = FleetController(fleet, make_argv, "127.0.0.1", port,
                          admin_ports=dict(admin_ports))
    autoscaler = Autoscaler(ctl, AutoscalePolicy(
        min_replicas=2, max_replicas=3, poll_s=0.25, up_queue_depth=8.0,
        down_hysteresis=10_000, cooldown_s=2.0))
    bodies = [binary_payload_bytes(panel["individual"][t], t)
              for t in range(T)]
    try:
        fleet.start()
        fleet.wait_ready(timeout=300)
        ctl.publish_layout()
        autoscaler.start()
        swing = run_ladder(
            f"http://127.0.0.1:{port}/v1/weights",
            lambda i: bodies[i % len(bodies)],
            rates=[8.0, 80.0, 8.0], durations=[2.0, 4.0, 2.0],
            retries=10, open_workers=8, timeout_s=20.0,
            content_type=BINARY_CONTENT_TYPE,
            class_of=lambda i: "bulk" if i % 4 == 0 else "interactive")
        run = swing["run"]
        # THE bar: zero interactive requests lost through the mid-swing
        # SIGKILL (bulk may shed 429s; that is the design, not a loss)
        assert run["by_class"]["interactive"]["dropped"] == 0, run
        non_shed = {k: v for k, v in
                    run["by_class"]["interactive"]["errors"].items()}
        assert non_shed == {}, non_shed
        assert run["n_retried"] >= 1  # the kill really dropped connections
        # the killed replica is back accepting
        fleet.wait_ready(timeout=300)
        assert sorted(fleet.live_ids())[:2] == [0, 1]
        # the autoscaler watched the whole swing (its ring is evidence)
        assert len(autoscaler.decisions) >= 5
    finally:
        autoscaler.stop()
        summaries = fleet.stop()
    assert sum((s or {}).get("restarts", 0) for s in summaries) == 1
    fault_rows = [json.loads(line) for line in (
        run_dir / "events.faults.jsonl").read_text().splitlines()]
    assert [r["site"] for r in fault_rows] == ["serve/replica_kill"]

    # the report CLI tells the whole story from the one run dir
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        load_run,
        summarize_run,
    )

    summary = summarize_run(load_run(run_dir))
    assert summary["reliability"]["restarts"] == 1
    sv = summary["serving"]
    assert sum(sv["requests_by_replica"].values()) >= run["n_requests"]
    assert sv["latency_by_priority"]["interactive"]["count"] >= 1


# --------------------------------------------------------------------------
# BENCH_LOADADAPT.json artifact bars
# --------------------------------------------------------------------------


def test_bench_loadadapt_artifact_bars():
    path = REPO / "BENCH_LOADADAPT.json"
    assert path.exists(), "BENCH_LOADADAPT.json must be checked in"
    d = json.loads(path.read_text())
    assert d["swing_factor"] == 10.0
    assert d["dropped_interactive"] == 0
    assert d["interactive_requests"] > 0
    assert d["shed_bulk_429"] >= 1
    assert d["autoscale"]["scale_ups"] >= 1
    assert d["autoscale"]["scale_downs"] >= 1
    assert d["autoscale"]["peak_replicas"] > d["autoscale"][
        "final_live_replicas"]
    assert d["coalesce_burst"]["dispatch_ratio"] <= 0.5
    assert d["coalesce_burst"]["n_ok"] == d["coalesce_burst"]["n_requests"]
    assert d["steady_state_recompiles_max"] == 0
    assert d["fleet_json_final"]["replicas"] == 1


# --------------------------------------------------------------------------
# lint gate: the load-adaptive plane's new/changed modules stay clean
# --------------------------------------------------------------------------


def test_loadadapt_modules_lint_clean():
    targets = [
        REPO / PKG / "serving" / "autoscale.py",
        REPO / PKG / "serving" / "batcher.py",
        REPO / PKG / "serving" / "server.py",
        REPO / PKG / "serving" / "aserver.py",
        REPO / PKG / "serving" / "fleet.py",
        REPO / PKG / "serving" / "flight.py",
        REPO / PKG / "serving" / "loadgen.py",
        REPO / PKG / "serving" / "__init__.py",
        REPO / PKG / "observability" / "report.py",
        REPO / PKG / "reliability" / "faults.py",
        REPO / "bench.py",
        Path(__file__),
    ]
    try:
        import ruff  # noqa: F401
    except ImportError:
        pytest.skip("ruff not installed in this container")
    out = subprocess.run(
        [sys.executable, "-m", "ruff", "check"] + [str(t) for t in targets],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
