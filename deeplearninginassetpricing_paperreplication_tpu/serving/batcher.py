"""Request batching: the async continuous batcher and the legacy
deadline-triggered micro-batcher.

:class:`ContinuousBatcher` (the production path) is asyncio-native: per-
bucket lanes, ONE dispatch in flight at a time, and the next flush takes
everything pending the moment the previous dispatch returns — the device
never sits idle waiting for a deadline, and batch occupancy grows with
offered load instead of being capped by a timer. A lone request on an idle
device dispatches immediately (no deadline latency floor); a burst under
load coalesces into one compiled [B, Nb] program call. Per-flush occupancy
and queue-depth gauges go to ``events.jsonl`` (``serve/flush``), and the
``serve/flush`` fault site lets the tier-1 fault matrix kill a replica
mid-flight.

:class:`MicroBatcher` is the PR-3 deadline/size-triggered thread batcher,
kept for the deprecated ``--server threaded`` path: a dedicated dispatcher
thread flushes a lane when it reaches ``max_batch`` items OR its oldest
item has waited ``max_delay_s`` — which leaves the device idle between
flushes under load, the gap the continuous batcher closes.

Both are bounded and loud: when ``max_queue`` items are pending across all
lanes, submission raises :class:`QueueFull` immediately (the server maps it
to HTTP 503) instead of growing an unbounded queue in front of a saturated
accelerator.

Admission is NOT flat FIFO-reject, though (the continuous batcher only):
requests carry a **priority class** (``interactive`` | ``bulk``) and an
optional **deadline**, and under pressure the batcher sheds *expired and
bulk* work first — DAGOR-style (Zhou et al., SoCC 2018): the queue-depth
signal that would have 503'd everyone instead (1) stops admitting bulk past
a soft threshold (:class:`Shed` → HTTP 429 with ``Retry-After``), (2) lets
an interactive request at a FULL queue evict the newest queued bulk item
instead of being rejected, (3) drops queued items whose deadline already
expired at flush-take time (serving them would waste a device slot on an
answer the client stopped waiting for), and (4) flushes interactive lanes
before bulk lanes — interactive preempts, bulk rides the idle capacity.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..reliability.faults import inject

# priority classes, highest first: _next_lane flushes strictly in this
# order, and admission sheds from the back of the list first
PRIORITIES = ("interactive", "bulk")
DEFAULT_PRIORITY = "interactive"


class QueueFull(RuntimeError):
    """Raised by submit() when the batcher's bounded queue is at capacity."""


class Shed(RuntimeError):
    """Admission control dropped this request — bulk past the shed
    threshold, a queued bulk item evicted by an arriving interactive one,
    or a deadline that expired in the queue. The server maps it to HTTP
    429 with a ``Retry-After`` header (``retry_after_s``): unlike the 503
    of :class:`QueueFull` this is a *policy* rejection — the service is
    alive and deliberately choosing who waits."""

    def __init__(self, msg: str, reason: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


class MicroBatcher:
    """Coalesce submit()ed items into handler(bucket, items) flushes.

    handler: called ON THE DISPATCHER THREAD with (bucket, [item, ...]) and
    must return one result per item, in order; results (or the raised
    exception) are delivered through each item's Future.
    """

    def __init__(
        self,
        handler: Callable[[Any, List[Any]], List[Any]],
        max_batch: int = 4,
        max_delay_s: float = 0.002,
        max_queue: int = 256,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._handler = handler
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # bucket -> list of (enqueue_monotonic, item, future, meta)
        self._lanes: Dict[Any, List[Tuple[float, Any, Future, Any]]] = {}
        self._pending = 0
        self._closed = False
        self.flushes = 0
        self.rejected = 0
        self.current_flush: Optional[int] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serving-batcher")
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, bucket: Any, item: Any,
               meta: Optional[Dict[str, Any]] = None,
               priority: str = DEFAULT_PRIORITY,
               deadline: Optional[float] = None) -> Future:
        """Enqueue one item into `bucket`'s lane; returns its Future.
        ``meta`` (a caller-owned dict) is filled with the item's batching
        timeline — ``t_enq``/``t_take``/``flush``/``occupancy``/
        ``dispatch_s`` — the request-trace segment evidence.
        ``priority``/``deadline`` are accepted for signature parity with
        :class:`ContinuousBatcher` but IGNORED: the deprecated threaded
        path keeps its flat FIFO admission."""
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._pending >= self.max_queue:
                self.rejected += 1
                raise QueueFull(
                    f"{self._pending} requests pending (max_queue="
                    f"{self.max_queue})")
            t_enq = time.monotonic()
            if meta is not None:
                meta["t_enq"] = t_enq
            self._lanes.setdefault(bucket, []).append(
                (t_enq, item, fut, meta))
            self._pending += 1
            self._cond.notify()
        return fut

    def submit_wait(self, bucket: Any, item: Any,
                    timeout: Optional[float] = None,
                    meta: Optional[Dict[str, Any]] = None,
                    priority: str = DEFAULT_PRIORITY,
                    deadline: Optional[float] = None) -> Any:
        """submit() and block for the result (the HTTP handler's shape)."""
        return self.submit(bucket, item, meta=meta, priority=priority,
                           deadline=deadline).result(timeout=timeout)

    # -- dispatcher ----------------------------------------------------------

    def _due_lanes(self, now: float):
        """(ready lanes, seconds until the next deadline or None)."""
        ready, next_deadline = [], None
        for bucket, lane in self._lanes.items():
            if not lane:
                continue
            oldest = lane[0][0]
            if len(lane) >= self.max_batch or now - oldest >= self.max_delay_s:
                ready.append(bucket)
            else:
                deadline = oldest + self.max_delay_s
                if next_deadline is None or deadline < next_deadline:
                    next_deadline = deadline
        return ready, (None if next_deadline is None
                       else max(0.0, next_deadline - now))

    def _run(self):
        while True:
            with self._cond:
                while True:
                    now = time.monotonic()
                    ready, wait = self._due_lanes(now)
                    if ready or (self._closed and self._pending == 0):
                        break
                    self._cond.wait(timeout=wait)
                if self._closed and self._pending == 0 and not ready:
                    return
                flushes = []
                for bucket in ready:
                    lane = self._lanes[bucket]
                    take, rest = lane[:self.max_batch], lane[self.max_batch:]
                    self._lanes[bucket] = rest
                    self._pending -= len(take)
                    flushes.append((bucket, take))
            for bucket, take in flushes:
                self._flush(bucket, take)

    def _flush(self, bucket, take):
        items = [item for _, item, _, _ in take]
        futures = [fut for _, _, fut, _ in take]
        t0 = time.monotonic()
        fid = self.flushes
        for _, _, _, meta in take:
            if meta is not None:
                meta.update(t_take=t0, t_dispatch=t0, flush=fid,
                            occupancy=len(take))
        try:
            self.current_flush = fid
            results = self._handler(bucket, items)
            if len(results) != len(items):
                raise RuntimeError(
                    f"handler returned {len(results)} results for "
                    f"{len(items)} items")
        except BaseException as e:
            for fut in futures:
                fut.set_exception(e)
            return
        finally:
            self.current_flush = None
            self.flushes += 1
            dispatch_s = time.monotonic() - t0
            for _, _, _, meta in take:
                if meta is not None:
                    meta["dispatch_s"] = dispatch_s
        for fut, res in zip(futures, results):
            fut.set_result(res)

    # -- lifecycle -----------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting work, drain pending items, join the dispatcher."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=timeout)

    def pending(self) -> int:
        with self._lock:
            return self._pending


class ContinuousBatcher:
    """Asyncio continuous batcher: flushes fold in-flight arrivals.

    Single-threaded on the event loop (lane state needs no locks); the
    handler runs on a dedicated one-thread executor so the loop keeps
    accepting requests while a flush is on the device. Exactly one flush is
    in flight at a time — the device is the serialization point — and the
    next flush is taken the instant the previous one returns, up to
    ``max_batch`` items from the highest-priority lane whose head has
    waited longest (interactive lanes strictly preempt bulk lanes).

    Admission (module doc): bulk is shed with :class:`Shed` once pending
    reaches ``bulk_threshold × max_queue``; an interactive submit at a
    FULL queue evicts expired then newest-bulk queued items before giving
    up with :class:`QueueFull`; queued items whose ``deadline`` (a
    ``time.monotonic()`` instant) has passed are shed at flush-take time
    instead of dispatched.

    handler: called OFF-LOOP with (bucket, [item, ...]); must return one
    result per item, in order. Construct and use from a running event loop.
    """

    def __init__(
        self,
        handler: Callable[[Any, List[Any]], List[Any]],
        max_batch: int = 16,
        max_queue: int = 256,
        events: Any = None,
        label: Optional[str] = None,
        flight: Any = None,
        bulk_threshold: float = 0.5,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if not 0.0 < bulk_threshold <= 1.0:
            raise ValueError("bulk_threshold must be in (0, 1]")
        self._handler = handler
        self.max_batch = max_batch
        self.max_queue = max_queue
        # the DAGOR-style soft threshold: pending at/above this stops
        # admitting bulk while interactive still has max_queue - this much
        # headroom to absorb the burst the autoscaler is reacting to
        self.bulk_max = max(1, int(round(max_queue * bulk_threshold)))
        self.events = events
        self.label = label
        self.flight = flight  # FlightRecorder: flush ring (may be None)
        # the id of the flush currently on the device (ONE in flight by
        # design): the engine stamps it onto its serve/dispatch span
        self.current_flush: Optional[int] = None
        # (priority, bucket) -> deque of
        # (enqueue_monotonic, item, asyncio.Future, meta, deadline)
        self._lanes: Dict[Tuple[str, Any], deque] = {}
        self._pending = 0
        self._pending_by: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self._closed = False
        self._wake = asyncio.Event()
        self.flushes = 0
        self.rejected = 0
        # shed accounting by reason: bulk_shed (admission), bulk_evicted
        # (displaced by an arriving interactive), deadline_expired
        self.shed: Dict[str, int] = {}
        self.items_flushed = 0
        self.occupancy_hist: Dict[int, int] = {}
        self._queue_depth_sum = 0
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serving-dispatch")
        self._task = asyncio.get_running_loop().create_task(self._run())

    # -- producer side (event-loop coroutines) --------------------------------

    async def submit(self, bucket: Any, item: Any,
                     meta: Optional[Dict[str, Any]] = None,
                     priority: str = DEFAULT_PRIORITY,
                     deadline: Optional[float] = None) -> Any:
        """Enqueue one item into the ``(priority, bucket)`` lane and await
        its result. ``meta`` (a caller-owned dict) receives the item's
        batching timeline: ``t_enq`` at enqueue, then ``t_take``/``flush``/
        ``occupancy`` when its flush is taken and ``dispatch_s`` when the
        dispatch returns — the queue_wait/batch_wait/dispatch_share
        segments of the request trace come straight from these.
        ``priority``: ``interactive`` (default) or ``bulk``; ``deadline``:
        an absolute ``time.monotonic()`` instant past which the caller no
        longer wants the answer (expired items are shed, not served)."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}: "
                             f"{priority!r}")
        # fault site: the admission decision point — a plan can raise/kill
        # exactly when a request is being admitted under pressure
        inject("serve/admit", priority=priority,
               queue_depth=self._pending, path=self.label or "")
        if self._closed:
            raise RuntimeError("batcher is closed")
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            # dead on arrival: never enqueue work nobody is waiting for
            self._shed_count("deadline_expired", priority)
            raise Shed("deadline expired before admission",
                       "deadline_expired", retry_after_s=0.0)
        if priority == "bulk" and self._pending >= self.bulk_max:
            self._shed_count("bulk_shed", priority)
            raise Shed(
                f"{self._pending} requests pending >= bulk admission "
                f"threshold {self.bulk_max} (max_queue={self.max_queue})",
                "bulk_shed", retry_after_s=self._retry_after_s())
        if self._pending >= self.max_queue:
            # interactive at a full queue: make room from expired and
            # bulk work before giving up — DAGOR sheds low priority first
            if not self._evict_for_admission(now):
                self.rejected += 1
                raise QueueFull(
                    f"{self._pending} requests pending (max_queue="
                    f"{self.max_queue})")
        fut = asyncio.get_running_loop().create_future()
        t_enq = time.monotonic()
        if meta is not None:
            meta["t_enq"] = t_enq
            meta["priority"] = priority
        self._lanes.setdefault((priority, bucket), deque()).append(
            (t_enq, item, fut, meta, deadline))
        self._pending += 1
        self._pending_by[priority] += 1
        self._wake.set()
        return await fut

    def pending(self) -> int:
        return self._pending

    def pending_by_priority(self) -> Dict[str, int]:
        return dict(self._pending_by)

    def mean_queue_depth(self) -> Optional[float]:
        """Mean pending count observed at flush time (queueing pressure)."""
        if not self.flushes:
            return None
        return self._queue_depth_sum / self.flushes

    # -- shedding -------------------------------------------------------------

    def _retry_after_s(self) -> float:
        """Retry hint for shed work: roughly one queue-drain time, floored
        at 1 s (the HTTP header carries whole seconds anyway)."""
        return max(1.0, self._pending / max(1.0, 4.0 * self.max_batch))

    def _shed_count(self, reason: str, priority: str) -> None:
        self.shed[reason] = self.shed.get(reason, 0) + 1
        if self.events is not None:
            try:
                self.events.counter(
                    "serve/shed", reason=reason, priority=priority,
                    queue_depth=self._pending, replica=self.label)
            except Exception:
                pass  # telemetry must never fail the admission path

    def _shed_entry(self, entry, reason: str, priority: str) -> None:
        """Fail one queued entry's future with Shed (counts + events)."""
        _, _, fut, _meta, _ = entry
        self._shed_count(reason, priority)
        if not fut.done():
            fut.set_exception(Shed(
                f"shed from queue: {reason}", reason,
                retry_after_s=self._retry_after_s()))

    def _evict_for_admission(self, now: float) -> bool:
        """Make room at a full queue for an INTERACTIVE arrival: shed every
        expired queued item, then the newest queued bulk item. True when a
        slot opened."""
        for (priority, bucket), lane in list(self._lanes.items()):
            kept = deque()
            for entry in lane:
                deadline = entry[4]
                if deadline is not None and now >= deadline:
                    self._shed_entry(entry, "deadline_expired", priority)
                    self._pending -= 1
                    self._pending_by[priority] -= 1
                else:
                    kept.append(entry)
            if len(kept) != len(lane):
                self._lanes[(priority, bucket)] = kept
        if self._pending < self.max_queue:
            return True
        # newest bulk item across lanes: the work least likely to be
        # missed (its sender is told to back off via Retry-After)
        newest_key, newest_t = None, None
        for (priority, bucket), lane in self._lanes.items():
            if priority != "bulk" or not lane:
                continue
            if newest_t is None or lane[-1][0] > newest_t:
                newest_key, newest_t = (priority, bucket), lane[-1][0]
        if newest_key is None:
            return False
        entry = self._lanes[newest_key].pop()
        self._shed_entry(entry, "bulk_evicted", "bulk")
        self._pending -= 1
        self._pending_by["bulk"] -= 1
        return self._pending < self.max_queue

    # -- dispatcher task ------------------------------------------------------

    def _next_lane(self):
        """The non-empty lane whose head has waited longest within the
        highest non-empty priority class — interactive lanes strictly
        preempt bulk lanes; FIFO fairness across buckets within a class."""
        for priority in PRIORITIES:
            best, best_t = None, None
            for key, lane in self._lanes.items():
                if key[0] != priority or not lane:
                    continue
                if best_t is None or lane[0][0] < best_t:
                    best, best_t = key, lane[0][0]
            if best is not None:
                return best
        return None

    async def _run(self):
        loop = asyncio.get_running_loop()
        while True:
            key = self._next_lane()
            if key is None:
                if self._closed:
                    return
                self._wake.clear()
                # re-check after clear: a submit between _next_lane and
                # clear() would otherwise be stranded until the next one
                if self._next_lane() is None and not self._closed:
                    await self._wake.wait()
                continue
            priority, bucket = key
            lane = self._lanes[key]
            depth_at_flush = self._pending
            # take up to max_batch live items; expired-deadline items are
            # shed HERE, not dispatched — a device slot must not be spent
            # on an answer whose client already gave up
            now = time.monotonic()
            take = []
            while lane and len(take) < self.max_batch:
                entry = lane.popleft()
                self._pending -= 1
                self._pending_by[priority] -= 1
                deadline = entry[4]
                if deadline is not None and now >= deadline:
                    self._shed_entry(entry, "deadline_expired", priority)
                    continue
                take.append(entry)
            if not take:
                continue  # the whole head of the lane had expired
            occupancy = len(take)
            fid = self.flushes  # this flush's id: links request rows to it
            self.flushes += 1
            self.items_flushed += occupancy
            self.occupancy_hist[occupancy] = (
                self.occupancy_hist.get(occupancy, 0) + 1)
            self._queue_depth_sum += depth_at_flush
            t_take = time.monotonic()
            for _, _, _, meta, _ in take:
                if meta is not None:
                    meta.update(t_take=t_take, flush=fid,
                                occupancy=occupancy)
            if self.events is not None:
                try:
                    self.events.counter(
                        "serve/flush", occupancy=occupancy,
                        queue_depth=depth_at_flush, bucket=str(bucket),
                        flush=fid, priority=priority, replica=self.label)
                except Exception:
                    # telemetry (disk full, deleted run dir) must never
                    # kill the dispatcher: a dead dispatcher would hang
                    # every future submit() with no watchdog signal
                    pass
            items = [item for _, item, _, _, _ in take]
            try:
                # fault site: a plan can kill/hang/raise a replica mid-
                # flight, with a whole flush of requests in the air (a
                # `raise` lands on this flush's futures as a 5xx; the
                # dispatcher itself survives)
                inject("serve/flush", occupancy=occupancy,
                       path=self.label or "")
                self.current_flush = fid
                t0 = time.monotonic()
                try:
                    results = await loop.run_in_executor(
                        self._executor, self._handler, bucket, items)
                finally:
                    self.current_flush = None
                dispatch_s = time.monotonic() - t0
                for _, _, _, meta, _ in take:
                    if meta is not None:
                        meta.update(t_dispatch=t0, dispatch_s=dispatch_s)
                if self.flight is not None:
                    self.flight.record_flush({
                        "flush": fid, "bucket": str(bucket),
                        "occupancy": occupancy, "priority": priority,
                        "queue_depth": depth_at_flush,
                        "dispatch_s": round(dispatch_s, 6),
                        "ts": round(time.time(), 6)})
                if self.events is not None:
                    try:
                        # the flush's dispatch as a span row: the trace
                        # flow arrows land on this slice (request rows
                        # reference it by flush id)
                        self.events.emit(
                            "span_end", "serve/flush_dispatch",
                            duration_s=round(dispatch_s, 6), flush=fid,
                            occupancy=occupancy, bucket=str(bucket),
                            priority=priority, replica=self.label,
                            status="ok")
                    except Exception:
                        pass  # same contract as the counter above
                if len(results) != len(items):
                    raise RuntimeError(
                        f"handler returned {len(results)} results for "
                        f"{len(items)} items")
            except BaseException as e:
                for _, _, fut, _, _ in take:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            for (_, _, fut, _, _), res in zip(take, results):
                if not fut.done():
                    fut.set_result(res)

    # -- lifecycle ------------------------------------------------------------

    async def aclose(self) -> None:
        """Stop accepting work, drain pending flushes, join the task."""
        if self._closed:
            return
        self._closed = True
        self._wake.set()
        await self._task
        self._executor.shutdown(wait=False)
