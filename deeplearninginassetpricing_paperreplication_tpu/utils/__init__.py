from .config import GANConfig, TrainConfig
