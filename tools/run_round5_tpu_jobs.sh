#!/usr/bin/env bash
# Round-5 TPU job queue — run when the axon tunnel is up, from /root/repo.
# Ordered by value-per-minute; each stage is independently resumable, so a
# mid-queue outage loses only the stage in flight. Do NOT run the CPU test
# suite concurrently (host contention pollutes cold numbers).
set -u
cd "$(dirname "$0")/.."

log() { echo "[r5-jobs $(date +%H:%M:%S)] $*"; }

# 1. The headline bench (resilient: survives raises/hangs, prints one JSON
#    line regardless). Produces matmul ceilings + dual rooflines the
#    compute-floor decision needs. State pinned so a re-run resumes.
log "stage 1: bench"
DLAP_BENCH_STATE=/tmp/bench_r05_state.json python bench.py > /tmp/BENCH_SELF_r05.json
cp /tmp/BENCH_SELF_r05.json BENCH_SELF_r05.json
log "bench done: $(head -c 200 BENCH_SELF_r05.json)"

# 2. TPU test lane: the three TPU-only tests, output committed as evidence
#    (VERDICT r4 #7).
log "stage 2: TPU test lane"
python -m pytest tests/test_pallas.py -q -k "dropout or batched_seed" \
    2>&1 | tail -20 > artifacts/TPU_TESTLANE_r05.txt
cat artifacts/TPU_TESTLANE_r05.txt

# 3. Parity re-runs on the default TPU bf16 route with the round-5
#    diagnostics (trajectory / selection_sensitivity / full precision).
log "stage 3: bf16 parity re-runs"
python tools/parity_vs_reference.py --data_dir bench_data_mid \
    --ref_save_dir ref_runs/mid2000 --exec_route bf16 --out PARITY_MID.json \
    || log "PARITY_MID re-run failed"
python tools/parity_vs_reference.py --data_dir bench_data_w4000 \
    --ref_save_dir ref_runs/w4000 --exec_route bf16 --out PARITY_W4000.json \
    || log "PARITY_W4000 re-run failed"

# 4. Selection-noise diagnostic artifact with n_pairs >= 8: resume from the
#    committed 384-point ranking, retrain winners + diagnostic ranks.
log "stage 4: sweep diagnostic"
python -m deeplearninginassetpricing_paperreplication_tpu.sweep \
    --data_dir bench_data_real --save_dir sweep_results_r05 \
    --resume_ranking sweep_results/sweep_ranking.json \
    || log "sweep diagnostic failed"

# 5. Execute the full-panel demo notebook against the real-shape panel.
log "stage 5: demo_full execution"
( cd notebooks && jupyter nbconvert --to notebook --execute --inplace \
    demo_full.ipynb --ExecutePreprocessor.timeout=3600 ) \
    || log "notebook execution failed"

log "queue complete"
