"""Portfolio metrics: Sharpe, max drawdown, weight normalization.

Sharpe-convention trap carried over from the reference, made explicit here:
the reference computes Sharpe with *torch* std (Bessel-corrected, ddof=1) in
training/eval (``/root/reference/src/train.py:29-34``, ``model.py:551``) but
with *numpy* std (ddof=0) in the ensemble evaluator
(``evaluate_ensemble.py:46-50``). Both are monthly (NOT annualized), and the
paper-convention headline number is computed on the NEGATED portfolio return
(``evaluate_ensemble.py:169-171``) while best-model selection during training
uses the un-negated value (``train.py:268, 378``). Use `ddof` to pick.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def sharpe(returns: jnp.ndarray, ddof: int = 1) -> jnp.ndarray:
    """Monthly Sharpe mean/std; 0 when std < 1e-8 (train.py:29-34)."""
    std = returns.std(ddof=ddof)
    return jnp.where(std < 1e-8, 0.0, returns.mean() / std)


def sharpe_monitor(returns: jnp.ndarray) -> jnp.ndarray:
    """The in-forward monitoring Sharpe: mean / (std_ddof1 + 1e-8)
    (model.py:551)."""
    return returns.mean() / (returns.std(ddof=1) + 1e-8)


def max_drawdown(returns: np.ndarray) -> float:
    """Max drawdown of the cumulative-product wealth curve (train.py:37-42)."""
    cumulative = np.cumprod(1.0 + np.asarray(returns))
    running_max = np.maximum.accumulate(cumulative)
    return float(((cumulative - running_max) / running_max).min())


def normalize_weights_abs(weights: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Per-period scaling so Σ_i |w·m| = 1 — vectorized over T (the reference
    loops over periods, model.py:584-592). Weights are assumed already masked;
    the abs-sum is clamped to 1e-8 as in the reference."""
    abs_sum = jnp.clip((jnp.abs(weights) * mask).sum(axis=1, keepdims=True), 1e-8, None)
    return weights / abs_sum
