"""Analytic FLOP accounting + matmul-ceiling microbench (VERDICT r4 next #2).

The roofline formulas are pure shape arithmetic — pin them by hand on small
dimensions so the bench's MFU numbers rest on verified counts, and check the
microbench kernel computes what it claims (its timing is only meaningful on
TPU, but its accumulation must be correct everywhere).
"""

import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.ops import roofline as R
from deeplearninginassetpricing_paperreplication_tpu.ops.microbench import (
    measure_matmul_ceiling,
    model_shape_ceiling_tflops,
)

SHAPES = {"T_train": 4, "T_valid": 2, "T_test": 3, "N": 100, "F": 5}


def test_ffn_flops_hand_count():
    # layers (5 -> 8 -> 1): fwd MACs per stock-period = 5*8 + 8*1 = 48
    fwd = R.ffn_flops_per_pass(T=4, N=100, F=5, hidden=(8,), mode="fwd")
    assert fwd == 4 * 100 * 2 * (5 * 8 + 8 * 1)
    # bwd = fwd recompute + dgrad (skip layer 1: 8*1) + wgrad (both layers)
    bwd = R.ffn_flops_per_pass(T=4, N=100, F=5, hidden=(8,), mode="bwd")
    assert bwd == 4 * 100 * 2 * ((5 * 8 + 8) + 8 + (5 * 8 + 8))


def test_moment_flops_hand_count():
    # input = F + M = 5 + 3; fwd = K*(F+M) matmul + K mean-contract MACs
    fwd = R.moment_flops_per_pass(T=2, N=10, F=5, M=3, K=4, mode="fwd")
    assert fwd == 2 * (2 * 4 * 8 * 10 + 2 * 4 * 10)


def test_phase_epoch_flops_composition():
    kw = dict(hidden=(8,), M=3, K=4)
    p1 = R.phase_epoch_flops(SHAPES, phase="phase1", **kw)
    p2 = R.phase_epoch_flops(SHAPES, phase="phase2", **kw)
    p3 = R.phase_epoch_flops(SHAPES, phase="phase3", **kw)
    # conditional trains strictly more than either single-network phase
    assert p3 > p1 and p3 > p2
    sched = R.schedule_flops(SHAPES, epochs=(2, 3, 5), **kw)
    assert sched == pytest.approx(2 * p1 + 3 * p2 + 5 * p3)
    with pytest.raises(ValueError):
        R.phase_epoch_flops(SHAPES, phase="phase9")


def test_roofline_summary_bound_flips_with_members():
    """One panel read serving S members multiplies intensity by S: the
    single model sits on the HBM side of the ridge, a large-enough fused
    ensemble on the MXU side — the core of the compute-floor story."""
    # intensity single = 212 GFLOP / 3 GB ≈ 71 FLOP/B < ridge(60 TFLOP/s,
    # 819 GB/s) ≈ 73 — just under the ridge; ×64 members is far over it
    nbytes = 3e9
    kw = dict(shapes={"T_train": 240, "T_valid": 60, "T_test": 300,
                      "N": 10000, "F": 46},
              panel_bytes_per_epoch=nbytes, shape_ceiling_tflops=60.0)
    single = R.roofline_summary(5e-3, n_members=1, **kw)
    fused = R.roofline_summary(40e-3, n_members=64, **kw)
    assert single["bound"] == "hbm"
    assert fused["bound"] == "mxu"
    assert fused["useful_gflops_per_epoch"] == pytest.approx(
        64 * single["useful_gflops_per_epoch"], rel=1e-4)  # rounded fields
    # the dual floor is the max of the two walls
    fc = single["floor_components_ms"]
    assert single["roofline_floor_ms"] == max(fc.values())
    assert 0 < single["mfu"] < 1
    assert single["fraction_of_shape_ceiling"] > single["mfu"]


def test_model_shape_ceiling_is_flop_weighted_harmonic():
    ceiling = {
        "64x46": {"tflops": 40.0},
        "64x64": {"tflops": 50.0},
        "8x224": {"tflops": 20.0},
        "128x128": {"tflops": 100.0},
    }
    got = model_shape_ceiling_tflops(ceiling, F=46, hidden=(64, 64),
                                     M=178, K=8)
    layers = [(64, 46, 40.0), (64, 64, 50.0), (1, 64, 50.0),
              (8, 224, 20.0)]
    f = [2.0 * m * k for m, k, _ in layers]
    t = [fi / c for fi, (_, _, c) in zip(f, layers)]
    assert got == pytest.approx(sum(f) / sum(t), rel=1e-3)


def test_microbench_kernel_accumulation_correct():
    """Interpret-mode value check: G grid steps × R repeats × S members of
    w[s]@x accumulate into exactly G·R·Σ_s w[s]@x."""
    out = measure_matmul_ceiling(
        shapes=((8, 16),), bn=128, n_members=2, repeats_per_step=2,
        grid_steps=3, timed_calls=1, interpret=True)
    assert "8x16" in out and out["8x16"]["seconds"] > 0

    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from deeplearninginassetpricing_paperreplication_tpu.ops.microbench import (
        _ceiling_kernel,
    )

    m, k, bn, S, Rp, G = 8, 16, 128, 2, 2, 3
    w = jnp.asarray(np.random.default_rng(0).standard_normal((S, m, k)),
                    jnp.bfloat16)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((k, bn)),
                    jnp.bfloat16)
    fn = pl.pallas_call(
        functools.partial(_ceiling_kernel, n_members=S, repeats=Rp),
        grid=(G,),
        in_specs=[pl.BlockSpec((S, m, k), lambda i: (0, 0, 0)),
                  pl.BlockSpec((k, bn), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((m, bn), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((m, bn), jnp.float32),
        interpret=True)
    res = np.asarray(fn(w, x))
    exp = G * Rp * sum(
        np.asarray(w[s], np.float32) @ np.asarray(x, np.float32)
        for s in range(S))
    np.testing.assert_allclose(res, exp, rtol=1e-4, atol=1e-4)
