"""XLA program introspection: cost/memory analysis for every AOT program.

The stack AOT-compiles (``.lower().compile()``) all of its hot-path
programs — the trainer's phase scans (``training/trainer.py``), the sweep's
vmapped bucket programs (``parallel/sweep.warm_bucket_programs``), and the
serving engine's (stock × batch) forward buckets (``serving/engine.py``).
Each compile site calls :func:`record_program`, which captures
``compiled.cost_analysis()`` (FLOPs, bytes accessed, transcendentals) and
``compiled.memory_analysis()`` (argument/output/temp/generated-code bytes
→ a peak estimate) into one JSON-able dict, emits it as a ``program``
event row, and lets the CLI fold the collection into ``manifest.json``
(``xla_programs``) — so every run dir carries a roofline story per program
without needing a device or a re-run.

Both XLA APIs are version- and backend-dependent (shape of the cost dict,
availability of memory stats), so every probe is guarded: a missing API
records ``{"available": false, "reason": ...}`` instead of raising —
introspection must never be the reason a compile fails.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# cost_analysis keys we surface (the raw dict also carries per-operand
# entries like "bytes accessed0{}" — noise at manifest granularity)
_COST_KEYS = {
    "flops": "flops",
    "transcendentals": "transcendentals",
    "bytes accessed": "bytes_accessed",
    "optimal_seconds": "optimal_seconds",
}

_MEMORY_ATTRS = (
    "generated_code_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "temp_size_in_bytes",
)


def analyze_compiled(compiled) -> Dict[str, Any]:
    """Cost + memory analysis of one ``jax.stages.Compiled``, guarded per
    jax version/backend. Always returns a dict; fields that cannot be
    captured are absent, with ``cost_available``/``memory_available``
    flags and a ``*_reason`` naming why."""
    out: Dict[str, Any] = {}

    cost = None
    try:
        cost = compiled.cost_analysis()
    except Exception as e:  # older jax / backend without the API
        out["cost_available"] = False
        out["cost_reason"] = f"{type(e).__name__}: {e}"[:200]
    if cost is not None:
        # jax <= 0.4.x returns [dict] (one per device program); newer
        # versions return the dict directly
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if isinstance(cost, dict):
            out["cost_available"] = True
            for key, label in _COST_KEYS.items():
                v = cost.get(key)
                if isinstance(v, (int, float)):
                    out[label] = float(v)
        elif "cost_available" not in out:
            out["cost_available"] = False
            out["cost_reason"] = (
                f"unexpected cost_analysis shape: {type(cost).__name__}")

    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception as e:
        out["memory_available"] = False
        out["memory_reason"] = f"{type(e).__name__}: {e}"[:200]
    if mem is not None:
        stats: Dict[str, float] = {}
        for attr in _MEMORY_ATTRS:
            v = getattr(mem, attr, None)
            if isinstance(v, (int, float)):
                stats[attr] = float(v)
        if stats:
            out["memory_available"] = True
            out.update(stats)
            # XLA's live-range peak: arguments + outputs + temporaries
            # (aliased bytes counted once — they overlap an argument)
            out["peak_memory_bytes"] = (
                stats.get("argument_size_in_bytes", 0.0)
                + stats.get("output_size_in_bytes", 0.0)
                + stats.get("temp_size_in_bytes", 0.0)
                - stats.get("alias_size_in_bytes", 0.0)
            )
        else:
            out.setdefault("memory_available", False)
            out.setdefault("memory_reason",
                           "memory_analysis returned no byte stats")
    elif "memory_available" not in out:
        out["memory_available"] = False
        out["memory_reason"] = "memory_analysis returned None"
    return out


def record_program(events, name: str, compiled,
                   analyses_out: Optional[Dict[str, Dict]] = None,
                   **attrs: Any) -> Dict[str, Any]:
    """Analyze one compiled program, emit the ``program`` event row, and
    (when given) collect into `analyses_out` keyed by `name` — the dict a
    CLI later folds into ``manifest.json`` as ``xla_programs``. Never
    raises."""
    try:
        analysis = analyze_compiled(compiled)
    except Exception as e:  # absolute backstop: see module doc
        analysis = {"cost_available": False, "memory_available": False,
                    "cost_reason": f"{type(e).__name__}: {e}"[:200]}
    analysis = {**attrs, **analysis}
    if analyses_out is not None:
        analyses_out[name] = analysis
    if events is not None:
        try:
            events.emit("program", name, analysis=analysis)
        except Exception:
            pass
    return analysis


def programs_from_events(events_rows) -> Dict[str, Dict[str, Any]]:
    """Rebuild the program-analysis collection from ``program`` event rows
    (the report CLI's fallback when a manifest predates ``xla_programs``
    or the CLI died before the manifest patch)."""
    out: Dict[str, Dict[str, Any]] = {}
    for row in events_rows:
        if row.get("kind") != "program":
            continue
        analysis = row.get("analysis")
        name = row.get("name")
        if isinstance(name, str) and isinstance(analysis, dict):
            out[name] = analysis
    return out
