"""W3C-style request trace context for the serving plane.

One request gets ONE 128-bit trace id for its whole life — generated at
the edge (the load generator, or the server when a client sends nothing)
and carried in the standard ``traceparent`` header::

    traceparent: 00-<32 hex trace id>-<16 hex parent span id>-<2 hex flags>

The id is REUSED across retries: a request killed with one replica and
retried against another is one trace with two server-side spans, which is
exactly what the merged ``report --trace`` flow arrows draw. Each hop
mints a fresh 64-bit span id; the previous hop's span id rides along as
``parent_id``.

Sampling: ``DLAP_TRACE_SAMPLE`` (a ratio in [0, 1], default 1.0) decides
whether a request emits its full ``request`` event row (segment timings,
trace ids — the per-request truth) or only the pre-existing aggregate
``span_end`` row. The decision is DETERMINISTIC in the trace id
(trace-id-ratio sampling), so every retry of one request — and every
replica that serves it — agrees on whether it is traced, and the client's
flag (``01`` sampled / ``00`` not) is honored when a header arrives.

Malformed headers are never an error: :func:`parse_traceparent` returns
``None`` and the server starts a fresh context — a bad client header must
not be able to 500 the hot path (asserted in tier-1).

Stdlib-only by contract (like ``metrics.py``/``heartbeat.py``): thin
parents and the load generator import this without jax.
"""

from __future__ import annotations

import os
import re
import secrets
from typing import Optional, Tuple

ENV_SAMPLE = "DLAP_TRACE_SAMPLE"

TRACEPARENT_HEADER = "traceparent"

# version "00" only; future versions parse tolerantly (trailing fields
# ignored) per the W3C spec's forward-compatibility rule
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})"
    r"(?:-[^\s]*)?$")

FLAG_SAMPLED = 0x01


def new_trace_id() -> str:
    """128 random bits, lowercase hex. The all-zero id is invalid per
    spec; secrets.token_hex cannot realistically produce it, but guard
    anyway — a zero id would be dropped by every parser downstream."""
    tid = secrets.token_hex(16)
    return tid if int(tid, 16) else new_trace_id()


def new_span_id() -> str:
    sid = secrets.token_hex(8)
    return sid if int(sid, 16) else new_span_id()


def parse_traceparent(header) -> Optional[Tuple[str, str, int]]:
    """``(trace_id, parent_span_id, flags)`` from a ``traceparent`` header
    value, or ``None`` for anything malformed (wrong shape, uppercase hex,
    all-zero ids, non-string): the caller starts a fresh context."""
    if not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":  # forbidden version per spec
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return trace_id, span_id, int(flags, 16)


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{FLAG_SAMPLED if sampled else 0:02x}"


def sample_rate() -> float:
    """The configured trace sampling ratio, clamped to [0, 1]."""
    try:
        rate = float(os.environ.get(ENV_SAMPLE, "1.0"))
    except ValueError:
        return 1.0
    return min(1.0, max(0.0, rate))


def trace_sampled(trace_id: str, rate: Optional[float] = None) -> bool:
    """Deterministic trace-id-ratio decision: the top 8 hex digits as a
    fraction of 2^32 against the rate — every process (and every retry)
    computes the same answer for the same trace id."""
    if rate is None:
        rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        return int(trace_id[:8], 16) / 2**32 < rate
    except (ValueError, TypeError):
        return False


class TraceContext:
    """One request's identity at one hop: trace id + this hop's span id +
    the upstream span id (when a header arrived) + the sampling verdict."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled

    @classmethod
    def from_header(cls, header,
                    rate: Optional[float] = None) -> "TraceContext":
        """Continue the client's context, or start a fresh edge context
        when the header is absent/malformed (never raises)."""
        parsed = parse_traceparent(header)
        if parsed is None:
            trace_id = new_trace_id()
            return cls(trace_id, new_span_id(), None,
                       trace_sampled(trace_id, rate))
        trace_id, parent_id, flags = parsed
        # honor an explicit client decision; a client that did not set the
        # sampled flag still gets the deterministic ratio decision so a
        # rate of 1.0 traces everything regardless of client flags
        sampled = bool(flags & FLAG_SAMPLED) or trace_sampled(trace_id, rate)
        return cls(trace_id, new_span_id(), parent_id, sampled)

    def header(self) -> str:
        return format_traceparent(self.trace_id, self.span_id, self.sampled)
