"""Startup pipeline: decoded-panel disk cache + streamed transfer + early
AOT compile (data/pipeline.py, data/diskcache.py).

The acceptance contract, tier-1 on CPU:
  * pipeline-produced device batches are BIT-IDENTICAL to the sequential
    `load_splits` + `device_put_batch` path on the dense, packed, and
    bf16-wire routes (and datasets match bitwise too);
  * the disk cache hits on an unchanged npz, misses + rewrites on any
    source change (mtime/size/header), and falls back to the npz decode on
    a corrupted cache entry;
  * `device_put_batch`/`stream_batch` routing: extra-key passthrough,
    bf16-wire ≡ post-hoc f32→bf16 cast, pack decision at both sides of
    AUTO_PACK_THRESHOLD;
  * a single-seed synthetic train lands the same final metrics with the
    pipeline on and off (train CLI A/B);
  * the native codec's g++ build stays off the load critical path;
  * the report CLI surfaces the startup breakdown from the pipeline spans.
"""

import json
import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.data import (
    diskcache,
    native,
    pipeline,
)
from deeplearninginassetpricing_paperreplication_tpu.data.panel import (
    load_splits,
)
from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
    AUTO_PACK_THRESHOLD,
    device_put_batch,
    pack_rows,
    warm_scatter,
)

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    """Every test gets a private, empty panel cache."""
    d = tmp_path / "panel_cache"
    monkeypatch.setenv("DLAP_PANEL_CACHE_DIR", str(d))
    monkeypatch.delenv("DLAP_PANEL_CACHE", raising=False)
    return d


# --------------------------------------------------------------------------
# shape probe
# --------------------------------------------------------------------------

def test_probe_split_shapes_matches_arrays(synthetic_dir, splits):
    shapes = pipeline.probe_split_shapes(synthetic_dir)
    for split, ds in zip(pipeline.SPLITS, splits):
        s = shapes[split]
        assert s["individual"] == ds.individual.shape
        assert s["returns"] == ds.returns.shape
        assert s["mask"] == ds.mask.shape
        assert s["macro"] == ds.macro.shape


def test_probe_reads_headers_not_payload(synthetic_dir):
    # the probe must stay cheap at any panel size: reading a 0.5 GB member
    # would defeat the early-compile stage. Headers parse in well under the
    # time a payload decompress would take even at this tiny size; assert
    # the API shape rather than time — and that dtype comes back f32.
    (t, n, c), dtype = pipeline.npz_member_shape(
        Path(synthetic_dir) / "char" / "Char_train.npz")
    assert (t, n) == (24, 64) and c == 11
    assert dtype == np.float32


# --------------------------------------------------------------------------
# streamed transfer ≡ device_put_batch (the tier-1 bit-identity criterion)
# --------------------------------------------------------------------------

ROUTES = [
    {"packed": True},
    {"packed": False},
    {"packed": "auto"},
    {"packed": True, "bf16_wire": True},
    {"packed": False, "bf16_wire": True},
]


@pytest.mark.parametrize("route", ROUTES)
def test_stream_batch_bit_identical(splits, route):
    ds = splits[0]
    batch = ds.full_batch()
    ref = device_put_batch(batch, **route)
    # chunk_bytes tiny → the multi-slab + on-device concatenate path runs
    got = pipeline.stream_batch(batch, chunk_bytes=4096, **route)
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]), err_msg=f"{route} {k}")
        assert np.asarray(ref[k]).dtype == np.asarray(got[k]).dtype


def test_stream_batch_packed_rep_short_circuits_dense_read(splits):
    """On a cache hit the packed triple is memmapped; stream_batch must use
    it verbatim (same bits as recomputing) — and single-chunk too."""
    ds = splits[0]
    batch = ds.full_batch()
    rep = pack_rows(batch["mask"], batch["individual"], batch["returns"])
    ref = device_put_batch(batch, packed=True)
    got = pipeline.stream_batch(batch, packed=True, packed_rep=rep)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(ref[k]), np.asarray(got[k]))


def test_stream_batch_extra_keys_pass_through(splits):
    ds = splits[0]
    batch = ds.full_batch()
    batch["n_assets"] = np.float32(ds.N - 3)
    for packed in (True, False):
        out = pipeline.stream_batch(batch, packed=packed)
        assert float(out["n_assets"]) == float(ds.N - 3)
        np.testing.assert_array_equal(np.asarray(out["macro"]), batch["macro"])


def test_device_put_batch_extra_keys_pass_through(splits):
    """Satellite: n_assets + macro ride every route of device_put_batch."""
    ds = splits[0]
    batch = ds.full_batch()
    batch["n_assets"] = np.float32(7)
    for kwargs in ({"packed": True}, {"packed": False},
                   {"packed": True, "bf16_wire": True}):
        out = device_put_batch(batch, **kwargs)
        assert float(out["n_assets"]) == 7.0
        np.testing.assert_array_equal(np.asarray(out["macro"]), batch["macro"])


def _coverage_batch(coverage, t=8, n=50, f=4, seed=0):
    rng = np.random.default_rng(seed)
    mask = (rng.random((t, n)) < coverage).astype(np.float32)
    ind = rng.standard_normal((t, n, f)).astype(np.float32) * mask[:, :, None]
    ret = rng.standard_normal((t, n)).astype(np.float32) * mask
    return {"individual": ind, "returns": ret, "mask": mask}


@pytest.mark.parametrize("coverage", [AUTO_PACK_THRESHOLD - 0.25,
                                      AUTO_PACK_THRESHOLD + 0.13])
def test_auto_pack_threshold_both_sides(coverage):
    """Satellite: at both sides of AUTO_PACK_THRESHOLD the auto route must
    (a) take the documented path and (b) stay bit-identical to both forced
    routes, for device_put_batch AND stream_batch."""
    batch = _coverage_batch(coverage)
    should_pack = float(batch["mask"].mean()) < AUTO_PACK_THRESHOLD
    # warm_scatter returns True exactly when "auto" packs — the one
    # externally visible encoding of the routing decision
    assert warm_scatter(batch) == should_pack
    auto = device_put_batch(batch, packed="auto")
    s_auto = pipeline.stream_batch(batch, packed="auto", chunk_bytes=2048)
    for forced in (True, False):
        ref = device_put_batch(batch, packed=forced)
        for k in ref:
            np.testing.assert_array_equal(np.asarray(ref[k]), np.asarray(auto[k]))
            np.testing.assert_array_equal(np.asarray(ref[k]), np.asarray(s_auto[k]))


def test_bf16_wire_equals_posthoc_cast(splits):
    """Satellite: the bf16 wire must land exactly the values a post-hoc
    f32-transfer → bf16 → f32 round-trip would produce (the compute route's
    later cast then reproduces identical bf16 bits)."""
    import jax.numpy as jnp

    ds = splits[0]
    batch = ds.full_batch()
    expected = (
        np.asarray(device_put_batch(batch, packed=False)["individual"])
        .astype(jnp.bfloat16).astype(np.float32)
    )
    for packed in (True, False):
        wired = device_put_batch(batch, packed=packed, bf16_wire=True)
        np.testing.assert_array_equal(np.asarray(wired["individual"]), expected)
        streamed = pipeline.stream_batch(
            batch, packed=packed, bf16_wire=True, chunk_bytes=4096)
        np.testing.assert_array_equal(
            np.asarray(streamed["individual"]), expected)


# --------------------------------------------------------------------------
# disk cache: hit / invalidation / corruption fallback
# --------------------------------------------------------------------------

def test_cache_hit_on_unchanged_npz(synthetic_dir, cache_dir):
    a = pipeline.load_splits_cached(synthetic_dir)  # miss + store
    b = pipeline.load_splits_cached(synthetic_dir)  # hit
    ref = load_splits(synthetic_dir)
    for ds_a, ds_b, ds_ref in zip(a, b, ref):
        for field in ("returns", "individual", "mask", "macro", "dates"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ds_a, field)),
                np.asarray(getattr(ds_ref, field)), err_msg=field)
            np.testing.assert_array_equal(
                np.asarray(getattr(ds_b, field)),
                np.asarray(getattr(ds_ref, field)), err_msg=field)
        np.testing.assert_array_equal(ds_b.mean_macro, ds_ref.mean_macro)
        np.testing.assert_array_equal(ds_b.std_macro, ds_ref.std_macro)
    # second load was served from cache: entry dirs exist and were reused
    entries = [d for d in cache_dir.iterdir() if d.is_dir()]
    assert len(entries) == 3  # one per split


def test_cache_misses_on_mtime_change(synthetic_dir, cache_dir):
    char = Path(synthetic_dir) / "char" / "Char_train.npz"
    macro = Path(synthetic_dir) / "macro" / "macro_train.npz"
    pipeline._load_split_raw(char, macro)  # store
    assert pipeline._load_split_raw(char, macro).cache_hit
    st = char.stat()
    os.utime(char, ns=(st.st_atime_ns, st.st_mtime_ns + 10**9))
    raw = pipeline._load_split_raw(char, macro)  # mtime changed → miss
    assert not raw.cache_hit
    # ... and the rewrite evicted the stale entry for the same source file
    entries = [d for d in cache_dir.iterdir() if d.is_dir()]
    assert len(entries) == 1


def test_cache_misses_on_content_change(synthetic_dir, tmp_path, monkeypatch):
    monkeypatch.setenv("DLAP_PANEL_CACHE_DIR", str(tmp_path / "pc"))
    data_dir = tmp_path / "data"
    import shutil

    shutil.copytree(synthetic_dir, data_dir)
    char = data_dir / "char" / "Char_train.npz"
    macro = data_dir / "macro" / "macro_train.npz"
    pipeline._load_split_raw(char, macro)
    assert pipeline._load_split_raw(char, macro).cache_hit
    # rewrite with different payload → size/CRC header change → miss, and
    # the re-decode reflects the NEW bytes (never the stale cache)
    with np.load(char, allow_pickle=True) as z:
        arrs = {k: z[k].copy() for k in z.files}
    arrs["data"] = arrs["data"] + np.float32(1.0)
    np.savez(char, **arrs)
    raw = pipeline._load_split_raw(char, macro)
    assert not raw.cache_hit
    fresh = pipeline._load_split_raw(char, macro)
    assert fresh.cache_hit
    np.testing.assert_array_equal(
        np.asarray(fresh.ds.returns), np.asarray(raw.ds.returns))


def test_corrupted_cache_entry_falls_back_to_npz(synthetic_dir, cache_dir):
    char = Path(synthetic_dir) / "char" / "Char_train.npz"
    macro = Path(synthetic_dir) / "macro" / "macro_train.npz"
    ref = pipeline._load_split_raw(char, macro)  # store
    entry = [d for d in cache_dir.iterdir() if d.is_dir()][0]
    # flavor 1: truncated array file
    rows = entry / "individual.npy"
    rows.write_bytes(rows.read_bytes()[: len(rows.read_bytes()) // 2])
    raw = pipeline._load_split_raw(char, macro)
    assert not raw.cache_hit  # corrupt entry deleted, npz decode served
    np.testing.assert_array_equal(
        np.asarray(raw.ds.individual), np.asarray(ref.ds.individual))
    # flavor 2: scribbled meta.json
    entry2 = [d for d in cache_dir.iterdir() if d.is_dir()][0]
    (entry2 / "meta.json").write_text("{not json")
    raw2 = pipeline._load_split_raw(char, macro)
    assert not raw2.cache_hit
    np.testing.assert_array_equal(
        np.asarray(raw2.ds.individual), np.asarray(ref.ds.individual))


def test_cache_disabled_by_env(synthetic_dir, cache_dir, monkeypatch):
    monkeypatch.setenv("DLAP_PANEL_CACHE", "0")
    pipeline.load_splits_cached(synthetic_dir)
    assert not cache_dir.exists() or not any(cache_dir.iterdir())


def test_cache_clear(synthetic_dir, cache_dir):
    pipeline.load_splits_cached(synthetic_dir)
    assert diskcache.clear() == 3
    assert not any(d.is_dir() for d in cache_dir.iterdir())


# --------------------------------------------------------------------------
# the full pipeline: bit-identity + early compile + cache round-trip
# --------------------------------------------------------------------------

def test_pipeline_bit_identical_miss_then_hit(synthetic_dir, cache_dir):
    ref_ds = load_splits(synthetic_dir)
    ref_b = [device_put_batch(ds.full_batch()) for ds in ref_ds]
    for expect_hit in (False, True):
        res = pipeline.StartupPipeline(synthetic_dir).start().result()
        assert all(h == expect_hit for h in res.cache_hits.values())
        for b_ref, b_got in zip(ref_b, res.batches):
            assert set(b_ref) == set(b_got)
            for k in b_ref:
                np.testing.assert_array_equal(
                    np.asarray(b_ref[k]), np.asarray(b_got[k]))
        for ds_ref, ds_got in zip(ref_ds, res.datasets):
            np.testing.assert_array_equal(
                np.asarray(ds_ref.macro), np.asarray(ds_got.macro))
            np.testing.assert_array_equal(ds_ref.mean_macro, ds_got.mean_macro)


def test_pipeline_compile_fn_runs_early_and_propagates(synthetic_dir, cache_dir):
    seen = {}
    started = threading.Event()

    def compile_fn(shapes):
        started.set()
        seen["shapes"] = shapes
        return "compiled-sentinel"

    res = pipeline.StartupPipeline(
        synthetic_dir, compile_fn=compile_fn).start().result()
    assert started.is_set()
    assert res.compiled == "compiled-sentinel"
    assert seen["shapes"]["train"]["individual"] == (24, 64, 10)


def test_pipeline_compile_fn_exception_reraised(synthetic_dir, cache_dir):
    def boom(shapes):
        raise RuntimeError("compile exploded")

    with pytest.raises(RuntimeError, match="compile exploded"):
        pipeline.StartupPipeline(
            synthetic_dir, compile_fn=boom).start().result()


def test_pipeline_emits_startup_spans_and_cache_counters(
        synthetic_dir, cache_dir, tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.observability import (
        EventLog,
    )

    ev = EventLog(tmp_path / "run", process_index=0)
    pipeline.StartupPipeline(synthetic_dir, events=ev).start().result()
    ev.close()
    rows = [json.loads(line)
            for line in (tmp_path / "run" / "events.jsonl").read_text().splitlines()]
    ends = {r["name"] for r in rows if r["kind"] == "span_end"}
    for split in pipeline.SPLITS:
        assert f"startup/load/{split}" in ends
        assert f"startup/transfer/{split}" in ends
    hits = [r for r in rows
            if r["kind"] == "counter" and r["name"] == "panel_cache"]
    assert len(hits) == 3 and all(h["hit"] is False for h in hits)


# --------------------------------------------------------------------------
# report: startup breakdown from the pipeline spans
# --------------------------------------------------------------------------

def test_report_startup_breakdown(synthetic_dir, cache_dir, tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.observability import (
        EventLog,
    )
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        load_run,
        main as report_main,
        summarize_run,
    )

    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    pipeline.StartupPipeline(synthetic_dir, events=ev).start().result()
    ev.close()
    s = summarize_run(load_run(run))
    st = s["startup"]
    assert st is not None
    for split in pipeline.SPLITS:
        assert f"load/{split}" in st["stages"]
        assert f"transfer/{split}" in st["stages"]
    assert st["cache"] == {"hits": 0, "misses": 3}
    # overlap-adjusted: the wall window never exceeds the stage-duration
    # sum by more than thread-scheduling gaps — on a saturated 1-core
    # full-suite run the decode/transfer threads can sit runnable-but-idle
    # BETWEEN stage spans for tens of ms (observed 31 ms under a 4x CPU
    # hog), which is wall time no stage accounts for; the margin absorbs
    # that while still failing if wall ever approached the UNadjusted sum
    # of overlapping stages
    assert st["wall_s"] <= sum(st["stages"].values()) + 0.25
    assert report_main([str(run)]) == 0
    out = capsys.readouterr().out
    assert "startup breakdown" in out
    assert "panel cache: 0 hits, 3 misses" in out


def test_report_startup_wall_is_window_not_sum(tmp_path):
    """Hand-stamped overlapping startup spans: wall must be the begin→end
    window (the stages run concurrently), not the per-stage sum."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        load_run,
        summarize_run,
    )

    run = tmp_path / "run"
    run.mkdir()
    rows = []
    names = ("startup/compile", "startup/load/train", "startup/transfer/train")
    for i, name in enumerate(names):
        rows.append({"kind": "span_begin", "name": name, "run_id": "r",
                     "process_index": 0, "seq": i + 1, "ts": 0.0,
                     "mono": 100.0 + i})
    for i, name in enumerate(names):
        rows.append({"kind": "span_end", "name": name, "run_id": "r",
                     "process_index": 0, "seq": i + 4, "ts": 0.0,
                     "mono": 106.0 + i, "duration_s": 6.0})
    with open(run / "events.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    st = summarize_run(load_run(run))["startup"]
    assert st["wall_s"] == pytest.approx(8.0)  # window, not 18
    assert st["stages"]["compile"] == pytest.approx(6.0)


# --------------------------------------------------------------------------
# train CLI A/B: identical final metrics with the pipeline on and off
# --------------------------------------------------------------------------

def test_train_cli_pipeline_on_off_parity(synthetic_dir, cache_dir, tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.train import main

    metrics = {}
    for label, extra in (("pipe", []), ("seq", ["--no_pipeline"])):
        run = tmp_path / label
        main(["--data_dir", str(synthetic_dir), "--save_dir", str(run),
              "--epochs_unc", "2", "--epochs_moment", "1", "--epochs", "2",
              "--ignore_epoch", "0", "--print_freq", "4",
              "--no_lstm", "--hidden_dim", "4", "--rnn_dim", "2"] + extra)
        metrics[label] = json.loads((run / "final_metrics.json").read_text())
    for split in ("train", "valid", "test"):
        assert metrics["pipe"][split] == metrics["seq"][split], split
    # the pipeline run left startup spans behind as evidence
    rows = [json.loads(line) for line in
            (tmp_path / "pipe" / "events.jsonl").read_text().splitlines()]
    names = {r["name"] for r in rows if r["kind"] == "span_end"}
    assert "startup/compile" in names
    assert "startup/transfer/train" in names
    manifest = json.loads((tmp_path / "pipe" / "manifest.json").read_text())
    assert manifest["startup_pipeline"] is True


# --------------------------------------------------------------------------
# native codec build stays off the load critical path
# --------------------------------------------------------------------------

def test_native_build_runs_in_background(monkeypatch, tmp_path):
    release = threading.Event()

    def slow_failing_build(so_path):
        release.wait(10.0)
        return False

    monkeypatch.setattr(native, "_build", slow_failing_build)
    monkeypatch.setattr(native, "_so_path", lambda: tmp_path / "absent.so")
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_FAILED", False)
    monkeypatch.setattr(native, "_BUILD_THREAD", None)
    t0 = time.monotonic()
    out = native.decode_panel(np.zeros((1, 2, 3), np.float32), -98.99)
    elapsed = time.monotonic() - t0
    # the decode fell through to NumPy (None) without waiting on the build
    assert out is None
    assert elapsed < 5.0
    release.set()
    # the explicit availability query joins the build → terminal failure
    assert native.native_available() is False
    assert native._FAILED is True


def test_native_decode_still_matches_after_async_load():
    """native_available() (which joins any build) then decode must work —
    the background build still produces a usable library."""
    if not native.native_available():
        pytest.skip("no C++ toolchain available")
    rng = np.random.default_rng(5)
    data = rng.standard_normal((3, 9, 4)).astype(np.float32)
    data[rng.random((3, 9)) < 0.4, 0] = -99.99
    out = native.decode_panel(data, -98.99)
    assert out is not None
    ret, ind = data[:, :, 0], data[:, :, 1:]
    mask = (ret > -98.99) & ~np.isnan(ret) & np.all(ind > -98.99, axis=2)
    np.testing.assert_array_equal(out[2], mask)


# --------------------------------------------------------------------------
# panel: subsample keeps the true asset count (satellite fix)
# --------------------------------------------------------------------------

def test_subsample_preserves_n_assets(splits):
    train = splits[0]
    padded = train.pad_stocks(100)  # 64 → 100, n_assets = 64
    assert padded.n_assets == train.N
    # keep more columns than real assets → some padded columns survive and
    # the true count must ride along (was dropped before this fix)
    sub = padded.subsample(n_periods=10, n_stocks=80)
    assert sub.n_assets == train.N
    assert "n_assets" in sub.full_batch()
    assert float(sub.full_batch()["n_assets"]) == train.N
    # keep fewer than the real count → every kept column is real; the key
    # collapses (min(n_assets, N) == N) exactly like an unpadded panel
    sub2 = padded.subsample(n_periods=10, n_stocks=32)
    assert sub2.n_assets == 32
    assert "n_assets" not in sub2.full_batch()
    # unpadded panels stay None
    assert train.subsample(10, 16).n_assets is None


# --------------------------------------------------------------------------
# lint gate: the new data modules stay clean under the pyproject ruff rules
# --------------------------------------------------------------------------

PKG = REPO / "deeplearninginassetpricing_paperreplication_tpu"
LINTED_NEW = [PKG / "data" / "pipeline.py", PKG / "data" / "diskcache.py"]


def test_new_data_modules_lint_clean():
    import sys

    from test_observability import _ast_unused_imports

    try:
        import subprocess

        import ruff  # noqa: F401

        proc = subprocess.run(
            [sys.executable, "-m", "ruff", "check",
             *[str(p) for p in LINTED_NEW]],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
    except ImportError:
        problems = {}
        for path in LINTED_NEW:
            unused = _ast_unused_imports(path)
            if unused:
                problems[path.name] = unused
        assert not problems, f"unused imports: {problems}"
