"""Tier-1 coverage for the unified run-telemetry layer (observability/).

Covers the artifact contract end to end, CPU-only:
  * span nesting/ordering + seq monotonicity in events.jsonl;
  * manifest schema round-trip through json + stable config hashing;
  * heartbeat files parse with bench.py's phase-attribution machinery;
  * device-memory aggregation over ALL local devices (the 8-device virtual
    CPU mesh from conftest);
  * the report CLI over a synthetic run dir and over a real tiny training
    run (the acceptance-criterion path: train → manifest.json +
    events.jsonl → report);
  * the observability package lints clean under the pyproject ruff rules
    (AST fallback when ruff is not installed).
"""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

from deeplearninginassetpricing_paperreplication_tpu.observability import (
    EventLog,
    Heartbeat,
    RunLogger,
    build_manifest,
    config_hash,
    device_memory_snapshot,
    load_manifest,
    write_manifest,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
    GANConfig,
    TrainConfig,
)

REPO = Path(__file__).resolve().parents[1]


def _read_events(path):
    return [json.loads(line) for line in Path(path).read_text().splitlines()]


# --------------------------------------------------------------------------
# events.jsonl: spans, ordering, nesting
# --------------------------------------------------------------------------

def test_span_nesting_and_ordering(tmp_path):
    ev = EventLog(tmp_path, process_index=0)
    with ev.span("phase/outer", epochs=4) as outer:
        with ev.span("compile/inner"):
            pass
    ev.counter("epochs_dispatched", value=4, phase="outer")
    ev.gauge("lr", 1e-3)
    ev.close()

    rows = _read_events(tmp_path / "events.jsonl")
    # strict seq ordering, one shared run id, process index stamped
    assert [r["seq"] for r in rows] == sorted(r["seq"] for r in rows)
    assert len({r["run_id"] for r in rows}) == 1
    assert all(r["process_index"] == 0 for r in rows)
    assert all("ts" in r and "mono" in r for r in rows)

    kinds = [(r["kind"], r["name"]) for r in rows]
    assert kinds == [
        ("span_begin", "phase/outer"),
        ("span_begin", "compile/inner"),
        ("span_end", "compile/inner"),
        ("span_end", "phase/outer"),
        ("counter", "epochs_dispatched"),
        ("gauge", "lr"),
    ]
    begin_outer, begin_inner, end_inner, end_outer = rows[:4]
    assert begin_outer["depth"] == 0 and begin_outer["parent"] is None
    assert begin_inner["depth"] == 1 and begin_inner["parent"] == "phase/outer"
    assert end_outer["duration_s"] >= end_inner["duration_s"] >= 0
    assert begin_outer["epochs"] == 4  # attrs ride on both rows
    assert end_outer["status"] == "ok"
    assert outer.seconds > 0


def test_span_records_error_status(tmp_path):
    ev = EventLog(tmp_path)
    with pytest.raises(ValueError):
        with ev.span("phase/boom"):
            raise ValueError("x")
    rows = _read_events(tmp_path / "events.jsonl")
    assert rows[-1]["status"] == "error" and rows[-1]["error"] == "ValueError"


def test_sinkless_eventlog_still_times_spans(tmp_path):
    ev = EventLog()  # no run dir: the trainer's default
    assert not ev.enabled
    with ev.span("compile/x") as sp:
        sum(range(1000))
    assert sp.seconds >= 0.0
    assert list(tmp_path.iterdir()) == []  # nothing written anywhere


def test_worker_processes_write_their_own_file(tmp_path):
    EventLog(tmp_path, process_index=1).log("worker line")
    assert (tmp_path / "events.proc1.jsonl").exists()
    assert not (tmp_path / "events.jsonl").exists()


# --------------------------------------------------------------------------
# manifest.json
# --------------------------------------------------------------------------

def test_manifest_schema_roundtrips_through_json(tmp_path):
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    (data_dir / "Char_train.npz").write_bytes(b"\x00" * 2048)
    cfg = GANConfig(macro_feature_dim=4, individual_feature_dim=6)
    tcfg = TrainConfig(num_epochs_unc=2, num_epochs_moment=1, num_epochs=2)
    ev = EventLog(tmp_path / "run")
    m = write_manifest(tmp_path / "run", "train", events=ev,
                       config=cfg, tcfg=tcfg, seed=42, data_dir=data_dir)

    # round-trip: what json gives back is exactly what was built
    loaded = load_manifest(tmp_path / "run")
    assert loaded == json.loads(json.dumps(m))
    assert loaded["kind"] == "train"
    assert loaded["run_id"] == ev.run_id  # events and manifest cross-ref
    assert loaded["seed"] == 42
    assert loaded["config"]["macro_feature_dim"] == 4
    assert loaded["train_config"]["num_epochs_unc"] == 2
    assert loaded["versions"]["jax"] is not None
    assert loaded["devices"]["backend"] == "cpu"
    assert loaded["devices"]["device_count"] >= 8  # conftest virtual mesh
    assert loaded["data"]["n_files"] == 1
    assert loaded["data"]["total_bytes"] == 2048
    assert len(loaded["data"]["digest"]) == 64


def test_config_hash_is_stable_and_discriminating():
    a = GANConfig(macro_feature_dim=4, individual_feature_dim=6)
    b = GANConfig(macro_feature_dim=4, individual_feature_dim=6)
    c = GANConfig(macro_feature_dim=4, individual_feature_dim=6,
                  hidden_dim=(32, 32))
    assert config_hash(a) == config_hash(b)
    assert config_hash(a) != config_hash(c)
    assert config_hash(None) is None


def test_manifest_survives_missing_probes(tmp_path):
    # no config, no data dir, argv explicit: every probe degrades to None
    m = build_manifest("train", argv=["--x"])
    assert m["config"] is None and m["config_hash"] is None
    assert m["data"] is None
    json.dumps(m)  # JSON-serializable whatever the probes returned


# --------------------------------------------------------------------------
# heartbeat.json: bench.py's phase-attribution protocol
# --------------------------------------------------------------------------

def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_obs_test", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_heartbeat_format_accepted_by_bench_parser(tmp_path):
    bench = _load_bench()
    path = tmp_path / "heartbeat.json"
    hb = Heartbeat(path, events=EventLog(tmp_path))
    hb.beat("phase3_conditional", memory=True)

    state = bench._read_state(path)
    # the exact expressions the bench parent uses for hang timing and
    # death attribution (orchestrate())
    assert (state.get("heartbeat") or {}).get("section", "setup") == \
        "phase3_conditional"
    assert isinstance(state["heartbeat"]["ts"], float)
    # the aggregated memory snapshot rides in the same state file
    assert state["device_memory"]["n_devices"] >= 8

    # bench's writer and ours are the same implementation (delegation):
    bench._heartbeat(path, state, "ensemble")
    assert Heartbeat(path).section == "ensemble"


def test_heartbeat_merges_over_existing_state(tmp_path):
    path = tmp_path / "hb.json"
    Heartbeat(path).beat("setup", extra_key=1)
    hb2 = Heartbeat(path)  # a respawned process keeps prior keys
    hb2.beat("phase1_unconditional")
    state = json.loads(path.read_text())
    assert state["extra_key"] == 1
    assert state["heartbeat"]["section"] == "phase1_unconditional"


# --------------------------------------------------------------------------
# heartbeat supervision semantics (satellite: staleness, tolerant reads,
# section attribution — the primitives reliability/supervisor.py times by)
# --------------------------------------------------------------------------

def test_read_state_tolerates_missing_and_midwrite_files(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.observability.heartbeat import (
        read_state,
    )

    assert read_state(tmp_path / "nope.json") == {}
    torn = tmp_path / "torn.json"
    torn.write_text('{"heartbeat": {"section": "phase1_unc')  # mid-write
    assert read_state(torn) == {}  # tolerant: never a raise, never partial


def test_last_beat_and_staleness_math(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.observability.heartbeat import (
        is_stale,
        last_beat,
        staleness_s,
    )

    now = 1_000_000.0
    state = {"heartbeat": {"section": "phase2_moment", "ts": now - 30.0}}
    assert last_beat(state) == ("phase2_moment", now - 30.0)
    assert staleness_s(state, now=now) == pytest.approx(30.0)
    assert is_stale(state, 10.0, now=now)
    assert not is_stale(state, 60.0, now=now)

    # malformed / absent heartbeats: no age → never declared hung
    assert last_beat({}) == (None, None)
    assert last_beat({"heartbeat": {"section": "s", "ts": "garbage"}}) == \
        ("s", None)
    assert staleness_s({}, now=now) is None
    assert not is_stale({}, 10.0, now=now)


def test_staleness_floor_protects_fresh_children():
    """A stale heartbeat inherited from a killed predecessor must not get a
    fresh child SIGKILLed before it can write its own beat — the supervisor
    times against max(heartbeat ts, spawn ts)."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.heartbeat import (
        is_stale,
        staleness_s,
    )

    now = 1_000_000.0
    stale_state = {"heartbeat": {"section": "ensemble", "ts": now - 900.0}}
    spawn_ts = now - 5.0
    assert staleness_s(stale_state, now=now, floor_ts=spawn_ts) == \
        pytest.approx(5.0)
    assert not is_stale(stale_state, 300.0, now=now, floor_ts=spawn_ts)
    # no heartbeat at all: the floor still provides the age
    assert staleness_s({}, now=now, floor_ts=spawn_ts) == pytest.approx(5.0)


def test_beat_section_attribution_roundtrip(tmp_path):
    """Death attribution end to end: the section named by the LAST beat is
    what a supervisor reads back, whatever order sections ran in."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.heartbeat import (
        last_beat,
        read_state,
    )

    path = tmp_path / "hb.json"
    hb = Heartbeat(path)
    for section in ("setup", "phase1_unconditional", "phase3_conditional"):
        hb.beat(section)
    section, ts = last_beat(read_state(path))
    assert section == "phase3_conditional"
    assert isinstance(ts, float)


# --------------------------------------------------------------------------
# device memory aggregation (satellite: all local devices, not device 0)
# --------------------------------------------------------------------------

def test_device_memory_snapshot_covers_all_local_devices():
    import jax

    snap = device_memory_snapshot()
    assert snap["n_devices"] == len(jax.local_devices()) >= 8
    assert len(snap["per_device"]) == snap["n_devices"]
    assert all("device" in d for d in snap["per_device"])
    # CPU devices may expose no counters; when they do, sums must cover
    # every device, not just device 0
    for key, total in snap["totals"].items():
        per_dev = [d.get(key, 0) for d in snap["per_device"]]
        if any(tag in key for tag in ("peak", "largest", "limit")):
            assert total == max(per_dev)
        else:
            assert total == sum(per_dev)


def test_trainer_timings_report_aggregated_memory():
    from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
        Trainer,
    )

    totals = Trainer.device_memory_stats()
    assert isinstance(totals, dict)
    snap = device_memory_snapshot()
    assert totals == snap["totals"]


# --------------------------------------------------------------------------
# report CLI
# --------------------------------------------------------------------------

def _synthetic_run_dir(tmp_path):
    """A hand-built run dir exercising every report input path."""
    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    with ev.span("compile/phase_unconditional"):
        pass
    with ev.span("compile/phase_conditional"):
        pass
    # non-zero sleep: a `pass` body can round to duration_s == 0.0 at clock
    # resolution, which reports throughput as n/a
    import time

    with ev.span("phase/phase1_unconditional", epochs=2):
        time.sleep(0.01)
    with ev.span("phase/phase3_conditional", epochs=3):
        time.sleep(0.01)
    ev.emit("memory", "device_memory", n_devices=2,
            totals={"bytes_in_use": 3 << 20, "peak_bytes_in_use": 5 << 20},
            per_device=[])
    write_manifest(run, "train", events=ev,
                   config=GANConfig(macro_feature_dim=2,
                                    individual_feature_dim=3),
                   seed=1)
    with open(run / "metrics.jsonl", "w") as f:
        for phase, n in (("unc", 2), ("cond", 3)):
            for e in range(n):
                f.write(json.dumps({"phase": phase, "epoch": e,
                                    "train_loss": 0.1}) + "\n")
    (run / "final_metrics.json").write_text(json.dumps({
        "train": {"sharpe": -1.0}, "valid": {"sharpe": 0.36},
        "test": {"sharpe": 0.08},
        "wall_clock_s": 12.5,
        "compile_seconds": {}, "phase_execute_seconds": {},
        "device_memory": {"totals": {"bytes_in_use": 1 << 20}},
    }))
    ev.close()
    return run


def test_report_cli_text_output(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    run = _synthetic_run_dir(tmp_path)
    assert main([str(run)]) == 0
    out = capsys.readouterr().out
    assert "compile vs execute" in out
    assert "phase_unconditional" in out and "phase_conditional" in out
    assert "per-phase throughput" in out
    assert "2 epochs" in out and "3 epochs" in out
    assert "epochs/s" in out
    assert "peak bytes in use" in out and "GiB" in out
    assert "final sharpe" in out


def test_report_cli_json_and_summary_content(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    run = _synthetic_run_dir(tmp_path)
    assert main([str(run), "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["kind"] == "train"
    assert set(s["compile_seconds"]) == {"phase_unconditional",
                                         "phase_conditional"}
    assert s["phases"]["phase1_unconditional"]["epochs"] == 2
    assert s["phases"]["phase3_conditional"]["epochs"] == 3
    assert s["phases"]["phase1_unconditional"]["epochs_per_s"] is not None
    # memory: max over event snapshots and final_metrics totals
    assert s["peak_bytes_in_use"] == 3 << 20
    assert s["peak_peak_bytes_in_use"] == 5 << 20
    assert s["wall_clock_s"] == 12.5
    assert s["sharpe"]["test"] == 0.08


def test_report_parity_comparison(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    run = _synthetic_run_dir(tmp_path)
    parity = tmp_path / "PARITY_FAKE.json"
    parity.write_text(json.dumps({
        "reference": {"sharpe": {"train": -1.0, "valid": 0.367,
                                 "test": 0.089}},
    }))
    assert main([str(run), "--parity", str(parity), "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    splits = s["parity"]["splits"]
    # train is informational only — the repo's bar gates valid/test
    assert splits["train"]["within_bar"] is None
    assert splits["train"]["abs_delta"] == 0.0
    assert splits["valid"]["within_bar"] is True  # |Δ| = 0.007
    assert splits["valid"]["abs_delta"] == pytest.approx(0.007, abs=1e-9)
    assert splits["test"]["within_bar"] is True   # |Δ| = 0.009


def test_report_resumed_phase_counts_only_executed_epochs(tmp_path):
    """A mid-phase resume's span times epochs [start, total) while
    metrics.jsonl re-lists the whole phase — throughput must divide the
    span's epoch count, not the row count."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        load_run,
        summarize_run,
    )

    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    import time

    with ev.span("phase/phase1_unconditional", epochs=256, start_epoch=200):
        time.sleep(0.01)
    ev.close()
    with open(run / "metrics.jsonl", "w") as f:
        for e in range(256):  # full-phase rows (resume prepends the prefix)
            f.write(json.dumps({"phase": "unc", "epoch": e,
                                "run_id": ev.run_id}) + "\n")
    s = summarize_run(load_run(run))
    assert s["phases"]["phase1_unconditional"]["epochs"] == 56


def test_report_compile_total_is_wall_not_sum(tmp_path):
    """Phase programs compile CONCURRENTLY (Trainer.precompile): the
    compile total must be the begin→end wall window, not the sum of
    per-program latencies (~3x too big on a default run)."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        load_run,
        summarize_run,
    )

    run = tmp_path / "run"
    run.mkdir()
    rows = []
    # three overlapping 8s compiles inside a 10s window, hand-stamped mono
    for i, name in enumerate(("compile/a", "compile/b", "compile/c")):
        rows.append({"kind": "span_begin", "name": name, "run_id": "r",
                     "process_index": 0, "seq": i + 1, "ts": 0.0,
                     "mono": 100.0 + i})
    for i, name in enumerate(("compile/a", "compile/b", "compile/c")):
        rows.append({"kind": "span_end", "name": name, "run_id": "r",
                     "process_index": 0, "seq": i + 4, "ts": 0.0,
                     "mono": 108.0 + i, "duration_s": 8.0})
    with open(run / "events.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    s = summarize_run(load_run(run))
    assert s["total_compile_s"] == pytest.approx(10.0)  # window, not 24


def test_report_tolerates_null_sharpe_in_final_metrics(tmp_path, capsys):
    """A crashed/partial final_metrics.json with sharpe: null must not
    take down the report (with or without --parity)."""
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    run = tmp_path / "run"
    run.mkdir()
    (run / "final_metrics.json").write_text(json.dumps({
        "test": {"sharpe": None}, "valid": {"sharpe": 0.3},
    }))
    parity = tmp_path / "p.json"
    parity.write_text(json.dumps(
        {"reference": {"sharpe": {"valid": 0.3, "test": 0.1}}}))
    assert main([str(run), "--parity", str(parity)]) == 0
    out = capsys.readouterr().out
    assert "valid" in out  # the numeric split still compares


def test_report_budget_stopped_phase_uses_dispatch_counters(tmp_path):
    """--stop_after_epochs: the span attr still says the PLANNED epoch
    count; the trainer's epochs_dispatched counters carry what actually
    ran, and they win."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        load_run,
        summarize_run,
    )

    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    import time

    with ev.span("phase/phase1_unconditional", epochs=256, start_epoch=0):
        ev.counter("epochs_dispatched", value=10,
                   phase="phase1_unconditional", epochs_done=10)
        time.sleep(0.01)
    ev.close()
    s = summarize_run(load_run(run))
    assert s["phases"]["phase1_unconditional"]["epochs"] == 10


def test_report_parity_missing_baseline_fails_loudly(tmp_path, capsys):
    """An unreadable --parity baseline must exit nonzero with a warning,
    never pass vacuously (CI-gate safety)."""
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    run = _synthetic_run_dir(tmp_path)
    assert main([str(run), "--parity", str(tmp_path / "nope.json")]) == 1
    captured = capsys.readouterr()
    assert "parity comparison failed" in captured.err
    assert "PARITY COMPARISON FAILED" in captured.out


def test_report_multiple_run_dirs(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    r1 = _synthetic_run_dir(tmp_path / "a")
    r2 = _synthetic_run_dir(tmp_path / "b")
    assert main([str(r1), str(r2)]) == 0
    out = capsys.readouterr().out
    assert "comparison (headline numbers)" in out
    assert out.count("run dir:") == 2


def test_report_scopes_to_latest_run_but_keeps_worker_files(tmp_path):
    """A re-run appends under a fresh run_id: the report must scope each
    file to ITS latest run (not drop worker files via a global manifest
    filter, and not mix stale epoch rows into throughput)."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        load_run,
        summarize_run,
    )

    run = tmp_path / "run"
    # first (stale) invocation
    ev_old = EventLog(run, run_id="run-old", process_index=0)
    with ev_old.span("phase/phase1_unconditional", epochs=8):
        pass
    ev_old.close()
    # latest invocation, same dir — plus a worker stream with its own id
    ev_new = EventLog(run, run_id="run-new", process_index=0)
    import time

    with ev_new.span("phase/phase1_unconditional", epochs=2):
        time.sleep(0.01)
    write_manifest(run, "train", events=ev_new)
    ev_new.close()
    EventLog(run, run_id="run-worker", process_index=1).log("worker alive")
    with open(run / "metrics.jsonl", "w") as f:
        for rid, n in (("run-old", 8), ("run-new", 2)):
            for e in range(n):
                f.write(json.dumps({"phase": "unc", "epoch": e,
                                    "run_id": rid}) + "\n")

    s = summarize_run(load_run(run))
    # only the latest run's 2 epochs and its span count toward throughput
    assert s["phases"]["phase1_unconditional"]["epochs"] == 2
    # the worker's rows survive scoping (per-file, not global)
    rows = load_run(run)["events"]
    assert any(r["run_id"] == "run-worker" for r in rows)
    assert not any(r["run_id"] == "run-old" for r in rows)


def test_report_tolerates_empty_dir(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    empty = tmp_path / "empty"
    empty.mkdir()
    assert main([str(empty)]) == 0  # n/a everywhere, never a crash
    assert "n/a" in capsys.readouterr().out


# --------------------------------------------------------------------------
# acceptance path: tiny real training run → telemetry artifacts → report
# --------------------------------------------------------------------------

def test_train_cli_writes_manifest_and_events(synthetic_dir, tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import (
        main as report_main,
    )
    from deeplearninginassetpricing_paperreplication_tpu.train import main

    run = tmp_path / "run"
    main(["--data_dir", str(synthetic_dir), "--save_dir", str(run),
          "--epochs_unc", "2", "--epochs_moment", "1", "--epochs", "2",
          "--ignore_epoch", "0", "--print_freq", "4",
          "--no_lstm", "--hidden_dim", "4", "--rnn_dim", "2"])

    # the run dir is self-describing: manifest + events alongside the
    # existing artifacts
    manifest = load_manifest(run)
    assert manifest["kind"] == "train"
    assert manifest["config_hash"] is not None
    assert manifest["data"]["digest"]
    rows = _read_events(run / "events.jsonl")
    assert {r["run_id"] for r in rows} == {manifest["run_id"]}
    names = {r["name"] for r in rows if r["kind"] == "span_end"}
    assert any(n.startswith("compile/") for n in names)
    assert {"phase/phase1_unconditional", "phase/phase2_moment",
            "phase/phase3_conditional"} <= names
    assert any(r["kind"] == "memory" for r in rows)
    hb_state = json.loads((run / "heartbeat.json").read_text())
    assert hb_state["heartbeat"]["section"] == "finalize"
    assert (run / "final_metrics.json").exists()
    fm = json.loads((run / "final_metrics.json").read_text())
    assert set(fm["device_memory"]) == {"n_devices", "totals", "per_device"}
    assert fm["device_memory"]["n_devices"] >= 8

    capsys.readouterr()  # drop training stdout
    assert report_main([str(run)]) == 0
    out = capsys.readouterr().out
    assert "compile vs execute" in out
    assert "phase1_unconditional: 2 epochs" in out
    assert "final sharpe" in out


def test_profile_trace_verification_helper(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.train import (
        profile_trace_nonempty,
    )

    assert profile_trace_nonempty(tmp_path / "missing") is False
    empty = tmp_path / "empty"
    empty.mkdir()
    assert profile_trace_nonempty(empty) is False
    nested = tmp_path / "trace" / "plugins"
    nested.mkdir(parents=True)
    (nested / "t.trace").write_bytes(b"x")
    assert profile_trace_nonempty(tmp_path / "trace") is True


# --------------------------------------------------------------------------
# run logger gating
# --------------------------------------------------------------------------

def test_run_logger_gates_prints_and_records_events(tmp_path, capsys):
    ev0 = EventLog(tmp_path / "a", process_index=0)
    RunLogger(events=ev0).info("hello from primary")
    assert "hello from primary" in capsys.readouterr().out

    ev1 = EventLog(tmp_path / "b", process_index=1)
    logger1 = RunLogger(events=ev1)
    logger1.info("hello from worker")
    logger1.warning("worker warning")
    captured = capsys.readouterr()
    assert captured.out == "" and captured.err == ""  # non-primary: silent
    rows = _read_events(tmp_path / "b" / "events.proc1.jsonl")
    assert [r["message"] for r in rows if r["kind"] == "log"] == \
        ["hello from worker", "worker warning"]
    levels = [r["name"] for r in rows if r["kind"] == "log"]
    assert levels == ["info", "warning"]


def test_run_logger_verbose_override(tmp_path, capsys):
    logger = RunLogger(events=EventLog(tmp_path, process_index=0),
                       verbose=True)
    logger.info("quiet line", verbose=False)
    assert capsys.readouterr().out == ""
    rows = _read_events(tmp_path / "events.jsonl")
    assert rows[-1]["message"] == "quiet line"  # still recorded


# --------------------------------------------------------------------------
# lint: the telemetry sink stays clean (ruff config in pyproject.toml)
# --------------------------------------------------------------------------

OBS_DIR = REPO / "deeplearninginassetpricing_paperreplication_tpu" / "observability"


def test_pyproject_has_ruff_lint_config():
    text = (REPO / "pyproject.toml").read_text()
    assert "[tool.ruff.lint]" in text
    for rule in ("F401", "F811", "F841", '"I"'):
        assert rule in text


def _ast_unused_imports(path):
    """Fallback F401 checker for when ruff isn't installed: names imported
    at module level but never referenced anywhere in the module."""
    import ast

    tree = ast.parse(path.read_text())
    imported = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imported[(a.asname or a.name).split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # `from __future__ import annotations` is a pragma
            for a in node.names:
                if a.name != "*":
                    imported[a.asname or a.name] = node.lineno
    used = {
        n.id for n in ast.walk(tree) if isinstance(n, ast.Name)
    } | {
        n.attr for n in ast.walk(tree) if isinstance(n, ast.Attribute)
    }
    # __all__ re-exports count as use
    for node in ast.walk(tree):
        if isinstance(node, (ast.List, ast.Tuple)):
            for elt in node.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    used.add(elt.value)
    return {name: ln for name, ln in imported.items() if name not in used}


def test_observability_package_lints_clean():
    try:
        import subprocess

        import ruff  # noqa: F401

        proc = subprocess.run(
            [sys.executable, "-m", "ruff", "check", str(OBS_DIR)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
    except ImportError:
        # container without ruff: enforce the F401 core of the config with
        # the AST fallback so the gate still bites
        problems = {}
        for path in sorted(OBS_DIR.glob("*.py")):
            unused = _ast_unused_imports(path)
            # the package __init__ re-exports via __all__ strings
            if unused:
                problems[path.name] = unused
        assert not problems, f"unused imports: {problems}"


def test_observability_package_has_no_top_level_star_imports():
    for path in sorted(OBS_DIR.glob("*.py")):
        assert "import *" not in path.read_text(), path
