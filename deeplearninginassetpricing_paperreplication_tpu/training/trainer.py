"""3-phase GAN trainer — every epoch loop is ONE compiled `lax.scan`.

The reference's trainer (``/root/reference/src/train.py:156-426``) runs 1,344
Python epochs, each doing a host→device round trip, two extra eval forwards,
and a host-side best-model copy. Here each phase compiles to a single XLA
program: `lax.scan` over epochs with the train step, the valid/test eval
steps, and best-model tracking (a `jnp.where`-selected copy of the 12k-param
tree) all fused on device. The host sees only the final carry and the stacked
per-epoch history — three compiles, three device calls, zero per-epoch syncs.

Replicated selection semantics (they shape the final Sharpe — SURVEY §3.5):
  * best-by-valid-sharpe and best-by-valid-loss tracked independently, only
    for epochs with index > ignore_epoch (strict, train.py:262, 372);
  * Phase 1 selects on valid `loss_unc` / sharpe; Phase 3 on valid
    `loss_cond` / sharpe; trackers reset between phases;
  * the best-sharpe params are reloaded after Phase 1 (train.py:289-292) and
    after Phase 3 (train.py:398-400); if a phase never updates (epochs ≤
    ignore_epoch), the previous best — or the running params — carry forward,
    exactly like the reference's `if best_model_state is not None` guard;
  * Phase 2 trains the moment net on the NEGATED conditional loss starting
    from the Phase-1-best sdf params, tracks best-by-highest train loss_cond
    for the loss checkpoint, and hands its LAST-epoch moment params to
    Phase 3 (no reload — train.py:304-336);
  * the sdf Adam state persists from Phase 1 into Phase 3 (the reference
    reuses `optimizer_sdf`, train.py:210, 242, 352).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from functools import partial
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gan import GAN
from ..observability.events import EventLog
from ..observability.heartbeat import Heartbeat
from ..observability.memory import device_memory_snapshot, log_memory
from ..observability.xla import record_program
from ..ops.metrics import cross_sectional_r2, explained_variation, factor_betas, max_drawdown
from ..reliability import verified
from ..reliability.faults import inject
from ..reliability.guard import DivergenceError, segment_nonfinite
from ..utils.config import GANConfig, TrainConfig
from ..utils.rng import train_base_key
from .checkpoint import save_params
from .steps import make_eval_step, make_optimizer, trainable_key

Params = Any
Batch = Dict[str, jnp.ndarray]

# phase name → the section label used in heartbeats, spans, and the
# compile/execute timing dicts (also what metrics.jsonl tags map to in
# observability.report.PHASE_LABELS)
PHASE_SECTIONS = {
    "unconditional": "phase1_unconditional",
    "moment": "phase2_moment",
    "conditional": "phase3_conditional",
}

PHASE_NUMBERS = {"unconditional": 1, "moment": 2, "conditional": 3}


def _select(pred, new_tree, old_tree):
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), new_tree, old_tree)


def _concat_hists(hists: list) -> Dict[str, np.ndarray]:
    """Concatenate per-segment stacked-history dicts along the epoch axis."""
    if len(hists) == 1:
        return {k: np.asarray(v) for k, v in hists[0].items()}
    return {
        k: np.concatenate([np.asarray(h[k]) for h in hists], axis=0)
        for k in hists[0]
    }


def _zeros_like_metrics():
    return {
        "loss": jnp.float32(0.0),
        "loss_unc": jnp.float32(0.0),
        "loss_cond": jnp.float32(0.0),
        "sharpe": jnp.float32(0.0),
        "mean_return": jnp.float32(0.0),
        "std_return": jnp.float32(0.0),
    }


def build_phase_scan(
    gan: GAN,
    phase: str,
    tx,
    num_epochs: int,
    ignore_epoch: int,
    has_test: bool = True,
    diag_stride: Optional[int] = None,
):
    """The pure (un-jitted) compiled-phase function:

        run(params, opt_state, best_init, train_b, valid_b, test_b, rng)
            → (params, opt_state, best, history)

    A `lax.scan` over epochs fusing the train step, valid/test evals, and
    best-model tracking. `Trainer` jits it for single-model training;
    `parallel.ensemble` vmaps it over seeds/configs before jitting.

    ``diag_stride``: fold the model-health diagnostic kernels
    (:mod:`ops.diagnostics`) into the scan body — every ``diag_stride``-th
    epoch computes the per-moment violation norms, SDF/portfolio stats,
    and adversarial gap on the VALID batch (eval-mode forward, no
    dropout), landing as ``diag_*`` history fields; off-stride epochs emit
    zeros through a ``lax.cond`` whose operand is only the params tree.
    The diagnostics read the carry and never feed it, so the trained
    params/best checkpoints are BIT-identical with the knob on or off
    (asserted in tier-1), and the zero-per-epoch-host-sync discipline is
    untouched. Phase 2 (no per-epoch evals, reference semantics) skips
    them — its history rows never join history.npz anyway.
    """
    from ..ops.diagnostics import make_diag_fn, strided_diagnostics
    from .steps import make_eval_step as _mk_eval, make_train_step as _mk_train

    train_step = _mk_train(gan, phase, tx)
    eval_step = _mk_eval(gan)
    track_eval = phase != "moment"
    loss_key = "loss_unc" if phase == "unconditional" else "loss_cond"
    diag_fn = (make_diag_fn(gan)
               if (diag_stride and track_eval) else None)
    n_moments = gan.cfg.num_condition_moment

    def epoch_body(carry, epoch, train_batch, valid_batch, test_batch, base_rng):
        params, opt_state, best = carry
        rng = jax.random.fold_in(base_rng, epoch)
        params, opt_state, tr = train_step(params, opt_state, train_batch, rng)

        if track_eval:
            va = eval_step(params, valid_batch)
            te = eval_step(params, test_batch) if has_test else _zeros_like_metrics()
            eligible = epoch > ignore_epoch
            better_loss = eligible & (va[loss_key] < best["loss"])
            better_sharpe = eligible & (va["sharpe"] > best["sharpe"])
            best = {
                "loss": jnp.where(better_loss, va[loss_key], best["loss"]),
                "sharpe": jnp.where(better_sharpe, va["sharpe"], best["sharpe"]),
                "params_loss": _select(better_loss, params, best["params_loss"]),
                "params_sharpe": _select(better_sharpe, params, best["params_sharpe"]),
                "updated_loss": best["updated_loss"] | better_loss,
                "updated_sharpe": best["updated_sharpe"] | better_sharpe,
            }
            hist = {
                "train_loss": tr["loss"],
                "train_sharpe": tr["sharpe"],
                "grad_norm": tr["grad_norm"],
                "valid_loss": va[loss_key],
                "valid_sharpe": va["sharpe"],
                "test_loss": te[loss_key],
                "test_sharpe": te["sharpe"],
            }
            if diag_fn is not None:
                diag = strided_diagnostics(
                    diag_fn, params, valid_batch, epoch, diag_stride,
                    n_moments)
                hist.update({f"diag_{k}": v for k, v in diag.items()})
        else:
            # Phase 2: no per-epoch evals (train.py:304-336); select the
            # HIGHEST train conditional loss (the discriminator's best).
            better = tr["loss_cond"] > best["loss"]
            best = {
                "loss": jnp.where(better, tr["loss_cond"], best["loss"]),
                "sharpe": best["sharpe"],
                "params_loss": _select(better, params, best["params_loss"]),
                "params_sharpe": best["params_sharpe"],
                "updated_loss": best["updated_loss"] | better,
                "updated_sharpe": best["updated_sharpe"],
            }
            hist = {"train_loss": tr["loss"], "train_loss_cond": tr["loss_cond"]}
        return (params, opt_state, best), hist

    def run(params, opt_state, best_init, train_batch, valid_batch, test_batch,
            base_rng, start_epoch=0):
        # derived arrays for the active execution route (e.g. the Pallas
        # kernel's feature-major panel) — computed HERE, outside lax.scan,
        # so they cost one transpose per phase program, not one per epoch
        train_batch = gan.prepare_batch(train_batch)
        valid_batch = gan.prepare_batch(valid_batch)
        test_batch = gan.prepare_batch(test_batch)
        body = partial(
            epoch_body,
            train_batch=train_batch,
            valid_batch=valid_batch,
            test_batch=test_batch,
            base_rng=base_rng,
        )
        # `start_epoch` (0 for a whole-phase program) shifts the scanned
        # epoch indices so a SEGMENT of a phase sees the same absolute epoch
        # numbers — and therefore the same fold_in dropout streams and
        # ignore_epoch eligibility — as the uninterrupted whole-phase scan.
        # Mid-phase checkpoint/resume is bit-identical because of this.
        (params, opt_state, best), hist = jax.lax.scan(
            body, (params, opt_state, best_init),
            jnp.arange(num_epochs) + start_epoch,
        )
        return params, opt_state, best, hist

    return run


def build_sdf_switched_scan(
    gan: GAN,
    tx,
    num_epochs: int,
    ignore_epoch: int,
    has_test: bool = True,
    diag_stride: Optional[int] = None,
):
    """One scan program serving BOTH sdf phases (1 and 3):

        run(params, opt, best_init, train_b, valid_b, test_b, rng,
            start_epoch, use_cond) → (params, opt, best, history)

    `use_cond` is a traced boolean: False replays phase 1 (unconditional
    loss; best tracked on valid loss_unc), True replays phase 3
    (conditional loss; best on valid loss_cond). Epoch-for-epoch the math
    matches `build_phase_scan`'s dedicated programs to XLA-fusion ulps
    (tests/test_training.py::test_shared_sdf_program_matches_dedicated) —
    the point is ONE ~6-10 s XLA+Mosaic compile instead of two, with phases
    dispatched as `num_epochs`-sized segments through the traced
    `start_epoch` offset (same absolute epoch indices ⇒ same dropout
    streams and ignore_epoch eligibility as the whole-phase scans). Costs
    ~1.6 ms/epoch over the dedicated programs at the real shape — see
    Trainer.share_sdf_program for the trade.
    """
    from ..ops.diagnostics import make_diag_fn, strided_diagnostics
    from .steps import (
        make_eval_step as _mk_eval,
        make_sdf_switched_train_step as _mk_sw,
    )

    train_step = _mk_sw(gan, tx)
    eval_step = _mk_eval(gan)
    diag_fn = make_diag_fn(gan) if diag_stride else None
    n_moments = gan.cfg.num_condition_moment

    def epoch_body(carry, epoch, train_batch, valid_batch, test_batch,
                   base_rng, use_cond):
        params, opt_state, best = carry
        rng = jax.random.fold_in(base_rng, epoch)
        params, opt_state, tr = train_step(
            params, opt_state, train_batch, rng, use_cond)
        va = eval_step(params, valid_batch)
        te = eval_step(params, test_batch) if has_test else _zeros_like_metrics()
        va_loss = jnp.where(use_cond, va["loss_cond"], va["loss_unc"])
        te_loss = jnp.where(use_cond, te["loss_cond"], te["loss_unc"])
        eligible = epoch > ignore_epoch
        better_loss = eligible & (va_loss < best["loss"])
        better_sharpe = eligible & (va["sharpe"] > best["sharpe"])
        best = {
            "loss": jnp.where(better_loss, va_loss, best["loss"]),
            "sharpe": jnp.where(better_sharpe, va["sharpe"], best["sharpe"]),
            "params_loss": _select(better_loss, params, best["params_loss"]),
            "params_sharpe": _select(better_sharpe, params, best["params_sharpe"]),
            "updated_loss": best["updated_loss"] | better_loss,
            "updated_sharpe": best["updated_sharpe"] | better_sharpe,
        }
        hist = {
            "train_loss": tr["loss"],
            "train_sharpe": tr["sharpe"],
            "grad_norm": tr["grad_norm"],
            "valid_loss": va_loss,
            "valid_sharpe": va["sharpe"],
            "test_loss": te_loss,
            "test_sharpe": te["sharpe"],
        }
        if diag_fn is not None:
            diag = strided_diagnostics(
                diag_fn, params, valid_batch, epoch, diag_stride, n_moments)
            hist.update({f"diag_{k}": v for k, v in diag.items()})
        return (params, opt_state, best), hist

    def run(params, opt_state, best_init, train_batch, valid_batch, test_batch,
            base_rng, start_epoch, use_cond):
        train_batch = gan.prepare_batch(train_batch)
        valid_batch = gan.prepare_batch(valid_batch)
        test_batch = gan.prepare_batch(test_batch)
        body = partial(
            epoch_body,
            train_batch=train_batch,
            valid_batch=valid_batch,
            test_batch=test_batch,
            base_rng=base_rng,
            use_cond=use_cond,
        )
        (params, opt_state, best), hist = jax.lax.scan(
            body, (params, opt_state, best_init),
            jnp.arange(num_epochs) + start_epoch,
        )
        return params, opt_state, best, hist

    return run


def fresh_best(params: Params, for_moment: bool = False) -> Dict:
    """Initial best-tracking carry; params fields alias the entry params."""
    return {
        "loss": jnp.float32(-np.inf if for_moment else np.inf),
        "sharpe": jnp.float32(-np.inf),
        "params_loss": params,
        "params_sharpe": params,
        "updated_loss": jnp.array(False),
        "updated_sharpe": jnp.array(False),
    }


def carry_donate_argnums() -> tuple:
    """Donated argnums for the SEGMENTED/SWITCHED phase runners: the
    ``(opt state, best tracker)`` carry — arguments 1 and 2 of
    ``run(params, opt, best, *batches, rng, ...)``. A checkpoint-segmented
    or budget-truncated run re-dispatches its compiled scan once per
    segment; donation recycles the carry's device buffers into the
    outputs instead of round-tripping the full state through fresh
    allocations at every boundary (double-buffered carry).

    Params (arg 0) are NOT donated: callers alias the phase-1 best
    selection across later dispatches, and ``fresh_best`` aliases the
    entry params inside ``best`` — ``_run_phase`` breaks THAT alias with
    a one-time device copy before the first donated dispatch (donating a
    buffer also passed undonated is an XLA runtime error). Batches and
    the rng key are reused across segments, never donated.

    Resolved OFF on the CPU backend like every other donation site
    (``parallel.ensemble.phase_donate_argnums`` is the fleet-side twin —
    defined separately because ensemble imports this module). Tests force
    donation on by overriding ``Trainer.carry_donate``: CPU still runs
    the full deletion bookkeeping, so alias/rollback semantics are
    exercised without an accelerator.
    """
    return (1, 2) if jax.default_backend() != "cpu" else ()


class Trainer:
    """Compiles and runs the three phases; owns checkpoint/history IO."""

    def __init__(self, gan: GAN, tcfg: TrainConfig, has_test: bool = True,
                 share_sdf_program: bool = False,
                 events: Optional[EventLog] = None,
                 heartbeat: Optional[Heartbeat] = None,
                 divergence_guard: bool = True,
                 guard_max_trips: int = 3,
                 diag_stride: Optional[int] = None):
        self.gan = gan
        self.tcfg = tcfg
        self.has_test = has_test
        # model-health diagnostics: every diag_stride-th epoch the scanned
        # phase programs also emit the per-moment violation norms / SDF
        # stats / portfolio diagnostics as diag_* history fields
        # (ops/diagnostics.py). None (default) compiles the exact pre-
        # diagnostics programs; any value is observationally free — the
        # diagnostics read the carry, never feed it, so trained params and
        # best checkpoints are bit-identical either way (tier-1 asserts).
        self.diag_stride = (int(diag_stride)
                            if diag_stride and int(diag_stride) > 0 else None)
        # divergence guard (reliability/guard.py): after each segment
        # dispatch, check the segment's per-epoch loss/grad series for
        # non-finite values; on a trip roll back to the pre-segment carry and
        # retry; after `guard_max_trips` CONSECUTIVE trips abort with
        # DivergenceError instead of writing NaN checkpoints. The check reads
        # series the scan already produces — outputs are bit-identical with
        # the guard on or off.
        self.divergence_guard = divergence_guard
        self.guard_max_trips = guard_max_trips
        self.divergence_trips: list = []  # (phase_no, start_epoch, end_epoch)
        # telemetry sinks: `events` (observability.EventLog) records spans/
        # memory/log rows into events.jsonl; without one, a sinkless log
        # still times spans (compile_seconds/phase_seconds stay filled).
        # `heartbeat` writes the bench-compatible phase-tagged liveness file.
        self.events = events if events is not None else EventLog()
        self.hb = heartbeat
        # OPT-IN: compile ONE program for both sdf phases (1 and 3) when
        # their epoch counts nest (1024 = 4×256 on the paper schedule).
        # Measured trade at the real shape (v5e, 2026-07): saves one ~6-10 s
        # XLA+Mosaic compile + one executable upload, but the switched body
        # executes ~1.6 ms/epoch slower than the dedicated programs (+~2 s
        # per full schedule; XLA fuses the select-routed grads less well —
        # lax.cond is worse still, its region copies the panel operand).
        # Default False: steady-state execute is the metric that matters on
        # a warm service; flip on for compile-dominated one-shot cold runs.
        self.share_sdf_program = share_sdf_program
        # segment-boundary carry donation for the segmented/switched
        # runners (see carry_donate_argnums). Captured once: the lazy
        # runners and precompile's AOT programs must agree on aliasing or
        # the executable cache would hand a donated program to an
        # undonated dispatch (or vice versa).
        self.carry_donate: tuple = carry_donate_argnums()
        self.tx_sdf = make_optimizer(tcfg.lr, tcfg.grad_clip)
        self.tx_moment = make_optimizer(tcfg.lr, tcfg.grad_clip)
        self.eval_step = make_eval_step(gan)
        self._runners: Dict[str, Any] = {}
        # observability: per-program compile seconds and per-phase execute
        # seconds, the TPU replacement for the reference's time.time() scatter
        # (train.py:227-277); surfaced via timings() into final_metrics.json
        self.compile_seconds: Dict[str, float] = {}
        self.phase_seconds: Dict[str, float] = {}
        # XLA cost/memory analysis per AOT-compiled phase program
        # (observability/xla.py) — the train CLI folds this into
        # manifest.json (xla_programs) so a run dir carries its roofline
        # story; only precompiled (.lower().compile()) programs appear,
        # lazily-jitted fallbacks do not expose the analysis APIs
        self.program_analyses: Dict[str, Dict[str, Any]] = {}
        # True after a train() that exited early via stop_after_epochs —
        # callers must not treat the returned params as a best-model selection
        self.stopped_midphase = False

        # host-facing eval: jitted once, also returns the portfolio series
        # plus the paper's Table-1 risk-premium metrics (EV, XS-R²) computed
        # against the SDF factor — capability the reference's evaluate
        # (train.py:106-153) lacks entirely
        def _full_eval(params, batch):
            batch = self.gan.prepare_batch(batch)
            metrics = self.eval_step(params, batch)
            nw = self.gan.normalized_weights(params, batch)
            port = (nw * batch["returns"] * batch["mask"]).sum(axis=1)
            betas = factor_betas(batch["returns"], port, batch["mask"])
            metrics = dict(
                metrics,
                explained_variation=explained_variation(
                    batch["returns"], port, batch["mask"], betas),
                cross_sectional_r2=cross_sectional_r2(
                    batch["returns"], port, batch["mask"], betas),
            )
            return metrics, port

        self._jitted_full_eval = jax.jit(_full_eval)

    # -- one compiled phase --------------------------------------------------

    def _phase_runner(self, phase: str, num_epochs: int):
        """Build (and cache) the jitted scan over `num_epochs` epochs.

        NOTE: no buffer donation — best_init aliases the incoming params
        tree (params_loss/params_sharpe start as the entry params), and the
        trees are ~12k floats, so donation would be unsound and pointless.
        """
        cache_key = (phase, num_epochs)
        if cache_key not in self._runners:
            tx = self.tx_moment if phase == "moment" else self.tx_sdf
            self._runners[cache_key] = jax.jit(
                build_phase_scan(
                    self.gan, phase, tx, num_epochs,
                    self.tcfg.ignore_epoch, self.has_test,
                    diag_stride=self.diag_stride,
                )
            )
        return self._runners[cache_key]

    def _fresh_best(self, params: Params, for_moment: bool = False) -> Dict:
        return fresh_best(params, for_moment)

    def _beat(self, section: str, memory: bool = False) -> None:
        """Phase-tagged liveness (+ optional all-device memory snapshot) —
        the bench-parser-compatible heartbeat, when one is attached."""
        if self.hb is not None:
            self.hb.beat(section, memory=memory)
        elif memory and self.events.enabled:
            log_memory(self.events, section=section)

    def _switched_seg_len(self) -> Optional[int]:
        """Segment length of the shared sdf-phase program, or None when the
        schedule doesn't nest (then the dedicated per-phase programs run)."""
        if not self.share_sdf_program:
            return None
        u, c = self.tcfg.num_epochs_unc, self.tcfg.num_epochs
        if u > 0 and c > 0:
            if c % u == 0:
                return u
            if u % c == 0:
                return c
        return None

    def _sdf_switched_runner(self, seg_len: int):
        """The shared phase-1/3 segment program (traced epoch offset AND
        traced loss switch); one compile serves both phases."""
        cache_key = ("sdfsw", seg_len)
        if cache_key not in self._runners:
            self._runners[cache_key] = jax.jit(
                build_sdf_switched_scan(
                    self.gan, self.tx_sdf, seg_len,
                    self.tcfg.ignore_epoch, self.has_test,
                    diag_stride=self.diag_stride,
                ),
                donate_argnums=self.carry_donate,
            )
        return self._runners[cache_key]

    def _segment_runner(self, phase: str, seg_len: int):
        """Jitted scan over `seg_len` epochs STARTING at a traced epoch
        offset — the mid-phase unit of work. Segments see the same absolute
        epoch indices (dropout streams, ignore_epoch eligibility) as the
        whole-phase program, so a segmented run is bit-identical to an
        uninterrupted one. The offset is a traced scalar: every segment of
        one size shares one compiled program regardless of where it starts."""
        cache_key = ("seg", phase, seg_len)
        if cache_key not in self._runners:
            tx = self.tx_moment if phase == "moment" else self.tx_sdf
            self._runners[cache_key] = jax.jit(
                build_phase_scan(
                    self.gan, phase, tx, seg_len,
                    self.tcfg.ignore_epoch, self.has_test,
                    diag_stride=self.diag_stride,
                ),
                donate_argnums=self.carry_donate,
            )
        return self._runners[cache_key]

    def _run_phase(
        self,
        phase: str,
        total_epochs: int,
        params: Params,
        opt,
        best: Dict,
        batches,
        rng,
        start_epoch: int = 0,
        partial_hist: Optional[Dict] = None,
        checkpoint_every: Optional[int] = None,
        midphase_save=None,
        budget: Optional[list] = None,
    ):
        """Run epochs [start_epoch, total_epochs) of one phase, optionally in
        `checkpoint_every`-sized segments with `midphase_save(epochs_done,
        params, opt, best, hist_so_far)` called at each interior boundary.

        `budget`: one-element list of remaining train epochs for this
        invocation (stop_after_epochs), decremented in place; the phase stops
        at a segment boundary when it runs out.

        Returns (params, opt, best, full_phase_hist_or_None, epochs_done,
        stopped) — hist is the stacked host-side dict covering epochs
        [0, epochs_done), including any resumed partial prefix; None only if
        zero epochs have run in total.
        """
        section = PHASE_SECTIONS.get(phase, phase)
        self._beat(section)
        hists = [partial_hist] if partial_hist is not None else []
        e = start_epoch
        seg = checkpoint_every if (checkpoint_every and checkpoint_every > 0) else None
        stopped = False

        # Shared sdf-phase program: when share_sdf_program is on, EVERY
        # dispatch of phases 1 and 3 — plain, checkpoint-segmented, or
        # budget-truncated — runs the ONE switched scan body (traced epoch
        # offset + traced loss select). One program type everywhere keeps
        # segmented/resumed runs bit-identical to uninterrupted ones (the
        # switched body differs from the dedicated per-phase body by XLA
        # fusion at the last ulp, so mixing the two inside one training run
        # would break that guarantee). On the plain nested schedule (1024 =
        # 4×256) both phases share a single K-epoch program: one ~6-10 s
        # compile instead of two.
        # non-nesting schedules (K None) fall back to the dedicated programs
        # entirely — two switched compiles would pay the switched body's
        # execute cost without saving any compile
        K = (self._switched_seg_len()
             if (phase != "moment" and self.share_sdf_program) else None)
        switched = K is not None
        use_cond = jnp.bool_(phase == "conditional")

        guard_trips = 0
        # donation bookkeeping for the segmented/switched dispatches below:
        # once the loop owns the carry's buffers outright (post-dispatch
        # outputs, or the one-time alias-breaking copy), each donated
        # dispatch recycles them in place
        donating = bool(self.carry_donate)
        carry_owned = False
        # metrics-plane record of the donation resolution (active off-CPU,
        # off on the CPU backend) — bench/tests assert it without reaching
        # into trainer internals
        self.events.counter("trainer/carry_donation", phase=section,
                            active=donating,
                            argnums=list(self.carry_donate))
        while e < total_epochs:
            if budget is not None and budget[0] <= 0:
                stopped = True
                break
            k = total_epochs - e if seg is None else min(seg, total_epochs - e)
            if budget is not None:
                k = min(k, budget[0])
            if (seg is None and budget is None and K is not None
                    and (total_epochs - e) % K == 0):
                k = K  # nested schedule: dispatch the shared K-epoch program
            whole = (not switched and seg is None and e == 0
                     and k == total_epochs)
            if donating and not whole and not carry_owned:
                # break fresh_best's best↔params alias before the FIRST
                # donated dispatch: donating a buffer that is also passed
                # as the undonated params arg is an XLA runtime error.
                # One device-side copy per phase — the price of entering
                # the double-buffered regime
                best = jax.tree.map(jnp.copy, best)
                carry_owned = True
            # pre-segment carry refs: the divergence guard's rollback
            # point. Undonated dispatches keep the free immutable refs; a
            # donated dispatch deletes the carry's opt/best buffers, so
            # the rollback point must own device-side copies
            if donating and not whole and self.divergence_guard:
                prev_carry = (params, jax.tree.map(jnp.copy, opt),
                              jax.tree.map(jnp.copy, best))
            else:
                prev_carry = (params, opt, best)
            if switched:
                runner = self._sdf_switched_runner(k)
                params, opt, best, h = runner(
                    params, opt, best, *batches, rng, jnp.int32(e), use_cond
                )
                carry_owned = True
            elif whole:
                runner = self._phase_runner(phase, k)
                params, opt, best, h = runner(params, opt, best, *batches, rng)
                carry_owned = True
            else:
                runner = self._segment_runner(phase, k)
                params, opt, best, h = runner(
                    params, opt, best, *batches, rng, jnp.int32(e)
                )
                carry_owned = True
            # fault-injection site: nan_loss poisons this segment's outputs
            # (the divergence guard's exercise path); raise/kill/hang die here
            action = inject("trainer/epoch_loop", phase=section,
                            epochs_done=e + k)
            if action == "nan_loss":
                nan = jnp.float32(np.nan)
                params = jax.tree.map(
                    lambda x: x * nan
                    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                    else x,
                    params,
                )
                h = dict(h, train_loss=jnp.full_like(h["train_loss"], nan))
            if self.divergence_guard and segment_nonfinite(h):
                guard_trips += 1
                phase_no = PHASE_NUMBERS.get(phase, 0)
                self.divergence_trips.append((phase_no, e, e + k))
                self.events.counter("guard/trip", phase=section,
                                    start_epoch=e, end_epoch=e + k,
                                    consecutive=guard_trips)
                if guard_trips >= self.guard_max_trips:
                    self.events.log(
                        f"divergence guard: non-finite loss/grads in "
                        f"{section} epochs [{e}, {e + k}) persisted through "
                        f"{guard_trips} consecutive attempts; aborting",
                        level="error",
                    )
                    raise DivergenceError(
                        f"{section}: non-finite loss/grads in epochs "
                        f"[{e}, {e + k}) after {guard_trips} consecutive "
                        f"attempts — aborting instead of writing NaN "
                        f"checkpoints (last good state: epoch {e})"
                    )
                # roll back to the pre-segment carry and retry the segment
                params, opt, best = prev_carry
                continue
            guard_trips = 0
            # keep history as device handles; fetch in ONE batched
            # device_get only when the host actually needs it (each
            # per-segment fetch costs a ~0.4 s round trip on the
            # remote-attached tunnel — 4 K-sized segments would pay it 4×)
            hists.append(h)
            e += k
            self.events.counter("epochs_dispatched", value=k, phase=section,
                                epochs_done=e)
            # liveness at each segment DISPATCH boundary (dispatch is async:
            # the device may still be executing — same entry-stamped
            # semantics as bench.py's section heartbeats); memory snapshot
            # is a host-side counter read, never a device sync
            self._beat(section, memory=True)
            if budget is not None:
                budget[0] -= k
            if midphase_save is not None and e < total_epochs:
                hists = list(jax.device_get(hists))
                midphase_save(e, params, opt, best, _concat_hists(hists))
        if hists:
            hist = _concat_hists(jax.device_get(hists))
        else:
            # zero-epoch phase (or an immediate budget stop with no partial):
            # valid empty history, matching the whole-phase scan over arange(0)
            keys = (
                ("train_loss", "train_loss_cond") if phase == "moment"
                else ("train_loss", "train_sharpe", "grad_norm", "valid_loss",
                      "valid_sharpe", "test_loss", "test_sharpe")
            )
            hist = {k: np.zeros(0, np.float32) for k in keys}
            if self.diag_stride and phase != "moment":
                n_m = self.gan.cfg.num_condition_moment
                for k in self._diag_hist_keys():
                    hist[k] = (np.zeros((0, n_m), np.float32)
                               if k == "diag_moment_violations"
                               else np.zeros(0, np.float32))
        return params, opt, best, hist, e, stopped

    # -- concurrent AOT compilation of the three phase programs --------------

    def precompile(self, params, train_batch, valid_batch, test_batch,
                   completed_phase: int = 0,
                   checkpoint_every: Optional[int] = None,
                   in_phase: int = 0, epochs_in_phase: int = 0,
                   stop_after_epochs: Optional[int] = None):
        """Compile the needed phase programs CONCURRENTLY (XLA releases the
        GIL), so total compile wall-time ≈ the slowest single program instead
        of the sum. Stores the AOT executables in the runner cache; `train`
        then dispatches straight into them. `completed_phase` (resume) drops
        programs for phases that will not run; `in_phase`/`epochs_in_phase`
        (mid-phase resume) shrink that phase's program to the remaining
        epochs. With `checkpoint_every`, the segment programs (size K + any
        remainder) are compiled instead of the whole-phase ones.
        `stop_after_epochs` replays _run_phase's budget clamps so the exact
        (possibly truncated) segment lengths the run will dispatch are the
        ones compiled."""
        import concurrent.futures

        tcfg = self.tcfg
        opt_sdf = self.tx_sdf.init(params[trainable_key("unconditional")])
        opt_moment = self.tx_moment.init(params[trainable_key("moment")])
        best = self._fresh_best(params)
        best_m = self._fresh_best(params, for_moment=True)
        # must match train()'s key impl or the AOT executable won't be reused
        rng = train_base_key(0)

        jobs = []  # (phase, phase_no, total_epochs, opt, best)
        if completed_phase < 1:
            jobs.append(("unconditional", 1, tcfg.num_epochs_unc, opt_sdf, best))
        if completed_phase < 2 and tcfg.num_epochs_moment > 0:
            jobs.append(("moment", 2, tcfg.num_epochs_moment, opt_moment, best_m))
        jobs.append(("conditional", 3, tcfg.num_epochs, opt_sdf, best))

        budget = [stop_after_epochs] if stop_after_epochs is not None else None
        K = self._switched_seg_len()

        # _switched_seg_len() already folds share_sdf_program in (returns
        # None when off), so "this phase runs the switched program" is
        # exactly `phase != "moment" and K is not None` — ONE definition,
        # used by segment_sizes and the dispatch loop below, mirroring
        # _run_phase's gate (a non-nesting schedule runs the DEDICATED
        # programs even with share_sdf_program on; precompiling 'sdfsw'
        # there would build programs that never run and lazily pay the
        # dedicated compiles inside the timed phase)
        def runs_switched(phase):
            return phase != "moment" and K is not None

        def segment_sizes(phase, phase_no, n):
            """The exact segment lengths _run_phase will dispatch, given the
            resume offset, checkpointing cadence, epoch budget, and (for sdf
            phases) the shared-program K override (budget clamps mirror
            _run_phase and carry across phases in order)."""
            switched = runs_switched(phase)
            start = epochs_in_phase if in_phase == phase_no else 0
            seg = checkpoint_every if (checkpoint_every and checkpoint_every > 0) else None
            sizes, e = [], start
            while e < n:
                if budget is not None and budget[0] <= 0:
                    break
                k = n - e if seg is None else min(seg, n - e)
                if budget is not None:
                    k = min(k, budget[0])
                if (seg is None and budget is None and switched
                        and (n - e) % K == 0):
                    k = K
                if budget is not None:
                    budget[0] -= k
                # full-phase program iff untruncated whole phase from epoch 0
                sizes.append((k, not (seg is None and e == 0 and k == n)))
                e += k
            return [(k, s) for k, s in dict.fromkeys(sizes)]

        sdf_lens: Dict[int, None] = {}  # ordered distinct switched seg lens
        expanded = []
        for phase, phase_no, n, opt, b in jobs:
            for seg, is_seg in segment_sizes(phase, phase_no, n):
                if runs_switched(phase):
                    sdf_lens.setdefault(seg)
                else:
                    expanded.append((phase, seg, opt, b, is_seg))
        jobs = [
            j for j in expanded
            if (("seg", j[0], j[1]) if j[4] else (j[0], j[1])) not in self._runners
        ]
        switched_jobs = [
            n for n in sdf_lens if ("sdfsw", n) not in self._runners
        ]
        if not jobs and not switched_jobs:
            return

        def compile_one(phase, n, opt, b, seg):
            tx = self.tx_moment if phase == "moment" else self.tx_sdf
            # segment programs donate the (opt, best) carry exactly like
            # the lazy _segment_runner — the AOT executable and the lazy
            # jit share one cache, so their aliasing must match; the
            # whole-phase program stays undonated (_phase_runner contract)
            fn = jax.jit(build_phase_scan(
                self.gan, phase, tx, n, tcfg.ignore_epoch, self.has_test,
                diag_stride=self.diag_stride),
                donate_argnums=self.carry_donate if seg else ())
            args = (params, opt, b, train_batch, valid_batch, test_batch, rng)
            if seg:
                args = args + (jnp.int32(0),)
            key = f"phase_{phase}" + (f"_seg{n}" if seg else "")
            with self.events.span(f"compile/{key}", epochs=n) as sp:
                compiled = fn.lower(*args).compile()
            self.compile_seconds[key] = round(sp.seconds, 3)
            record_program(self.events, key, compiled,
                           analyses_out=self.program_analyses,
                           program=key, phase=phase, epochs=n)
            return (("seg", phase, n) if seg else (phase, n)), compiled

        def compile_switched(n):
            fn = jax.jit(build_sdf_switched_scan(
                self.gan, self.tx_sdf, n, tcfg.ignore_epoch, self.has_test,
                diag_stride=self.diag_stride),
                donate_argnums=self.carry_donate)
            args = (params, opt_sdf, best, train_batch, valid_batch,
                    test_batch, rng, jnp.int32(0), jnp.bool_(True))
            key = f"sdf_switched_seg{n}"
            with self.events.span(f"compile/{key}", epochs=n) as sp:
                compiled = fn.lower(*args).compile()
            self.compile_seconds[key] = round(sp.seconds, 3)
            record_program(self.events, key, compiled,
                           analyses_out=self.program_analyses,
                           program=key, epochs=n)
            return ("sdfsw", n), compiled

        tasks = [partial(compile_one, *j) for j in jobs]
        tasks += [partial(compile_switched, n) for n in switched_jobs]
        with concurrent.futures.ThreadPoolExecutor(len(tasks)) as ex:
            for key, compiled in ex.map(lambda f: f(), tasks):
                self._runners[key] = compiled

    # -- the full 3-phase schedule ------------------------------------------

    def train(
        self,
        params: Params,
        train_batch: Batch,
        valid_batch: Batch,
        test_batch: Optional[Batch] = None,
        save_dir: Optional[str] = None,
        verbose: bool = True,
        seed: Optional[int] = None,
        precompile: bool = True,
        resume: bool = False,
        stop_after_phase: Optional[int] = None,
        checkpoint_every: Optional[int] = None,
        stop_after_epochs: Optional[int] = None,
    ):
        """Run phases 1-3. Returns (final_params, history dict of np arrays).

        `resume=True` (requires save_dir): continue from the last recorded
        resume point in save_dir — a phase boundary, or a mid-phase segment
        boundary when `checkpoint_every` was set — carrying params, both
        Adam states, the best trackers, and the history so far. A resumed
        run is bit-identical to an uninterrupted one: segments scan the same
        absolute epoch indices, so dropout streams and best-selection
        eligibility are unchanged.

        `checkpoint_every` (requires save_dir): run each phase in segments
        of this many epochs, persisting a resumable state after every
        segment — epoch-granular fault tolerance. Costs one extra compile
        per distinct segment length and a ~12k-param host write per segment.

        `stop_after_epochs`: run at most this many more train epochs in THIS
        invocation (checked at segment boundaries), persist the mid-phase
        state, and return the running params — time-budgeted training.

        `stop_after_phase` ends the run after that phase's boundary
        checkpoint (used by tests/orchestration to simulate interruption).
        """
        tcfg = self.tcfg
        seed = tcfg.seed if seed is None else seed
        if stop_after_epochs is not None and not save_dir:
            raise ValueError(
                "stop_after_epochs requires save_dir — without it the "
                "mid-phase state cannot be persisted and the partial "
                "training would be unrecoverable"
            )
        if stop_after_epochs is not None and stop_after_epochs <= 0:
            # a zero budget on a fresh run would stop before phase 1 writes
            # any resume state, and the 'resumable state saved' exit message
            # would point at nothing — refuse instead of lying
            raise ValueError(
                f"stop_after_epochs must be positive, got {stop_after_epochs}"
            )
        self.stopped_midphase = False
        self.divergence_trips = []
        rng = train_base_key(seed)
        r1, r2, r3 = jax.random.split(rng, 3)
        if test_batch is None:
            test_batch = valid_batch  # placeholder; has_test=False skips it
        t0 = time.time()

        sdf_key = trainable_key("unconditional")
        opt_sdf = self.tx_sdf.init(params[sdf_key])
        opt_moment = self.tx_moment.init(params[trainable_key("moment")])

        history: Dict[str, list] = {
            "train_loss": [], "train_sharpe": [],
            "valid_loss": [], "valid_sharpe": [],
            "test_loss": [], "test_sharpe": [],
            "grad_norm": [], "phase": [],
        }
        for k in self._diag_hist_keys():
            history[k] = []

        def log(msg):
            # every progress line also lands in events.jsonl (when a sink is
            # attached), so a quiet or crashed run is still reconstructable
            self.events.log(msg)
            if verbose:
                print(msg, flush=True)

        completed_phase = 0
        in_phase, epochs_in_phase = 0, 0
        best_phase_loaded, partial_hist = None, None
        best1 = None
        resumed = False
        if resume:
            if not save_dir:
                raise ValueError("resume=True requires save_dir")
            loaded = self._load_resume(
                Path(save_dir), params, opt_sdf, opt_moment, seed
            )
            if loaded is not None:
                (completed_phase, params, opt_sdf, opt_moment, best1, history,
                 in_phase, epochs_in_phase, best_phase_loaded, partial_hist) = loaded
                resumed = True
                where = (f"mid-phase {in_phase} at epoch {epochs_in_phase}"
                         if in_phase else f"after phase {completed_phase}")
                log(f"Resuming {where} "
                    f"({len(history['train_loss'])} epochs of completed history)")
        budget = [stop_after_epochs] if stop_after_epochs is not None else None
        batches = (train_batch, valid_batch, test_batch)

        if precompile:
            t_c = time.time()
            self.precompile(params, train_batch, valid_batch, test_batch,
                            completed_phase=completed_phase,
                            checkpoint_every=checkpoint_every if save_dir else None,
                            in_phase=in_phase, epochs_in_phase=epochs_in_phase,
                            stop_after_epochs=stop_after_epochs)
            log(f"compiled phase programs concurrently in {time.time()-t_c:.1f}s")

        if save_dir and not resumed:
            # fresh run: truncate any stale structured log so re-runs into the
            # same dir don't double-count epochs (resume keeps prior rows)
            open(Path(save_dir) / "metrics.jsonl", "w").close()

        def midphase_saver(phase_no, for_moment=False):
            """Persist a resumable mid-phase state (requires save_dir). For
            phase 1 the running tracker IS best1; phases 2/3 keep the final
            phase-1 tracker alongside their own."""
            if not save_dir:
                return None

            def save(e, p, opt, b, hist_so_far):
                self._save_resume(
                    Path(save_dir), phase_no - 1, p,
                    opt if phase_no != 2 else opt_sdf,
                    opt if phase_no == 2 else opt_moment,
                    b if phase_no == 1 else best1,
                    history, seed,
                    in_phase=phase_no, epochs_in_phase=e,
                    best_phase=b, partial_hist=hist_so_far,
                )

            return save

        def stopped_return(phase_no, e_done):
            self.stopped_midphase = True
            log(f"Stopping mid-phase {phase_no} at epoch {e_done} "
                f"(stop_after_epochs); resumable state saved — the returned "
                f"params are the RUNNING state, not a best-model selection")
            return params, {k: np.asarray(v) for k, v in history.items()}

        # ---- Phase 1: sdf on unconditional loss ----
        if completed_phase < 1:
            start1 = epochs_in_phase if in_phase == 1 else 0
            log(f"PHASE 1 (unconditional): {tcfg.num_epochs_unc} epochs"
                + (f" (resuming at {start1})" if start1 else ""))
            best1_init = (best_phase_loaded if in_phase == 1
                          else self._fresh_best(params))
            with self.events.span("phase/phase1_unconditional",
                                  epochs=tcfg.num_epochs_unc,
                                  start_epoch=start1) as sp1:
                params, opt_sdf, best1, h1, e_done, stopped = self._run_phase(
                    "unconditional", tcfg.num_epochs_unc, params, opt_sdf,
                    best1_init, batches, r1, start_epoch=start1,
                    partial_hist=partial_hist if in_phase == 1 else None,
                    checkpoint_every=checkpoint_every if save_dir else None,
                    midphase_save=midphase_saver(1), budget=budget,
                )
            if stopped:
                return stopped_return(1, e_done)
            self._append_history(history, h1, "unc")
            self.phase_seconds["phase1_unconditional"] = round(sp1.seconds, 3)
            if save_dir:
                self._write_jsonl(Path(save_dir), self._jsonl_rows(h1, "unc"))
            self._print_phase_history(log, h1, tcfg.num_epochs_unc, tcfg.print_freq, 1)
            # reload best-by-sharpe (train.py:289-292); keep running params if
            # the phase never updated (epochs ≤ ignore_epoch)
            params_after1 = _select(best1["updated_sharpe"], best1["params_sharpe"], params)
            params = params_after1
            if save_dir:
                # Save-on-update-only: the reference writes each best_model
                # file only when its tracker improves (train.py:266, 272); a
                # phase that never updates leaves the file absent / untouched.
                if bool(best1["updated_loss"]):
                    save_params(Path(save_dir) / "best_model_loss.msgpack",
                                best1["params_loss"])
                if bool(best1["updated_sharpe"]):
                    save_params(Path(save_dir) / "best_model_sharpe.msgpack", params_after1)
                self._save_resume(
                    Path(save_dir), 1, params, opt_sdf, opt_moment, best1,
                    history, seed,
                )
            inject("trainer/phase_boundary", phase=1)
            log(f"Phase 1 done in {time.time()-t0:.1f}s; "
                f"best valid sharpe {float(best1['sharpe']):.4f}")
        if stop_after_phase == 1:
            log("Stopping after phase 1 (stop_after_phase)")
            return params, {k: np.asarray(v) for k, v in history.items()}

        # ---- Phase 2: moment net maximizes conditional loss ----
        if completed_phase < 2 and tcfg.num_epochs_moment > 0:
            start2 = epochs_in_phase if in_phase == 2 else 0
            log(f"PHASE 2 (moment update): {tcfg.num_epochs_moment} epochs"
                + (f" (resuming at {start2})" if start2 else ""))
            best2_init = (best_phase_loaded if in_phase == 2
                          else self._fresh_best(params, for_moment=True))
            with self.events.span("phase/phase2_moment",
                                  epochs=tcfg.num_epochs_moment,
                                  start_epoch=start2) as sp2:
                params, opt_moment, best2, h2, e_done, stopped = self._run_phase(
                    "moment", tcfg.num_epochs_moment, params, opt_moment,
                    best2_init, batches, r2, start_epoch=start2,
                    partial_hist=partial_hist if in_phase == 2 else None,
                    checkpoint_every=checkpoint_every if save_dir else None,
                    midphase_save=midphase_saver(2), budget=budget,
                )
            if stopped:
                return stopped_return(2, e_done)
            self.phase_seconds["phase2_moment"] = round(sp2.seconds, 3)
            if save_dir:
                self._write_jsonl(Path(save_dir), self._jsonl_rows(h2, "moment"))
            if save_dir and bool(best2["updated_loss"]):
                save_params(Path(save_dir) / "best_model_loss.msgpack",
                            best2["params_loss"])
            if save_dir:
                self._save_resume(
                    Path(save_dir), 2, params, opt_sdf, opt_moment, best1,
                    history, seed,
                )
            inject("trainer/phase_boundary", phase=2)
            log(f"Phase 2 done; best train cond loss {float(best2['loss']):.6f}")
            # Phase 3 continues from LAST-epoch moment params (no reload).
        if stop_after_phase == 2:
            log("Stopping after phase 2 (stop_after_phase)")
            return params, {k: np.asarray(v) for k, v in history.items()}

        # ---- Phase 3: sdf on conditional loss ----
        start3 = epochs_in_phase if in_phase == 3 else 0
        log(f"PHASE 3 (conditional): {tcfg.num_epochs} epochs"
            + (f" (resuming at {start3})" if start3 else ""))
        best3_init = (best_phase_loaded if in_phase == 3
                      else self._fresh_best(params))
        with self.events.span("phase/phase3_conditional",
                              epochs=tcfg.num_epochs,
                              start_epoch=start3) as sp3:
            params, opt_sdf, best3, h3, e_done, stopped = self._run_phase(
                "conditional", tcfg.num_epochs, params, opt_sdf,
                best3_init, batches, r3, start_epoch=start3,
                partial_hist=partial_hist if in_phase == 3 else None,
                checkpoint_every=checkpoint_every if save_dir else None,
                midphase_save=midphase_saver(3), budget=budget,
            )
        if stopped:
            return stopped_return(3, e_done)
        self._append_history(history, h3, "cond")
        self.phase_seconds["phase3_conditional"] = round(sp3.seconds, 3)
        if save_dir:
            self._write_jsonl(Path(save_dir), self._jsonl_rows(h3, "cond"))
        self._print_phase_history(log, h3, tcfg.num_epochs, tcfg.print_freq, 3)
        # Final reload chain (train.py:398-400): the persistent best_model_state
        # is phase-3's best-by-sharpe if it updated, else phase-1's (captured
        # BEFORE phase 2 touched the moment net), else the running params.
        final_params = _select(
            best3["updated_sharpe"],
            best3["params_sharpe"],
            _select(best1["updated_sharpe"], best1["params_sharpe"], params),
        )

        if save_dir:
            save_dir = Path(save_dir)
            save_dir.mkdir(parents=True, exist_ok=True)
            if bool(best3["updated_loss"]):
                save_params(save_dir / "best_model_loss.msgpack", best3["params_loss"])
            if bool(best3["updated_sharpe"]):
                save_params(save_dir / "best_model_sharpe.msgpack", final_params)
            save_params(save_dir / "final_model.msgpack", final_params)
            self._save_history(save_dir, history)
            self._write_health(save_dir, final_params, valid_batch, history)
            # boundary fault site BEFORE the resume state clears: a kill here
            # restarts with --resume from the phase-2 boundary and re-writes
            # identical final artifacts
            inject("trainer/phase_boundary", phase=3)
            self._clear_resume(save_dir)
        else:
            inject("trainer/phase_boundary", phase=3)
        # final boundary: liveness + the run's closing memory high-water mark
        self._beat("finalize", memory=True)
        log(f"Training complete in {time.time()-t0:.1f}s "
            f"({tcfg.num_epochs_unc}+{tcfg.num_epochs_moment}+{tcfg.num_epochs} epochs)")
        return final_params, {k: np.asarray(v) for k, v in history.items()}

    def _print_phase_history(self, log, hist, num_epochs, print_freq, phase_no):
        """Reference-style periodic epoch lines (train.py:275-282), printed
        from the device-collected history after the compiled scan returns —
        same cadence, zero in-loop host syncs."""
        if num_epochs == 0:
            return
        tl = np.asarray(hist["train_loss"])
        ts = np.asarray(hist["train_sharpe"])
        vl = np.asarray(hist["valid_loss"])
        vs = np.asarray(hist["valid_sharpe"])
        tes = np.asarray(hist["test_sharpe"])
        for e in range(num_epochs):
            if e == 0 or (e + 1) % print_freq == 0:
                log(
                    f"  [P{phase_no}] epoch {e+1:4d}/{num_epochs} | "
                    f"train loss={tl[e]:.4f} sharpe={ts[e]:.2f} | "
                    f"valid loss={vl[e]:.4f} sharpe={vs[e]:.2f} | "
                    f"test sharpe={tes[e]:.2f}"
                )

    # -- observability --------------------------------------------------------

    @staticmethod
    def _write_jsonl(save_dir: Path, rows: list) -> None:
        """Append rows phase-by-phase so a crash mid-run keeps everything
        logged so far (and a resumed run appends only its own phases)."""
        with open(save_dir / "metrics.jsonl", "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")

    def _jsonl_rows(self, hist_stacked, phase_label) -> list:
        """Per-epoch structured-log rows from a phase's stacked history.
        Rows carry the run_id so report tooling can scope an appended-to
        metrics.jsonl (resume / re-run) to the latest run's rows. Only
        scalar series land in rows (the [K]-vector diag_moment_violations
        rides history.npz; its max is already a scalar field)."""
        arrs = hist_stacked  # already host numpy (fetched per segment in _run_phase)
        n = arrs[next(iter(arrs))].shape[0]
        return [
            {"phase": phase_label, "epoch": int(e),
             "run_id": self.events.run_id,
             **{k: float(v[e]) for k, v in arrs.items()
                if np.asarray(v).ndim == 1}}
            for e in range(n)
        ]

    @staticmethod
    def device_memory_stats() -> Dict[str, int]:
        """Live device memory counters (bytes) AGGREGATED over all local
        devices: count-like stats sum, ``peak_*``/``*_limit`` stats take the
        per-device max (observability.memory). Reading only device 0 — the
        old behavior — under-reports a multi-chip host by the device count
        and misses the one chip about to OOM."""
        return device_memory_snapshot()["totals"]

    def timings(self) -> Dict[str, Any]:
        """Compile/execute wall-clock per phase program + device memory —
        written into final_metrics.json by the CLI (SURVEY §5 tracing).
        ``device_memory`` carries the aggregated totals AND the per-device
        breakdown (``{"n_devices", "totals", "per_device"}``)."""
        return {
            "compile_seconds": dict(self.compile_seconds),
            "phase_execute_seconds": dict(self.phase_seconds),
            "device_memory": device_memory_snapshot(),
        }

    # -- phase-boundary resume state -----------------------------------------

    _HISTORY_KEYS = ("train_loss", "train_sharpe", "valid_loss", "valid_sharpe",
                     "test_loss", "test_sharpe", "grad_norm")

    def _diag_hist_keys(self) -> tuple:
        """The diag_* history fields this trainer's scans emit (empty when
        diagnostics are off) — one per scalar in
        :data:`ops.diagnostics.SCALAR_KEYS` plus the [K]-vector
        per-moment violations."""
        if not self.diag_stride:
            return ()
        from ..ops.diagnostics import SCALAR_KEYS

        return tuple(f"diag_{k}" for k in SCALAR_KEYS) + (
            "diag_moment_violations",)

    def _history_state_keys(self) -> tuple:
        return self._HISTORY_KEYS + self._diag_hist_keys()

    def _save_resume(self, save_dir: Path, completed_phase: int, params,
                     opt_sdf, opt_moment, best1, history, seed: int,
                     in_phase: int = 0, epochs_in_phase: int = 0,
                     best_phase: Optional[Dict] = None,
                     partial_hist: Optional[Dict] = None) -> None:
        """Checkpoint everything a later process needs to continue from this
        point (the reference's train_3phase has no continue path at all — a
        crash restarts from scratch; SURVEY §5). Two flavors:
          * phase boundary (in_phase=0): params, both Adam states, the
            phase-1 best tracker, completed history;
          * mid-phase segment boundary (in_phase=1..3): additionally the
            running phase's best tracker and its partial stacked history
            covering epochs [0, epochs_in_phase)."""
        state = {
            "params": params,
            "opt_sdf": opt_sdf,
            "opt_moment": opt_moment,
            "best1": best1,
            "history": {
                k: np.asarray(history[k], np.float32)
                for k in self._history_state_keys()
            },
        }
        if in_phase:
            state["best_phase"] = best_phase
            state["partial_hist"] = {
                k: np.asarray(v, np.float32) for k, v in partial_hist.items()
            }
        import dataclasses

        from flax import serialization

        # verified generational pair: the state's sha256 is embedded in the
        # meta, binding the two files — a kill between the two writes leaves
        # an unmatched pair that _load_resume skips in favor of the previous
        # (.g1) generation, so a mid-save death can never strand the run
        data = serialization.to_bytes(jax.device_get(state))
        state_sha = verified.write_verified(
            save_dir / "resume_state.msgpack", data)
        meta = {
            "completed_phase": completed_phase,
            "seed": int(seed),
            "tcfg": dataclasses.asdict(self.tcfg),
            "gan_config": self.gan.cfg.to_dict(),
            "history_phases": list(history["phase"]),
            "in_phase": int(in_phase),
            "epochs_in_phase": int(epochs_in_phase),
            "partial_hist_keys": sorted(partial_hist) if in_phase else [],
            # the switched and dedicated sdf bodies differ at the last ulp,
            # so a continuation is only bit-identical on the SAME route
            "share_sdf_program": bool(self.share_sdf_program),
            # diag fields change the history schema (and the compiled scan
            # bodies), so a continuation must keep the same setting
            "diag_stride": self.diag_stride,
            "state_sha256": state_sha,
        }
        verified.write_verified(
            save_dir / "resume_meta.json",
            json.dumps(meta).encode("utf-8"),
        )

    def _save_history(self, save_dir: Path, history) -> None:
        """history.npz, written atomically (tmp + os.replace); divergence-
        guard trips ride along as a [n, 3] (phase_no, start_epoch,
        end_epoch) array when any occurred."""
        arrays = {k: np.asarray(v) for k, v in history.items()}
        if self.divergence_trips:
            arrays["divergence_trips"] = np.asarray(
                self.divergence_trips, np.float32)
        tmp = save_dir / "history.npz.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, save_dir / "history.npz")

    def _write_health(self, save_dir: Path, final_params, valid_batch,
                      history) -> None:
        """The verified ``health.json`` summary every completed run dir
        carries (observability/modelhealth.py): final-model diagnostics on
        the valid batch, divergence-guard trip count, and the last
        in-training diag readings. Telemetry must never fail a training
        run that already wrote its checkpoints — failures log and move
        on."""
        try:
            from ..observability.modelhealth import compute_health, write_health

            health = compute_health(
                self.gan, final_params, valid_batch, history=history,
                guard_trips=self.divergence_trips,
                diag_stride=self.diag_stride)
            write_health(save_dir, health)
            self.events.counter(
                "health/written",
                finite=health["finite"],
                moment_violation_max=health["diagnostics"].get(
                    "moment_violation_max"),
                guard_trips=health["guard_trips"])
        except Exception as e:  # noqa: BLE001 — observability, not training
            self.events.log(
                f"health.json write failed ({type(e).__name__}: {e}); "
                "run artifacts are unaffected", level="warning")

    def _clear_resume(self, save_dir: Path) -> None:
        """A finished run leaves nothing to resume (all generations)."""
        verified.clear_generations(save_dir / "resume_state.msgpack")
        verified.clear_generations(save_dir / "resume_meta.json")

    def _load_resume(self, save_dir: Path, params_template, opt_sdf_template,
                     opt_moment_template, seed: int):
        """Returns (completed_phase, params, opt_sdf, opt_moment, best1,
        history, in_phase, epochs_in_phase, best_phase, partial_hist) or
        None when no resume state exists. in_phase=0 means a phase-boundary
        state (best_phase/partial_hist are None).

        Loads through the verified generational path: the newest
        (meta, state) pair whose digests verify AND whose state bytes match
        the meta's recorded ``state_sha256`` wins; a corrupt or torn newest
        pair falls back to the previous (.g1) generation. When every
        generation is unusable, warns and returns None — restarting from
        scratch is the recovery of last resort, and it still converges to
        the identical final artifacts."""
        import warnings

        from flax import serialization

        meta_path = save_dir / "resume_meta.json"
        state_path = save_dir / "resume_state.msgpack"
        meta_gens = [p for p in verified.generation_candidates(meta_path)
                     if p.exists()]
        if not meta_gens:
            return None
        errors = []
        meta, state_data, used_fallback = None, None, False
        for mp in meta_gens:
            raw = mp.read_bytes()
            ok, why = verified.check_digest(mp, raw)
            if not ok:
                errors.append(f"{mp.name}: {why}")
                continue
            try:
                candidate = json.loads(raw.decode("utf-8"))
            except (UnicodeDecodeError, ValueError) as e:
                errors.append(f"{mp.name}: {e}")
                continue
            want = candidate.get("state_sha256")
            for sp in verified.generation_candidates(state_path):
                if not sp.exists():
                    continue
                data = sp.read_bytes()
                ok, why = verified.check_digest(sp, data)
                if not ok:
                    errors.append(f"{sp.name}: {why}")
                    continue
                if (want is not None
                        and hashlib.sha256(data).hexdigest() != want):
                    errors.append(
                        f"{sp.name}: does not pair with {mp.name} "
                        "(state_sha256 mismatch)")
                    continue
                meta, state_data = candidate, data
                used_fallback = (mp != meta_path or sp != state_path)
                break
            if meta is not None:
                break
        if meta is None:
            warnings.warn(
                f"resume state in {save_dir} unusable "
                f"({'; '.join(errors) or 'no state file'}); starting from "
                "scratch — the rerun converges to identical final artifacts",
                stacklevel=2,
            )
            self.events.counter("checkpoint/unusable",
                                path=str(state_path), errors=len(errors))
            return None
        if used_fallback:
            self.events.counter("checkpoint/fallback", path=str(state_path),
                                errors="; ".join(errors))
        import dataclasses
        # the continuation is only bit-identical if EVERY hyperparameter
        # matches — schedule, lr, grad_clip, ignore_epoch, model config, seed
        current_tcfg = dataclasses.asdict(self.tcfg)
        for field, saved in meta["tcfg"].items():
            if current_tcfg.get(field) != saved:
                raise ValueError(
                    f"resume state tcfg.{field}={saved} does not match the "
                    f"current value {current_tcfg.get(field)}"
                )
        if meta["gan_config"] != self.gan.cfg.to_dict():
            raise ValueError(
                "resume state model config does not match the current GANConfig"
            )
        if meta["seed"] != int(seed):
            raise ValueError(
                f"resume state seed={meta['seed']} != requested seed {seed}"
            )
        saved_route = bool(meta.get("share_sdf_program", False))
        if saved_route != bool(self.share_sdf_program):
            raise ValueError(
                f"resume state was written with share_sdf_program="
                f"{saved_route}; resuming with {self.share_sdf_program} "
                "would mix program bodies that differ at the last ulp — "
                "pass the same setting to keep the continuation bit-identical"
            )
        saved_diag = meta.get("diag_stride")
        if saved_diag != self.diag_stride:
            raise ValueError(
                f"resume state was written with diag_stride={saved_diag}; "
                f"resuming with {self.diag_stride} would change the history "
                "schema mid-run — pass the same setting"
            )
        in_phase = int(meta.get("in_phase", 0))
        template = {
            "params": params_template,
            "opt_sdf": opt_sdf_template,
            "opt_moment": opt_moment_template,
            "best1": self._fresh_best(params_template),
            "history": {
                k: np.zeros(0, np.float32)
                for k in self._history_state_keys()
            },
        }
        if in_phase:
            template["best_phase"] = self._fresh_best(
                params_template, for_moment=(in_phase == 2)
            )
            template["partial_hist"] = {
                k: np.zeros(0, np.float32) for k in meta["partial_hist_keys"]
            }
        try:
            state = serialization.from_bytes(template, state_data)
        except Exception as e:  # noqa: BLE001 — any deserialization failure
            raise ValueError(
                f"corrupt or truncated resume state msgpack in {save_dir} "
                f"(digest verified but deserialization failed): "
                f"{type(e).__name__}: {e}"
            ) from e
        history = {k: list(np.asarray(v)) for k, v in state["history"].items()}
        history["phase"] = list(meta["history_phases"])
        return (
            int(meta["completed_phase"]),
            state["params"],
            state["opt_sdf"],
            state["opt_moment"],
            state["best1"],
            history,
            in_phase,
            int(meta.get("epochs_in_phase", 0)),
            state.get("best_phase"),
            state.get("partial_hist"),
        )

    def _append_history(self, history, hist_stacked, phase_label):
        arrs = hist_stacked  # already host numpy (fetched per segment in _run_phase)
        n = int(np.asarray(arrs["train_loss"]).shape[0])
        for k in self._history_state_keys():
            if k in arrs:
                history[k].extend(np.asarray(arrs[k]).tolist())
        history["phase"].extend([phase_label] * n)

    # -- final evaluation (host-side, includes drawdown) ---------------------

    def final_eval(self, params: Params, batch: Batch) -> Dict[str, float]:
        metrics, port = self._jitted_full_eval(params, batch)
        m = {k: float(v) for k, v in metrics.items()}
        port = np.asarray(port)
        m["max_drawdown"] = max_drawdown(port)
        # numpy (ddof=0) flavors for parity with reference's final report
        m["mean_return"] = float(port.mean())
        m["std_return"] = float(port.std())
        return m


def train_3phase(
    config: GANConfig,
    train_batch: Batch,
    valid_batch: Batch,
    test_batch: Optional[Batch] = None,
    tcfg: Optional[TrainConfig] = None,
    save_dir: Optional[str] = None,
    seed: Optional[int] = None,
    verbose: bool = True,
    resume: bool = False,
    stop_after_phase: Optional[int] = None,
    exec_cfg=None,
    checkpoint_every: Optional[int] = None,
    stop_after_epochs: Optional[int] = None,
    share_sdf_program: bool = False,
    events: Optional[EventLog] = None,
    heartbeat: Optional[Heartbeat] = None,
    trainer: Optional[Trainer] = None,
    divergence_guard: bool = True,
    guard_max_trips: int = 3,
    diag_stride: Optional[int] = None,
):
    """Functional front door mirroring the reference's ``train_3phase``.

    Returns (gan, final_params, history, trainer) — keep the trainer for
    `final_eval` so its compiled eval steps are reused.

    `share_sdf_program`: compile one shared program for phases 1 and 3
    (see Trainer.share_sdf_program for the compile-vs-execute trade; meant
    for one-shot cold runs where compile weather dominates).

    `events` / `heartbeat`: observability sinks (events.jsonl writer and the
    bench-compatible liveness file) — created by the CLIs, optional here.

    `divergence_guard` / `guard_max_trips`: the non-finite segment check
    (reliability/guard.py) — on by default; outputs are bit-identical with
    it on or off.

    `trainer`: a pre-built Trainer — e.g. from the startup pipeline's
    early-compile stage (data.pipeline.trainer_precompile_fn) — whose
    AOT-compiled phase programs in `_runners` are dispatched directly
    (Trainer.precompile is idempotent, so the in-train precompile pass only
    fills whatever the early compile did not cover, such as resume-shrunk
    segment programs). Its own gan/events/heartbeat are used; this
    function's `exec_cfg`/`share_sdf_program`/`events`/`heartbeat` arguments
    are ignored in that case, and its config must equal `config`.
    """
    tcfg = tcfg or TrainConfig()
    seed = tcfg.seed if seed is None else seed
    if trainer is not None:
        if trainer.gan.cfg != config:
            raise ValueError(
                "precompiled trainer was built for a different GANConfig "
                "than the one passed to train_3phase"
            )
        if trainer.tcfg != tcfg:
            raise ValueError(
                "precompiled trainer was built for a different TrainConfig "
                "(its phase programs are sized to that schedule)"
            )
        gan = trainer.gan
    else:
        gan = GAN(config, exec_cfg)
    params = gan.init(jax.random.key(seed))
    if save_dir:
        Path(save_dir).mkdir(parents=True, exist_ok=True)
        config.save(Path(save_dir) / "config.json")
    if trainer is None:
        trainer = Trainer(gan, tcfg, has_test=test_batch is not None,
                          share_sdf_program=share_sdf_program,
                          events=events, heartbeat=heartbeat,
                          divergence_guard=divergence_guard,
                          guard_max_trips=guard_max_trips,
                          diag_stride=diag_stride)
    final_params, history = trainer.train(
        params, train_batch, valid_batch, test_batch,
        save_dir=save_dir, verbose=verbose, seed=seed,
        resume=resume, stop_after_phase=stop_after_phase,
        checkpoint_every=checkpoint_every,
        stop_after_epochs=stop_after_epochs,
    )
    return gan, final_params, history, trainer
