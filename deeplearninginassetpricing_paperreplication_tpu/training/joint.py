"""Joint (1-phase) trainer — the reference demo notebook's training mode.

``/root/reference/notebooks/demo.ipynb`` cells 9-10 train the GAN with ONE
Adam over ALL parameters (generator + discriminator together) on the default
conditional forward, grad-clip 5.0, and ``ReduceLROnPlateau(mode='max',
factor=0.5, patience=20)`` stepped on the validation Sharpe; cell 16 trains
the SimpleSDF baseline the same way (no scheduler, no clip).

Here the whole loop is ONE compiled `lax.scan` (train step + valid eval +
plateau-LR state per epoch, zero host syncs), with torch's exact plateau
semantics: an epoch improves iff ``metric > best * (1 + threshold)`` for
rel-mode / positive metrics (torch default threshold 1e-4); after `patience`
non-improving epochs the LR multiplies by `factor` and the bad-epoch counter
resets (cooldown 0).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.gan import GAN
from ..ops.metrics import sharpe
from .steps import make_eval_step

Params = Any
Batch = Dict[str, jnp.ndarray]


def _plateau_update(lr_scale, best, bad, metric, factor, patience, threshold):
    """torch ReduceLROnPlateau(mode='max', threshold_mode='rel') step:
    is_better(a, best) == a > best * (1 + threshold), best := a on improve."""
    improved = metric > best * (1.0 + threshold)
    best = jnp.where(improved, metric, best)
    bad = jnp.where(improved, 0, bad + 1)
    reduce_now = bad > patience
    lr_scale = jnp.where(reduce_now, lr_scale * factor, lr_scale)
    bad = jnp.where(reduce_now, 0, bad)
    return lr_scale, best, bad


def joint_train(
    gan: GAN,
    params: Params,
    train_batch: Batch,
    valid_batch: Batch,
    num_epochs: int = 200,
    lr: float = 1e-3,
    grad_clip: float = 5.0,
    plateau_factor: float = 0.5,
    plateau_patience: int = 20,
    phase: str = "conditional",
    seed: int = 0,
) -> Tuple[Params, Dict[str, np.ndarray]]:
    """Joint optimizer over the FULL param tree, compiled to one scan.

    Returns (final_params, history) with per-epoch train/valid loss+sharpe
    and the lr trace. Dropout is active during training (rng from `seed`).
    """
    eval_step = make_eval_step(gan)
    adam = optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.scale_by_adam(eps=1e-8),
    )
    opt_state = adam.init(params)
    base_rng = jax.random.key(seed)

    train_b = gan.prepare_batch(train_batch)
    valid_b = gan.prepare_batch(valid_batch)

    def loss_fn(p, rng):
        out = gan.forward(p, train_b, phase=phase, rng=rng)
        return out["loss"], out

    def epoch(carry, e):
        p, opt, lr_scale, best, bad = carry
        rng = jax.random.fold_in(base_rng, e)
        (loss, out), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, rng)
        updates, opt = adam.update(grads, opt, p)
        updates = jax.tree.map(lambda u: -lr * lr_scale * u, updates)
        p = optax.apply_updates(p, updates)
        va = eval_step(p, valid_b)
        lr_scale, best, bad = _plateau_update(
            lr_scale, best, bad, va["sharpe"],
            plateau_factor, plateau_patience, 1e-4,
        )
        hist = {
            "train_loss": loss,
            "train_sharpe": sharpe(out["portfolio_returns"], ddof=1),
            "valid_loss": va["loss"],
            "valid_sharpe": va["sharpe"],
            "lr": lr * lr_scale,
        }
        return (p, opt, lr_scale, best, bad), hist

    init = (
        params, opt_state, jnp.float32(1.0), jnp.float32(-np.inf),
        jnp.int32(0),
    )
    (params, *_), hist = jax.jit(
        lambda init: jax.lax.scan(epoch, init, jnp.arange(num_epochs))
    )(init)
    return params, {k: np.asarray(v) for k, v in hist.items()}


def train_simple_sdf(
    macro_dim: int,
    individual_dim: int,
    train_batch: Batch,
    valid_batch: Batch,
    hidden_dims: Tuple[int, ...] = (32, 16),
    dropout: float = 0.1,
    num_epochs: int = 200,
    lr: float = 1e-3,
    seed: int = 0,
) -> Tuple[Any, Params, Dict[str, np.ndarray]]:
    """SimpleSDF baseline trained jointly (demo.ipynb cell 16): plain Adam,
    no clip, no scheduler; history of train/valid Sharpe per epoch."""
    from ..models.networks import SimpleSDF, simple_sdf_forward

    model = SimpleSDF(
        macro_dim=macro_dim, individual_dim=individual_dim,
        hidden_dims=tuple(hidden_dims), dropout=dropout,
    )
    rng = jax.random.key(seed)
    params = model.init(
        {"params": rng},
        train_batch.get("macro"), train_batch["individual"],
        train_batch["mask"], True,
    )["params"]
    adam = optax.adam(lr, eps=1e-8)
    opt_state = adam.init(params)
    base_rng = jax.random.key(seed + 1)

    def fwd(p, batch, rng=None):
        return simple_sdf_forward(model, p, batch, rng=rng)

    def epoch(carry, e):
        p, opt = carry
        rng = jax.random.fold_in(base_rng, e)
        def loss_fn(p):
            return fwd(p, train_batch, rng=rng)["loss"]
        grads = jax.grad(loss_fn)(p)
        updates, opt = adam.update(grads, opt)
        p = optax.apply_updates(p, updates)
        tr = fwd(p, train_batch)
        va = fwd(p, valid_batch)
        hist = {
            "train_sharpe": sharpe(tr["portfolio_returns"], ddof=1),
            "valid_sharpe": sharpe(va["portfolio_returns"], ddof=1),
            "train_loss": tr["loss"],
            "valid_loss": va["loss"],
        }
        return (p, opt), hist

    (params, _), hist = jax.jit(
        lambda init: jax.lax.scan(epoch, init, jnp.arange(num_epochs))
    )((params, opt_state))
    return model, params, {k: np.asarray(v) for k, v in hist.items()}
