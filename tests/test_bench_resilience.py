"""Kill-injection tests for the bench orchestrator (VERDICT r4 next #1).

The round-4 driver bench died rc=1 to a TPU-tunnel outage (`BENCH_r04.json`
is a traceback). These tests prove the orchestrator survives both documented
outage classes — a backend raise (child exits nonzero) and a tunnel RPC hang
(child stops heartbeating and ignores SIGTERM) — and always assembles one
valid JSON payload from whatever sections completed.

Stub child scripts stand in for the measurement process: they speak the same
state-file protocol (atomic JSON + heartbeats + exit codes) without touching
jax, so the quick lane stays fast.
"""

import importlib.util
import json
import sys
import textwrap
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]

spec = importlib.util.spec_from_file_location("bench_module", REPO / "bench.py")
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)

# every stub reads/writes the same state protocol as the real child
STUB_PRELUDE = """
import json, os, sys, time
state_path = sys.argv[sys.argv.index("--state") + 1]
def read():
    try:
        with open(state_path) as f:
            return json.load(f)
    except Exception:
        return {}
def write(s):
    tmp = state_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(s, f)
    os.replace(tmp, state_path)
def heartbeat(s, name):
    s["heartbeat"] = {"section": name, "ts": time.time()}
    write(s)
state = read()
state.setdefault("sections", {})
state.setdefault("attempts", {})
state.setdefault("section_errors", {})
"""

REAL_SHAPE_RESULT = {
    "shape": "T=240/60/300 N=10000 F=46 M=178",
    "cold_compile_s": 35.0, "warm_compile_s": 9.0,
    "cold_execute_s": 18.0, "execute_s": 9.0,
    "cold_total_s": 53.0, "warm_total_s": 18.0,
    "cached_cold_total_s": 27.0, "test_sharpe": 0.05,
}


def _make_stub(tmp_path, body):
    script = tmp_path / "stub_child.py"
    script.write_text(STUB_PRELUDE + textwrap.dedent(body))
    # -S: skip site processing — this image's sitecustomize (.axon_site TPU
    # plugin registration) costs ~5 s of interpreter startup, which would
    # dwarf the test's sub-second hang timeouts. The REAL bench child needs
    # site processing; the stubs only need the stdlib.
    return [sys.executable, "-S", str(script)]


def _orchestrate(cmd, state_path, **kw):
    kw.setdefault("timeouts", {"setup": 2.0, "real_shape": 2.0,
                               "synthetic_small": 2.0, "ensemble": 0.5,
                               "sweep_bucket": 2.0})
    kw.setdefault("max_restarts", 2)
    kw.setdefault("backoffs", (0.05,))
    kw.setdefault("poll_s", 0.05)
    return bench.orchestrate(cmd, state_path, **kw)


def test_backend_raise_yields_valid_error_json(tmp_path):
    """Child that dies like the r4 outage (UNAVAILABLE at setup, rc=3):
    the parent must still return a serializable payload with an error
    field — never a traceback."""
    cmd = _make_stub(tmp_path, """
    heartbeat(state, "setup")
    state["section_errors"]["setup"] = (
        "RuntimeError(\\"Unable to initialize backend 'axon': UNAVAILABLE\\")")
    write(state)
    sys.exit(3)
    """)
    state_path = tmp_path / "state.json"
    bench._write_state(state_path, {})
    out = _orchestrate(cmd, state_path)
    json.dumps(out)  # one valid JSON line, by construction
    assert out["value"] is None
    assert "UNAVAILABLE" in out["error"]["section_errors"]["setup"]
    assert set(out["error"]["missing_sections"]) == set(bench.SECTION_ORDER)
    # setup failed twice in a row -> early exit, not the full restart budget
    assert out["resilience"]["restarts"] == 1


def test_hang_is_sigkilled_and_completed_sections_survive(tmp_path):
    """Child hangs in a tunnel RPC after finishing real_shape: the parent
    SIGKILLs on heartbeat timeout, and the final payload keeps the headline
    from the section that completed before the outage."""
    cmd = _make_stub(tmp_path, f"""
    if "real_shape" not in state["sections"]:
        heartbeat(state, "real_shape")
        state["sections"]["real_shape"] = {REAL_SHAPE_RESULT!r}
        write(state)
    heartbeat(state, "ensemble")
    time.sleep(600)  # hung RPC: never returns, ignores SIGTERM
    """)
    state_path = tmp_path / "state.json"
    bench._write_state(state_path, {})
    t0 = time.time()
    out = _orchestrate(cmd, state_path, max_restarts=1)
    assert time.time() - t0 < 30, "hang must be killed, not waited out"
    json.dumps(out)
    assert out["value"] == 27.0  # cached-cold headline from real_shape
    assert out["true_cold_total_s"] == 53.0
    assert "ensemble" in out["error"]["missing_sections"]
    assert "hang" in out["error"]["section_errors"]["ensemble"]


def test_restart_skips_completed_sections_and_recovers(tmp_path):
    """Child crashes once mid-run (wedged backend); the respawned child
    skips what's done and finishes. No error field in the final payload."""
    cmd = _make_stub(tmp_path, f"""
    if "real_shape" not in state["sections"]:
        heartbeat(state, "real_shape")
        state["sections"]["real_shape"] = {REAL_SHAPE_RESULT!r}
        state["section_errors"]["synthetic_small"] = "UNAVAILABLE (transient)"
        write(state)
        sys.exit(3)
    for name in {tuple(s for s in bench.SECTION_ORDER if s != "real_shape")!r}:
        if name not in state["sections"]:
            heartbeat(state, name)
            state["sections"][name] = {{"cold_total_s": 1.0, "note": name}}
            state["section_errors"].pop(name, None)
            write(state)
    sys.exit(0)
    """)
    state_path = tmp_path / "state.json"
    bench._write_state(state_path, {})
    out = _orchestrate(cmd, state_path)
    json.dumps(out)
    assert "error" not in out
    assert out["value"] == 27.0
    assert out["resilience"]["restarts"] == 1
    assert out["ensemble_real_shape"]["note"] == "ensemble"


def test_assemble_full_state_headlines_cached_cold():
    """Headline semantics (VERDICT r4 next #3): value = cached-cold, with the
    true-cold figure and its own vs_baseline disclosed beside it."""
    state = {
        "sections": {
            "matmul_ceiling": {"model_shape_ceiling_tflops": 60.0},
            "real_shape": dict(REAL_SHAPE_RESULT),
            "startup_pipeline": {"cold_s": 30.0, "cache_hit_s": 5.0},
            "synthetic_small": {"cold_total_s": 28.0},
            "ensemble": {"warm_wall_s": 56.0},
            "sweep_bucket": {"warm_wall_s": 11.0},
            "serving": {"compiles": 2, "dispatches": 400},
            "serving_async": {"replicas": 2,
                              "steady_state_recompiles": {"replica0": 0}},
        },
        "bandwidth": {"hbm_peak_gbps": 819.0},
        "device": "TPU v5 lite0",
        "restarts": 0,
    }
    out = bench.assemble(state)
    assert out["metric"].endswith("cached_cold")
    assert out["value"] == 27.0
    assert out["vs_baseline"] == round(2400.0 / 27.0, 2)
    assert out["true_cold_total_s"] == 53.0
    assert out["true_cold_vs_baseline"] == round(2400.0 / 53.0, 2)
    assert out["serving"]["dispatches"] == 400
    assert out["serving_async"]["replicas"] == 2
    assert "error" not in out
    json.dumps(out)


def test_two_consecutive_setup_failures_exit_early(tmp_path):
    """A backend that is simply DOWN (every child dies in setup) must not
    burn the full restart budget at the 900 s setup timeout: the parent
    stops after two consecutive setup failures and assembles what it has."""
    cmd = _make_stub(tmp_path, """
    state["spawn_count"] = state.get("spawn_count", 0) + 1
    heartbeat(state, "setup")
    state["section_errors"]["setup"] = "UNAVAILABLE (backend down)"
    write(state)
    sys.exit(3)
    """)
    state_path = tmp_path / "state.json"
    bench._write_state(state_path, {})
    out = _orchestrate(cmd, state_path, max_restarts=5)
    json.dumps(out)
    assert out["value"] is None
    assert bench._read_state(state_path)["spawn_count"] == 2
    # a child that PROGRESSES resets the counter: completed sections keep
    # the run going through later crashes up to max_restarts
    cmd2 = _make_stub(tmp_path, f"""
    state["spawn_count"] = state.get("spawn_count", 0) + 1
    if "real_shape" not in state["sections"]:
        heartbeat(state, "real_shape")
        state["sections"]["real_shape"] = {REAL_SHAPE_RESULT!r}
        write(state)
        sys.exit(3)
    for name in {tuple(s for s in bench.SECTION_ORDER if s != "real_shape")!r}:
        if name not in state["sections"]:
            heartbeat(state, name)
            state["sections"][name] = {{"cold_total_s": 1.0}}
            write(state)
    sys.exit(0)
    """)
    state_path2 = tmp_path / "state2.json"
    bench._write_state(state_path2, {})
    out2 = _orchestrate(cmd2, state_path2)
    assert "error" not in out2 and out2["value"] == 27.0

    # tunnel dies AFTER a section completed: the early exit must still fire
    # on the two consecutive setup deaths (per-child progress, not the
    # cumulative section count, feeds the counter)
    cmd3 = _make_stub(tmp_path, f"""
    state["spawn_count"] = state.get("spawn_count", 0) + 1
    if "real_shape" not in state["sections"]:
        heartbeat(state, "real_shape")
        state["sections"]["real_shape"] = {REAL_SHAPE_RESULT!r}
        write(state)
        sys.exit(3)  # crash after landing the section (tunnel drops here)
    heartbeat(state, "setup")
    state["section_errors"]["setup"] = "UNAVAILABLE (backend down)"
    write(state)
    sys.exit(3)
    """)
    state_path3 = tmp_path / "state3.json"
    bench._write_state(state_path3, {})
    out3 = _orchestrate(cmd3, state_path3, max_restarts=5)
    json.dumps(out3)
    assert out3["value"] == 27.0  # the completed section survives
    # spawns: 1 (progress+crash) + 2 setup deaths -> early exit
    assert bench._read_state(state_path3)["spawn_count"] == 3


def test_sigterm_mid_run_still_prints_valid_json(tmp_path):
    """e2e against the REAL bench.py parent: a driver-style SIGTERM while
    the child hangs (injected at setup, before any jax import) must produce
    one valid JSON line on stdout and rc=0 — never a traceback."""
    import os
    import signal
    import subprocess

    env = dict(os.environ,
               DLAP_BENCH_INJECT="hang:setup",
               DLAP_BENCH_STATE=str(tmp_path / "state.json"),
               DLAP_BENCH_LOG=str(tmp_path / "child.log"))
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py")], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        time.sleep(8)  # parent up (≈5 s sitecustomize) + child spawned
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out
    payload = json.loads(out.strip().splitlines()[-1])
    assert "orchestrator" in payload["error"]["section_errors"]
    assert payload["value"] is None


def test_inject_hook_raises_for_matching_section(monkeypatch):
    monkeypatch.setenv("DLAP_BENCH_INJECT", "raise:ensemble")
    bench._maybe_inject("real_shape")  # no-op: different section
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        bench._maybe_inject("ensemble")
