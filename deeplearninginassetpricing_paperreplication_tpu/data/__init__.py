from .panel import PanelDataset, load_panel, load_splits
from .synthetic import generate_all_splits, generate_dataset
