from .checkpoint import (
    load_checkpoint_dir,
    load_params,
    load_torch_checkpoint,
    save_params,
    save_torch_checkpoint,
    torch_state_dict_from_params,
)
from .steps import make_eval_step, make_optimizer, make_train_step
from .trainer import Trainer, train_3phase
