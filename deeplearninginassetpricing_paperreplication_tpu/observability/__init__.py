"""Unified run telemetry: the single sink for everything the stack logs.

Every CLI run directory is self-describing through three artifacts:

  * ``manifest.json``  — who/what/where: config hash, seed, jax versions,
    device topology, git sha, data fingerprint (:mod:`.manifest`);
  * ``events.jsonl``   — append-only structured events: span begin/end pairs
    with monotonic timestamps, counters, gauges, memory snapshots, log lines
    (:mod:`.events`); multihost workers write ``events.proc{p}.jsonl``;
  * ``heartbeat.json`` — phase-tagged liveness in the exact state-file format
    ``bench.py``'s parent uses for hang detection and death attribution
    (:mod:`.heartbeat`).

``python -m deeplearninginassetpricing_paperreplication_tpu.report`` —
see :mod:`.report` — aggregates one or many run dirs into a
compile-vs-execute breakdown, per-phase throughput, peak memory, and an
optional parity comparison against the repo's ``PARITY_*.json`` baselines.
"""

from .budgets import check_budgets, format_budget_report
from .events import EventLog, new_run_id
from .heartbeat import Heartbeat, read_state, write_state
from .logging import RunLogger, get_run_logger, set_run_logger
from .manifest import (
    build_manifest,
    config_hash,
    data_fingerprint,
    load_manifest,
    update_manifest,
    write_manifest,
)
from .memory import device_memory_snapshot
from .metrics import (
    MetricsRegistry,
    MetricsSidecar,
    parse_prom_exemplars,
    parse_prom_text,
)
from .trace import assemble_trace, write_trace
from .tracecontext import (
    TraceContext,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    trace_sampled,
)
from .xla import analyze_compiled, record_program

__all__ = [
    "EventLog",
    "Heartbeat",
    "MetricsRegistry",
    "MetricsSidecar",
    "RunLogger",
    "TraceContext",
    "analyze_compiled",
    "assemble_trace",
    "build_manifest",
    "check_budgets",
    "config_hash",
    "data_fingerprint",
    "device_memory_snapshot",
    "format_budget_report",
    "format_traceparent",
    "get_run_logger",
    "load_manifest",
    "new_span_id",
    "new_trace_id",
    "parse_prom_exemplars",
    "parse_prom_text",
    "parse_traceparent",
    "record_program",
    "trace_sampled",
    "update_manifest",
    "new_run_id",
    "read_state",
    "set_run_logger",
    "write_manifest",
    "write_state",
    "write_trace",
]
