"""Checkpoint IO: params as flax msgpack, plus reference .pt import.

Artifacts per run directory mirror the reference's
(``/root/reference/src/train.py:266-272, 424, 579-580, 603``):

    config.json               — GANConfig (reference-shaped keys)
    best_model_loss.msgpack   — best by valid loss (per phase semantics)
    best_model_sharpe.msgpack — best by valid sharpe (the ensemble input)
    final_model.msgpack       — the reloaded-best final model
    history.npz               — per-epoch series + phase labels

`load_torch_checkpoint` maps a reference PyTorch ``state_dict`` (.pt) into
our params tree — used for cross-framework numeric parity tests and so users
can migrate trained reference checkpoints without retraining.
"""

from __future__ import annotations

import warnings
from pathlib import Path
from typing import Any, Dict, Optional, Tuple, Union

import jax
import numpy as np
from flax import serialization

from ..models.gan import GAN
from ..reliability.verified import (
    DEFAULT_GENERATIONS,
    load_verified,
    verified_exists,
    write_verified,
)
from ..utils.config import GANConfig

Params = Any


def save_params(path: Union[str, Path], params: Params,
                generations: int = DEFAULT_GENERATIONS) -> None:
    """Atomic, digest-verified, generational write (reliability/verified):
    tmp + ``os.replace`` + a ``.sha256`` sidecar, with the previous file
    rotated to ``.g1`` — a kill mid-save can never strand the run, and a
    later corruption falls back to the previous good generation on load."""
    # pull to host once; tiny trees (≈12k params)
    host = jax.device_get(params)
    write_verified(Path(path), serialization.to_bytes(host),
                   generations=generations)


def _parse_params(template: Params, path: Union[str, Path]):
    """A flax-msgpack parser whose failures NAME the offending file (the
    raw flax traceback on a truncated file is unpacker internals)."""

    def parse(data: bytes) -> Params:
        try:
            return serialization.from_bytes(template, data)
        except Exception as e:  # noqa: BLE001 — any deserialization failure
            raise ValueError(
                f"corrupt or truncated checkpoint msgpack {path}: "
                f"{type(e).__name__}: {e}"
            ) from e

    return parse


def load_params(path: Union[str, Path], template: Params) -> Params:
    """Deserialize into the structure of `template` (from GAN.init).

    Loads through the verified path: the ``.sha256`` sidecar is checked
    when present, and a corrupt newest file falls back generation-by-
    generation (``.g1``, …) to the last good checkpoint. When no generation
    is usable, raises a ``ValueError`` naming each offending file."""
    path = Path(path)
    params, _ = load_verified(path, _parse_params(template, path))
    return params


def load_checkpoint_dir(
    ckpt_dir: Union[str, Path],
    which: str = "best_model_sharpe",
) -> Tuple[GAN, Params]:
    """Load (gan, params) from a run directory (config.json + msgpack),
    mirroring the reference's ``load_model`` (evaluate_ensemble.py:17-29)."""
    ckpt_dir = Path(ckpt_dir)
    cfg = GANConfig.load(ckpt_dir / "config.json")
    gan = GAN(cfg)
    # candidate order: the requested artifact in our format, then the
    # reference's torch format (a reference run directory loads transparently
    # — the mirror image of save_torch_checkpoint), then the final-model
    # fallbacks (a run whose schedule never passed ignore_epoch writes no
    # best_model file — save-on-update-only, matching the reference)
    candidates = [ckpt_dir / f"{which}.msgpack", ckpt_dir / f"{which}.pt"]
    if which.startswith("best_model"):
        candidates += [ckpt_dir / "final_model.msgpack",
                       ckpt_dir / "final_model.pt"]
    for path in candidates:
        # msgpack artifacts may survive only as a fallback generation
        # (.g1, …) after a corrupted newest write — still loadable
        present = (verified_exists(path) if path.suffix == ".msgpack"
                   else path.exists())
        if not present:
            continue
        if path.stem == "final_model" and which != "final_model":
            warnings.warn(
                f"{which} absent in {ckpt_dir} (best tracker never "
                f"updated); using {path.name}"
            )
        if path.suffix == ".pt":
            _, params = load_torch_checkpoint(path, cfg=cfg)
        else:
            params = load_params(path, gan.init(jax.random.key(0)))
        return gan, params
    raise FileNotFoundError(
        f"no {which}(.msgpack|.pt) or final_model fallback in {ckpt_dir}"
    )


# -- reference (PyTorch) checkpoint import ----------------------------------


def _from_torch_tensor(t) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy())


def params_from_torch_state_dict(state_dict: Dict[str, Any], cfg: GANConfig) -> Params:
    """Map a reference ``AssetPricingGAN.state_dict()`` to our params tree.

    Reference module paths (src/model.py):
        sdf_net.macro_lstm.lstm.{weight_ih_l0, weight_hh_l0, bias_ih_l0, bias_hh_l0}
        sdf_net.fc_layers.{0,3,...}.{weight, bias}   (Linear at stride 3: Linear/ReLU/Dropout)
        sdf_net.output_proj.{weight, bias}
        moment_net.fc_layers....                      (or Identity when no hidden)
        moment_net.output_proj.{weight, bias}

    Ours (flax): sdf_net/{macro_lstm/{w_ih_l0,...}, TorchDense_i/Dense_0/{kernel,bias},
    output_proj/Dense_0/...}; kernels are transposed torch weights.
    """
    sd = {k: _from_torch_tensor(v) for k, v in state_dict.items()}

    def dense(prefix_torch: str) -> Dict[str, np.ndarray]:
        return {
            "Dense_0": {
                "kernel": sd[f"{prefix_torch}.weight"].T,
                "bias": sd[f"{prefix_torch}.bias"],
            }
        }

    sdf: Dict[str, Any] = {}
    if cfg.use_rnn and cfg.macro_feature_dim > 0:
        lstm = {}
        for li in range(len(cfg.num_units_rnn)):
            lstm[f"w_ih_l{li}"] = sd[f"sdf_net.macro_lstm.lstm.weight_ih_l{li}"]
            lstm[f"w_hh_l{li}"] = sd[f"sdf_net.macro_lstm.lstm.weight_hh_l{li}"]
            lstm[f"b_ih_l{li}"] = sd[f"sdf_net.macro_lstm.lstm.bias_ih_l{li}"]
            lstm[f"b_hh_l{li}"] = sd[f"sdf_net.macro_lstm.lstm.bias_hh_l{li}"]
        sdf["macro_lstm"] = lstm
    for i in range(len(cfg.hidden_dim)):
        # torch Sequential index: Linear at 3*i (Linear, ReLU, Dropout triplets)
        sdf[f"TorchDense_{i}"] = dense(f"sdf_net.fc_layers.{3*i}")
    sdf["output_proj"] = dense("sdf_net.output_proj")

    moment: Dict[str, Any] = {}
    for i in range(len(cfg.hidden_dim_moment)):
        moment[f"TorchDense_{i}"] = dense(f"moment_net.fc_layers.{3*i}")
    moment["output_proj"] = dense("moment_net.output_proj")

    return {"sdf_net": sdf, "moment_net": moment}


def torch_state_dict_from_params(params: Params, cfg: GANConfig) -> Dict[str, Any]:
    """Inverse of :func:`params_from_torch_state_dict`: our params tree →
    a reference-shaped ``AssetPricingGAN.state_dict()`` (torch tensors).

    Completes checkpoint interchangeability: models trained here load into
    the reference with ``model.load_state_dict(...)`` (strict), so its
    evaluate/ensemble/plots tooling can consume our training runs.
    """
    import torch

    host = jax.device_get(params)
    sd: Dict[str, Any] = {}

    def put_dense(prefix_torch: str, tree: Dict[str, Any]) -> None:
        sd[f"{prefix_torch}.weight"] = torch.from_numpy(
            np.asarray(tree["Dense_0"]["kernel"], np.float32).T.copy()
        )
        sd[f"{prefix_torch}.bias"] = torch.from_numpy(
            np.asarray(tree["Dense_0"]["bias"], np.float32).copy()
        )

    sdf = host["sdf_net"]
    if cfg.use_rnn and cfg.macro_feature_dim > 0:
        lstm = sdf["macro_lstm"]
        for li in range(len(cfg.num_units_rnn)):
            for ours, theirs in (
                (f"w_ih_l{li}", f"weight_ih_l{li}"), (f"w_hh_l{li}", f"weight_hh_l{li}"),
                (f"b_ih_l{li}", f"bias_ih_l{li}"), (f"b_hh_l{li}", f"bias_hh_l{li}"),
            ):
                sd[f"sdf_net.macro_lstm.lstm.{theirs}"] = torch.from_numpy(
                    np.asarray(lstm[ours], np.float32).copy()
                )
    for i in range(len(cfg.hidden_dim)):
        put_dense(f"sdf_net.fc_layers.{3*i}", sdf[f"TorchDense_{i}"])
    put_dense("sdf_net.output_proj", sdf["output_proj"])
    moment = host["moment_net"]
    for i in range(len(cfg.hidden_dim_moment)):
        put_dense(f"moment_net.fc_layers.{3*i}", moment[f"TorchDense_{i}"])
    put_dense("moment_net.output_proj", moment["output_proj"])
    return sd


def save_torch_checkpoint(
    pt_path: Union[str, Path], params: Params, cfg: GANConfig
) -> None:
    """Write a reference-loadable .pt plus the config.json the reference's
    ``load_model`` requires beside it (evaluate_ensemble.py:17-29), so the
    output directory is directly consumable by the reference tooling."""
    import torch

    pt_path = Path(pt_path)
    pt_path.parent.mkdir(parents=True, exist_ok=True)
    torch.save(torch_state_dict_from_params(params, cfg), pt_path)
    config_path = pt_path.parent / "config.json"
    if config_path.exists():
        # a stale config from an earlier different-architecture run would
        # make the reference's load_model build the wrong model; overwrite
        # on mismatch (and say so) instead of silently keeping it
        try:
            existing = GANConfig.load(config_path)
        except Exception:
            existing = None
        if existing != cfg:
            import warnings

            warnings.warn(
                f"{config_path} did not match the exported checkpoint's "
                "architecture; overwriting it so the reference's strict "
                "load succeeds",
                stacklevel=2,
            )
            cfg.save(config_path)
    else:
        cfg.save(config_path)


def load_torch_checkpoint(
    pt_path: Union[str, Path],
    cfg: Optional[GANConfig] = None,
    config_path: Optional[Union[str, Path]] = None,
) -> Tuple[GAN, Params]:
    """Load a reference .pt checkpoint (requires torch, CPU-only is fine)."""
    import torch  # local import: torch is optional at runtime

    if cfg is None:
        if config_path is None:
            config_path = Path(pt_path).parent / "config.json"
        cfg = GANConfig.load(config_path)
    state_dict = torch.load(pt_path, map_location="cpu", weights_only=True)
    params = params_from_torch_state_dict(state_dict, cfg)
    return GAN(cfg), jax.tree.map(lambda x: np.asarray(x, np.float32), params)
