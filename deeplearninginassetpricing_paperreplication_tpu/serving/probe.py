"""Blackbox synthetic prober: availability measured from OUTSIDE, even at
zero organic traffic.

The metrics plane (PR 8/10/14) is whitebox — it reports what the serving
processes say about themselves, which is exactly nothing when a replica is
wedged, SIGSTOPped, or dead. The :class:`Prober` is the traffic-
independent counterpart: a supervised thread (or standalone CLI) that
every ``interval_s`` fires known-good fixture requests

  * at the PUBLIC port — a ``/v1/weights`` request in the same shape the
    PR-14 canary ring replays (a fixed characteristics matrix + month, on
    the raw-f32 wire), so the probe exercises the full parse → batch →
    dispatch → serialize path a real client pays; the response bytes are
    sha256-digested and digest CHANGES are counted (``probe/digest_change``)
    — a hot-swap legitimately moves the digest once, a flapping one does
    not;
  * at every replica's private admin ``/healthz`` and ``/metrics``,
    discovered from the live ``fleet.json`` layout each cycle — so a
    wedged-but-accepting replica (socket accepts, loop never answers) is
    caught by the probe TIMEOUT between autoscaler polls, and a scaled
    fleet is re-discovered without restarts.

Every check lands in the metrics plane (``dlap_probe_*``: per-target
success gauge, latency gauge, check counters by outcome) and the event
log; FAILURES are additionally emitted as kind-``probe`` rows
(``probe/failure``) — a DURABLE event kind, fsync'd within one flush
window — and render as instant marks in ``report --trace``. A missing or
torn ``fleet.json`` is itself recorded (``probe/layout_unreadable``) and
the prober carries on with its last-known layout: the layout file dying
must not blind the prober exactly when the fleet is in trouble.

:func:`build_sources` wires prober counts + fleet scrapes + the promotion
pointer into the named sources an :class:`~..observability.slo.SLOEngine`
spec references. The CLI (``python -m ….serving.probe``) runs prober +
engine together against a fleet run dir.

Stdlib + numpy only (the fixture payload); never imports jax — the prober
runs in thin parents and ops boxes.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import signal
import sys
import threading
import time
import urllib.request
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability.events import EventLog
from .fleet import read_fleet_json

DEFAULT_INTERVAL_S = 1.0
DEFAULT_TIMEOUT_S = 2.0
# fixture shape: small enough to cost microseconds per dispatch, real
# enough to ride the production wire end to end
FIXTURE_STOCKS = 32
# server.BINARY_CONTENT_TYPE, duplicated as a literal so the standalone
# probe CLI never imports the serving engine (and with it jax) just for
# a header string; tier-1 asserts the two stay equal
BINARY_CONTENT_TYPE = "application/x-dlap-f32"


def fixture_payload(n_features: int, month: int = 0,
                    n_stocks: int = FIXTURE_STOCKS,
                    seed: int = 1234) -> bytes:
    """The known-good probe body: a deterministic characteristics matrix
    on the raw-f32 wire — the same request shape the PR-14 canary ring
    replays across hot-swaps, so a probe is indistinguishable from a
    (tiny) real query to every layer it crosses."""
    from .loadgen import binary_payload_bytes

    rng = np.random.default_rng(seed)
    individual = rng.standard_normal(
        (n_stocks, n_features)).astype(np.float32)
    return binary_payload_bytes(individual, month)


class ProbeTarget:
    """One probed endpoint: ``kind`` is ``fixture`` (POST the known-good
    body to the public port) or ``get`` (GET an admin path)."""

    __slots__ = ("name", "kind", "url", "body", "content_type")

    def __init__(self, name: str, kind: str, url: str,
                 body: Optional[bytes] = None,
                 content_type: Optional[str] = None):
        self.name = name
        self.kind = kind
        self.url = url
        self.body = body
        self.content_type = content_type


class Prober:
    """The supervised probe loop (see module doc). ``probe_once()`` is one
    sweep over the current target set, exposed for deterministic tests;
    ``start()`` runs it on a daemon thread every ``interval_s``."""

    def __init__(
        self,
        events: EventLog,
        public_url: Optional[str] = None,
        fixture: Optional[bytes] = None,
        fleet_dir=None,
        replica_paths: Tuple[str, ...] = ("/healthz", "/metrics"),
        interval_s: float = DEFAULT_INTERVAL_S,
        timeout_s: float = DEFAULT_TIMEOUT_S,
    ):
        self.events = events
        self.public_url = (public_url.rstrip("/") if public_url else None)
        self.fixture = fixture
        self.fixture_content_type = BINARY_CONTENT_TYPE
        self.fleet_dir = Path(fleet_dir) if fleet_dir else None
        self.replica_paths = tuple(replica_paths)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self.checks = 0
        self.failures = 0
        self.digest_changes = 0
        self.layout_unreadable = 0
        self.cycles = 0
        self._last_layout: Optional[Dict[str, Any]] = None
        self._last_digest: Optional[str] = None
        self._consecutive: Dict[str, int] = {}
        self._pool: Any = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- target discovery ----------------------------------------------------

    def targets(self) -> List[ProbeTarget]:
        """The current probe set: the public fixture target plus two admin
        targets per live replica from ``fleet.json``. A missing/torn
        layout is counted and the LAST-KNOWN layout keeps the replica
        targets alive — tooling losing a file must not read as the fleet
        being healthy."""
        out: List[ProbeTarget] = []
        if self.public_url and self.fixture is not None:
            out.append(ProbeTarget(
                "public", "fixture", self.public_url + "/v1/weights",
                body=self.fixture, content_type=self.fixture_content_type))
        if self.fleet_dir is not None:
            layout = read_fleet_json(self.fleet_dir)
            if layout is None:
                with self._lock:
                    self.layout_unreadable += 1
                self.events.counter("probe/layout_unreadable")
                layout = self._last_layout
            else:
                self._last_layout = layout
            for rid in sorted((layout or {}).get("admin_ports") or {},
                              key=lambda r: int(r)):
                port = layout["admin_ports"][rid]
                for path in self.replica_paths:
                    slug = path.strip("/").replace("/", "_")
                    out.append(ProbeTarget(
                        f"replica{rid}_{slug}", "get",
                        f"http://127.0.0.1:{port}{path}"))
        return out

    # -- one probe -----------------------------------------------------------

    def _check(self, target: ProbeTarget) -> Dict[str, Any]:
        t0 = time.monotonic()
        error = None
        body = b""
        try:
            if target.kind == "fixture":
                req = urllib.request.Request(
                    target.url, data=target.body,
                    headers={"Content-Type": target.content_type},
                    method="POST")
            else:
                req = urllib.request.Request(target.url, method="GET")
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                body = r.read()
                if r.status != 200:
                    error = f"http_{r.status}"
        except Exception as e:  # noqa: BLE001 — every failure mode counts
            error = type(e).__name__
        latency_s = time.monotonic() - t0
        rec: Dict[str, Any] = {
            "target": target.name, "ok": error is None,
            "latency_s": round(latency_s, 6), "error": error,
        }
        if error is None and target.kind == "fixture":
            rec["digest"] = hashlib.sha256(body).hexdigest()[:16]
        return rec

    def probe_once(self) -> List[Dict[str, Any]]:
        """One sweep over the current targets — CONCURRENT, so a wedged
        target costs one timeout, not one timeout per target in the sweep
        (the cycle cadence survives half the fleet hanging); records every
        result in the event log / metrics registry and returns the result
        list (deterministic target order)."""
        targets = self.targets()
        if self._pool is None:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="probe")
        results = list(self._pool.map(self._check, targets))
        for target, rec in zip(targets, results):
            with self._lock:
                self.checks += 1
                if rec["ok"]:
                    self._consecutive[target.name] = 0
                else:
                    self.failures += 1
                    self._consecutive[target.name] = (
                        self._consecutive.get(target.name, 0) + 1)
                consecutive = self._consecutive[target.name]
            outcome = "ok" if rec["ok"] else str(rec["error"])[:40]
            self.events.counter("probe/check", target=target.name,
                                outcome=outcome)
            self.events.gauge("probe/success", float(rec["ok"]),
                              target=target.name)
            self.events.gauge("probe/latency_ms",
                              round(rec["latency_s"] * 1e3, 3),
                              target=target.name)
            if not rec["ok"]:
                # DURABLE row (kind "probe" rides the events fsync set):
                # the evidence a SIGKILLed prober host may never get to
                # flush twice
                self.events.emit(
                    "probe", "probe/failure", target=target.name,
                    error=rec["error"],
                    latency_ms=round(rec["latency_s"] * 1e3, 3),
                    consecutive=consecutive)
            digest = rec.get("digest")
            if digest is not None:
                with self._lock:
                    changed = (self._last_digest is not None
                               and digest != self._last_digest)
                    self._last_digest = digest
                    if changed:
                        self.digest_changes += 1
                if changed:
                    self.events.counter("probe/digest_change",
                                        target=target.name)
        with self._lock:
            self.cycles += 1
        return results

    # -- SLO source + stats --------------------------------------------------

    def counts(self) -> Tuple[int, int]:
        """Cumulative ``(failures, checks)`` — the ratio source an
        availability/probe-success SLO objective differences."""
        with self._lock:
            return self.failures, self.checks

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "cycles": self.cycles,
                "checks": self.checks,
                "failures": self.failures,
                "digest_changes": self.digest_changes,
                "layout_unreadable": self.layout_unreadable,
                "consecutive_failures": {
                    k: v for k, v in sorted(self._consecutive.items())
                    if v},
            }

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        # restartable: the overhead bench toggles the prober off and on
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.probe_once()
                except Exception:
                    pass  # the prober outlives a bad cycle

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="blackbox-prober")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


# -- fleet scraping + SLO source wiring --------------------------------------


class FleetScraper:
    """Cumulative whitebox signals from every live replica's admin JSON
    ``/metrics`` (the same endpoints the autoscaler polls), re-discovered
    from ``fleet.json`` each call.

    The summed ``requests``/``drift`` series must stay MONOTONE or the
    burn-rate windows break exactly during incidents: a replica whose
    scrape times out (wedged, mid-restart) must not drop its LIFETIME
    counts from the sum, and a supervised restart resetting its counters
    to zero must not make the sum dip. Per-replica state carries each
    admin URL's last-seen counts across dropouts and folds pre-restart
    totals into a base offset on reset — the same per-replica merge the
    PR-12 autoscaler needed for its shed-rate deltas. An unreachable
    replica therefore contributes its last-seen counts (the sum goes
    flat → the window reads "no new data", never "recovered")."""

    def __init__(self, fleet_dir, timeout_s: float = 2.0):
        self.fleet_dir = Path(fleet_dir)
        self.timeout_s = float(timeout_s)
        # admin url -> {base_*: folded pre-restart totals, last_*: the
        # incarnation's last-seen cumulative counts}
        self._state: Dict[str, Dict[str, float]] = {}

    def _scrape(self, url: str) -> Optional[Dict[str, Any]]:
        try:
            with urllib.request.urlopen(
                    url.rstrip("/") + "/metrics",
                    timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except (OSError, ValueError):
            return None

    def sample(self) -> Optional[Dict[str, Any]]:
        layout = read_fleet_json(self.fleet_dir)
        if layout is None and not self._state:
            return None
        urls = list((layout or {}).get("admin_urls") or self._state)
        p99s: List[float] = []
        reached = 0
        for url in urls:
            m = self._scrape(url)
            if m is None:
                continue  # held state keeps its last-seen contribution
            reached += 1
            cur = {"bad": 0.0, "total": 0.0,
                   "drift_alerts": 0.0, "drift_scored": 0.0}
            for key, n in (m.get("requests") or {}).items():
                status = key.rsplit(" ", 1)[-1]
                if status.isdigit():
                    cur["total"] += int(n)
                    if int(status) >= 500:
                        cur["bad"] += int(n)
            drift = (m.get("model_health") or {}).get("drift") or {}
            cur["drift_alerts"] = float(drift.get("alerts") or 0)
            cur["drift_scored"] = float(drift.get("scored") or 0)
            st = self._state.setdefault(url, {
                "base_bad": 0.0, "base_total": 0.0,
                "base_drift_alerts": 0.0, "base_drift_scored": 0.0,
                "last_bad": 0.0, "last_total": 0.0,
                "last_drift_alerts": 0.0, "last_drift_scored": 0.0})
            if cur["total"] < st["last_total"]:
                # counter reset (supervised restart): fold the previous
                # incarnation's totals into the base so the sum never dips
                for k in ("bad", "total", "drift_alerts", "drift_scored"):
                    st[f"base_{k}"] += st[f"last_{k}"]
            for k in ("bad", "total", "drift_alerts", "drift_scored"):
                st[f"last_{k}"] = cur[k]
            p99 = (m.get("latency") or {}).get("p99_ms")
            if isinstance(p99, (int, float)):
                p99s.append(float(p99))
        if not self._state and reached == 0:
            return None
        sums = {k: sum(st[f"base_{k}"] + st[f"last_{k}"]
                       for st in self._state.values())
                for k in ("bad", "total", "drift_alerts", "drift_scored")}
        return {
            "requests": (sums["bad"], sums["total"]),
            "latency_p99_ms": (max(p99s) if p99s else None),
            "drift": (sums["drift_alerts"],
                      max(sums["drift_scored"], sums["drift_alerts"])),
        }


def pointer_freshness_months(pointer_root) -> Optional[float]:
    """Months since the promotion pointer last advanced (the serving-
    freshness SLO source): ``promoted_at`` age / the mean Gregorian month.
    None when there is no pointer — no refit plane means no freshness
    objective, not a firing one."""
    from ..reliability.promotion import read_pointer

    try:
        pointer = read_pointer(pointer_root)
    except Exception:
        return None
    if not pointer:
        return None
    promoted_at = pointer.get("promoted_at")
    if not isinstance(promoted_at, (int, float)):
        return None
    return max(0.0, (time.time() - promoted_at) / (30.44 * 86400.0))


def build_sources(
    prober: Optional[Prober] = None,
    scraper: Optional[FleetScraper] = None,
    pointer_root=None,
) -> Dict[str, Callable[[], Any]]:
    """The named SLO sources (:data:`~..observability.slo.KNOWN_SOURCES`)
    for one deployment: prober counts (blackbox), fleet scrapes
    (whitebox), pointer freshness. Each fleet-scrape tick samples the
    scraper ONCE and the per-source callables read the shared snapshot."""
    sources: Dict[str, Callable[[], Any]] = {}
    if prober is not None:
        sources["probe"] = prober.counts
    if scraper is not None:
        snapshot: Dict[str, Any] = {}
        lock = threading.Lock()
        # one urllib sweep per engine tick would triple-poll the fleet;
        # instead the first-read source scrapes and the rest reuse the
        # snapshot for the next 50 ms
        state: Dict[str, Any] = {"tick": None}

        def shared(key: str):
            def get():
                with lock:
                    now = time.monotonic()
                    if state["tick"] is None or now - state["tick"] > 0.05:
                        state["tick"] = now
                        sample = scraper.sample()
                        snapshot.clear()
                        if sample:
                            snapshot.update(sample)
                return snapshot.get(key)
            return get

        sources["requests"] = shared("requests")
        sources["latency_p99_ms"] = shared("latency_p99_ms")
        sources["drift"] = shared("drift")
    if pointer_root is not None:
        sources["freshness_months"] = (
            lambda: pointer_freshness_months(pointer_root))
    return sources


# -- CLI ---------------------------------------------------------------------


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Blackbox synthetic prober (+ optional SLO engine) "
                    "for a serving fleet")
    p.add_argument("--url", type=str, default=None,
                   help="public serving URL (e.g. http://127.0.0.1:8787) "
                        "to fire fixture /v1/weights probes at")
    p.add_argument("--fleet_dir", type=str, default=None,
                   help="fleet run dir: fleet.json supplies the per-"
                        "replica admin /healthz + /metrics targets")
    p.add_argument("--run_dir", type=str, required=True,
                   help="telemetry dir: probe/alert events land in "
                        "events.probe.jsonl here")
    p.add_argument("--n_features", type=int, default=46,
                   help="fixture characteristics width (must match the "
                        "served config's individual_feature_dim)")
    p.add_argument("--fixture_month", type=int, default=0)
    p.add_argument("--interval", type=float, default=DEFAULT_INTERVAL_S)
    p.add_argument("--timeout", type=float, default=DEFAULT_TIMEOUT_S)
    p.add_argument("--slo", type=str, default=None,
                   help="slo.json spec: also run the burn-rate SLOEngine "
                        "over the probe + fleet sources")
    p.add_argument("--pointer", type=str, default=None,
                   help="promotion pointer root for the serving-freshness "
                        "source")
    p.add_argument("--alerts_out", type=str, default=None,
                   help="append alert transitions to this JSONL file "
                        "(default: RUN_DIR/alerts.jsonl when --slo is "
                        "given)")
    p.add_argument("--webhook", type=str, default=None,
                   help="also POST alert transitions to this URL")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve the prober's own /metrics (dlap_probe_*, "
                        "dlap_alert_*) on this port")
    return p


def main(argv=None) -> int:
    from ..observability.metrics import MetricsSidecar
    from ..observability.slo import (
        FileAlertSink,
        SLOEngine,
        WebhookAlertSink,
        load_slo,
    )

    args = build_arg_parser().parse_args(argv)
    if not args.url and not args.fleet_dir:
        print("probe: need --url and/or --fleet_dir", file=sys.stderr)
        return 2
    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    events = EventLog(run_dir, process_index=0,
                      filename="events.probe.jsonl")
    fixture = (fixture_payload(args.n_features, args.fixture_month)
               if args.url else None)
    prober = Prober(events, public_url=args.url, fixture=fixture,
                    fleet_dir=args.fleet_dir,
                    interval_s=args.interval, timeout_s=args.timeout)
    engine = None
    if args.slo:
        spec = load_slo(args.slo)
        sinks: list = [FileAlertSink(
            args.alerts_out or run_dir / "alerts.jsonl")]
        if args.webhook:
            sinks.append(WebhookAlertSink(args.webhook))
        scraper = (FleetScraper(args.fleet_dir)
                   if args.fleet_dir else None)
        sources = build_sources(prober=prober, scraper=scraper,
                                pointer_root=args.pointer)
        # the engine refuses a spec with unwired sources (fail-loud
        # contract); running a deliberate subset is the operator's
        # choice, so each dropped objective is named on stderr
        wired = [o for o in spec["objectives"]
                 if o["source"] in sources]
        for o in spec["objectives"]:
            if o["source"] not in sources:
                print(f"probe: WARNING — objective {o['name']!r} "
                      f"DROPPED: source {o['source']!r} is not wired "
                      f"here (needs --fleet_dir and/or --pointer); it "
                      f"will NOT be monitored", file=sys.stderr)
        if not wired:
            print("probe: no objective in the spec has a wired source "
                  "— nothing to monitor", file=sys.stderr)
            return 2
        engine = SLOEngine(
            dict(spec, objectives=wired), sources,
            events=events, sinks=tuple(sinks),
            poll_s=max(args.interval, 0.25))
    sidecar = None
    if args.metrics_port is not None:
        sidecar = MetricsSidecar([events.metrics], port=args.metrics_port)
        port = sidecar.start()
        print(f"probe metrics on http://127.0.0.1:{port}/metrics",
              flush=True)
    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — signal-handler shape
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    prober.start()
    if engine is not None:
        engine.start()
    print(f"prober live: {len(prober.targets())} targets every "
          f"{args.interval:g}s"
          + (", SLO engine armed" if engine is not None else ""),
          flush=True)
    try:
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        prober.stop()
        if engine is not None:
            engine.stop()
        if sidecar is not None:
            sidecar.stop()
        events.close()
        print(json.dumps({"probe": prober.stats(),
                          "slo": engine.state() if engine else None}),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
