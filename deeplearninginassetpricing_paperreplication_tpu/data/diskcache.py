"""Decoded-panel disk cache: skip npz decompress + mask build on re-runs.

The paper workload loads the SAME ~1.2 GB npz panel on every run, then pays
the same decompress, mask build (`panel._build_mask`), zero-fill, and
host-side `flatnonzero`/gather repack (`transfer.pack_rows`) before a single
byte ships to the device. All of that is a pure function of the source file
bytes, so after the first decode this module persists the results as raw
``.npy`` files that later runs ``np.load(mmap_mode="r")`` straight into the
transfer path — no decompress, no mask build, no repack.

Layout: one directory per cache entry under :func:`cache_root`::

    <root>/<key>/meta.json       entry descriptor (version, fingerprints,
                                 shapes, coverage)
    <root>/<key>/returns.npy     [T, N]    float32, zero-filled
    <root>/<key>/individual.npy  [T, N, F] float32, zero-filled
    <root>/<key>/mask.npy        [T, N]    bool
    <root>/<key>/macro.npy       [T, M]    float32 RAW (un-normalized —
                                 normalization depends on the TRAIN split's
                                 stats, so it is applied at load time and the
                                 entry stays keyed by its OWN source files)
    <root>/<key>/dates.npy, variable_names.npy
    <root>/<key>/idx.npy         [V]    int32   ─┐ the packed valid-rows rep
    <root>/<key>/rows.npy        [V, F] float32  ├ transfer.py ships (stored
    <root>/<key>/ret_packed.npy  [V]    float32 ─┘ only when coverage packs)

``<key>`` digests (CACHE_VERSION, char fingerprint, macro fingerprint); a
fingerprint is (resolved path, size, mtime_ns, sha256 of the npz member
directory — names, sizes, CRCs — read from the zip central directory without
touching payload bytes). Any source change (mtime, size, header) therefore
MISSES to a fresh key; :func:`store` evicts superseded entries for the same
source path so the root does not accumulate stale gigabytes.

Stores are atomic (write into a tmp dir, ``os.rename`` into place) and loads
are paranoid: a missing file, a shape mismatch against meta.json, or any
parse error deletes the entry and returns None — the caller falls back to
the npz decode path, never crashes on a corrupt cache.

**Chunked entries** (the sharded data plane, PR 7): alongside the monolithic
layout above, :func:`store_chunked` persists a split with the STOCK axis cut
into fixed-width shards, so a mesh slot can load (and digest-verify) only
the shards it owns instead of materializing the whole panel::

    <root>/<key>/meta.json            chunk manifest (shard width, bounds,
                                      per-file sha256 — written LAST, via
                                      reliability.verified, so its presence
                                      marks a complete entry)
    <root>/<key>/shards/s00000.returns.npy     [T, W]    float32
    <root>/<key>/shards/s00000.individual.npy  [T, W, F] float32
    <root>/<key>/shards/s00000.mask.npy        [T, W]    bool
    <root>/<key>/shards/s00001.*               ... (last shard may be ragged)
    <root>/<key>/{macro,dates,variable_names}.npy   global (un-sharded)

Every file is written through :mod:`..reliability.verified` (atomic tmp +
``os.replace``, sha256 sidecar), and the manifest records each file's digest
independently, binding the shard SET together: a torn or truncated shard
fails :meth:`ChunkedEntry.verify_shard` and the loader re-decodes (and
re-stores) JUST that shard from the source npz — never the whole entry.
The chunked key digests the shard width too, so changing
``DLAP_PANEL_SHARD_WIDTH`` misses to a fresh entry instead of mis-slicing
an old one.

Location: ``$DLAP_PANEL_CACHE_DIR``, else ``$XDG_CACHE_HOME/dlap/panel_cache``,
else ``~/.cache/dlap/panel_cache``. ``DLAP_PANEL_CACHE=0`` disables entirely.
Clear with ``python -m ...data.diskcache --clear`` (or just delete the dir).
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import shutil
import tempfile
import zipfile
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ..reliability.verified import compute_digest, load_verified, write_verified

CACHE_VERSION = 1

# entry arrays: filename -> (meta shape key, required). macro/variable_names
# and the packed triple are optional (absent macro / high-coverage panels).
_REQUIRED = ("returns", "individual", "mask", "dates")
_OPTIONAL = ("macro", "variable_names", "idx", "rows", "ret_packed")

# chunked-entry layout: the stock-axis-sharded arrays vs the global ones
SHARD_ARRAYS = ("returns", "individual", "mask")
GLOBAL_ARRAYS = ("dates", "macro", "variable_names")
SHARD_DIRNAME = "shards"
ENV_SHARD_WIDTH = "DLAP_PANEL_SHARD_WIDTH"
DEFAULT_SHARD_WIDTH = 2048


def cache_enabled() -> bool:
    return os.environ.get("DLAP_PANEL_CACHE", "1") not in ("0", "false", "off")


def cache_root() -> Path:
    override = os.environ.get("DLAP_PANEL_CACHE_DIR")
    if override:
        return Path(override)
    xdg = os.environ.get("XDG_CACHE_HOME")
    base = Path(xdg) if xdg else Path.home() / ".cache"
    return base / "dlap" / "panel_cache"


def npz_fingerprint(path: Union[str, Path]) -> Dict[str, Any]:
    """Cheap content identity for one .npz: stat fields + a digest of the
    zip central directory (member names, sizes, CRC-32s) — real content
    evidence without reading any payload bytes."""
    path = Path(path)
    st = path.stat()
    h = hashlib.sha256()
    with zipfile.ZipFile(path) as z:
        for info in z.infolist():
            h.update(f"{info.filename}:{info.file_size}:{info.CRC};".encode())
    return {
        "path": str(path.resolve()),
        "size": st.st_size,
        "mtime_ns": st.st_mtime_ns,
        "header_sha": h.hexdigest(),
    }


def entry_key(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]] = None,
) -> Tuple[str, Dict[str, Any]]:
    """(cache key, the fingerprints that produced it). Any change to either
    source file — or the cache format version — changes the key."""
    fps = {
        "version": CACHE_VERSION,
        "char": npz_fingerprint(char_path),
        "macro": npz_fingerprint(macro_path) if macro_path is not None else None,
    }
    digest = hashlib.sha256(
        json.dumps(fps, sort_keys=True).encode()
    ).hexdigest()[:20]
    return digest, fps


@dataclasses.dataclass
class CacheEntry:
    """One split's decoded arrays, memmapped read-only from the cache.

    ``macro`` is RAW (un-normalized); ``idx``/``rows``/``ret_packed`` are the
    packed valid-rows representation (None when the entry's coverage was
    above the packing threshold at store time)."""

    returns: np.ndarray
    individual: np.ndarray
    mask: np.ndarray
    dates: np.ndarray
    macro: Optional[np.ndarray]
    variable_names: Optional[np.ndarray]
    idx: Optional[np.ndarray]
    rows: Optional[np.ndarray]
    ret_packed: Optional[np.ndarray]
    meta: Dict[str, Any]


def _entry_dir(key: str) -> Path:
    return cache_root() / key


def load(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]] = None,
) -> Optional[CacheEntry]:
    """Memmap a cache hit for (char_path, macro_path), or None on miss.

    Corruption of any flavor — unreadable meta, missing array file, shape
    drift against meta — deletes the entry and reports a miss so the caller
    re-decodes from the npz."""
    if not cache_enabled():
        return None
    try:
        key, _ = entry_key(char_path, macro_path)
    except (OSError, zipfile.BadZipFile):
        return None  # unreadable SOURCE: let the npz path raise its own error
    d = _entry_dir(key)
    meta_path = d / "meta.json"
    if not meta_path.exists():
        return None
    try:
        meta = json.loads(meta_path.read_text())
        if meta.get("version") != CACHE_VERSION:
            raise ValueError(f"cache version {meta.get('version')}")
        arrays: Dict[str, Optional[np.ndarray]] = {}
        for name in _REQUIRED + _OPTIONAL:
            f = d / f"{name}.npy"
            if not f.exists():
                if name in _REQUIRED or name in meta["shapes"]:
                    raise FileNotFoundError(f.name)
                arrays[name] = None
                continue
            a = np.load(f, mmap_mode="r")
            expect = meta["shapes"].get(name)
            if expect is None or tuple(a.shape) != tuple(expect):
                raise ValueError(
                    f"{name}.npy shape {a.shape} != meta {expect}"
                )
            arrays[name] = a
        return CacheEntry(meta=meta, **arrays)  # type: ignore[arg-type]
    except Exception:
        shutil.rmtree(d, ignore_errors=True)
        return None


def store(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]],
    arrays: Dict[str, Optional[np.ndarray]],
    extra_meta: Optional[Dict[str, Any]] = None,
) -> Optional[Path]:
    """Persist one split's decoded arrays; returns the entry dir (None when
    caching is disabled or the write fails — a cache must never take down a
    load that already succeeded).

    `arrays` uses the :class:`CacheEntry` field names; missing/None optional
    entries are simply not written. The write is atomic (tmp dir + rename)
    and evicts any older entry recorded for the same source char path."""
    if not cache_enabled():
        return None
    try:
        key, fps = entry_key(char_path, macro_path)
        root = cache_root()
        root.mkdir(parents=True, exist_ok=True)
        final = root / key
        if (final / "meta.json").exists():
            return final  # concurrent writer beat us; entry is complete
        shapes = {}
        tmp = Path(tempfile.mkdtemp(dir=root, prefix=f".{key}."))
        try:
            for name in _REQUIRED + _OPTIONAL:
                a = arrays.get(name)
                if a is None:
                    continue
                a = np.asarray(a)
                np.save(tmp / f"{name}.npy", a, allow_pickle=False)
                shapes[name] = list(a.shape)
            meta = {
                "version": CACHE_VERSION,
                "fingerprints": fps,
                "shapes": shapes,
                **(extra_meta or {}),
            }
            # meta.json is written LAST: its presence marks a complete entry
            (tmp / "meta.json").write_text(json.dumps(meta, indent=1))
            _evict_stale(root, fps["char"], keep=key)
            os.rename(tmp, final)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return final
    except Exception:
        return None


def _evict_stale(root: Path, char_fp: Dict[str, Any], keep: str) -> None:
    """Remove superseded entries recorded for the same source file (a
    re-generated npz would otherwise leave its old decode behind forever).

    `char_fp` is the CURRENT char fingerprint dict: an entry for the same
    path whose recorded fingerprint still matches is a live sibling (e.g. a
    chunked entry next to a monolithic one, or another shard width) and is
    kept; only entries whose recorded source fingerprint DIFFERS — a stale
    decode of a superseded file — are evicted."""
    for d in root.iterdir():
        if not d.is_dir() or d.name == keep or d.name.startswith("."):
            continue
        try:
            meta = json.loads((d / "meta.json").read_text())
            recorded = meta["fingerprints"]["char"]
            if recorded["path"] == char_fp["path"] and recorded != char_fp:
                shutil.rmtree(d, ignore_errors=True)
        except Exception:
            continue  # unreadable sibling: not ours to judge


# --------------------------------------------------------------------------
# chunked entries: the stock axis cut into fixed-width, verified shards
# --------------------------------------------------------------------------

def shard_width(override: Optional[int] = None) -> int:
    """The stock-shard width: explicit override > $DLAP_PANEL_SHARD_WIDTH >
    DEFAULT_SHARD_WIDTH. Part of the chunked cache key — changing it can
    never mis-slice an existing entry, it just misses to a fresh one."""
    if override is not None:
        return int(override)
    env = os.environ.get(ENV_SHARD_WIDTH, "").strip()
    return int(env) if env else DEFAULT_SHARD_WIDTH


def shard_bounds(n: int, width: int) -> List[Tuple[int, int]]:
    """Fixed-width [start, stop) column spans covering the stock axis; the
    last shard is ragged when `width` does not divide N."""
    width = max(1, int(width))
    return [(a, min(a + width, n)) for a in range(0, max(n, 1), width)]


def chunked_entry_key(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]] = None,
    width: Optional[int] = None,
) -> Tuple[str, Dict[str, Any]]:
    """Like :func:`entry_key` but for the chunked layout: the digest also
    covers the shard width, so monolithic / differently-sharded entries for
    the same source never collide."""
    fps = {
        "version": CACHE_VERSION,
        "kind": "chunked",
        "shard_width": shard_width(width),
        "char": npz_fingerprint(char_path),
        "macro": npz_fingerprint(macro_path) if macro_path is not None else None,
    }
    digest = hashlib.sha256(
        json.dumps(fps, sort_keys=True).encode()
    ).hexdigest()[:20]
    return digest, fps


def _npy_bytes(a: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return buf.getvalue()


_SINGLE_SHOT_DIGEST_BYTES = 1 << 28  # 256 MiB


def _file_sha256(path: Path, blocksize: int = 1 << 25) -> str:
    """File digest. Normal shards (≲20 MB at the default width) hash in
    ONE read + one hashlib call — the block-looped path runs at roughly
    half the hash throughput (Python-loop overhead on the read side) and
    the verify pass is on the shard-local load's critical path. Only
    oversized files fall back to streaming so the heap never holds more
    than `blocksize` of a pathological multi-GB shard."""
    try:
        if path.stat().st_size <= _SINGLE_SHOT_DIGEST_BYTES:
            return hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError:
        pass  # stat raced a writer: the streamed path reports it
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(blocksize)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


@dataclasses.dataclass
class ChunkedEntry:
    """A chunked cache entry: the manifest plus lazy per-shard access.

    Shards are loaded individually (memmapped) after a per-file fingerprint
    check against the manifest, so a consumer touches ONLY the stock spans
    it owns — corruption anywhere else is invisible to it."""

    dir: Path
    meta: Dict[str, Any]

    @property
    def width(self) -> int:
        return int(self.meta["shard_width"])

    @property
    def n_shards(self) -> int:
        return int(self.meta["n_shards"])

    @property
    def n_stocks(self) -> int:
        return int(self.meta["shapes"]["returns"][1])

    def bounds(self) -> List[Tuple[int, int]]:
        return [tuple(s["cols"]) for s in self.meta["shards"]]

    def shards_for(
        self, columns: Optional[Tuple[int, int]] = None
    ) -> List[int]:
        """Indices of the shards intersecting [a, b) (all when None)."""
        if columns is None:
            return list(range(self.n_shards))
        a, b = columns
        return [i for i, (lo, hi) in enumerate(self.bounds())
                if hi > a and lo < b]

    def shard_path(self, i: int, name: str) -> Path:
        return self.dir / SHARD_DIRNAME / f"s{i:05d}.{name}.npy"

    def verify_shard(self, i: int) -> Tuple[bool, str]:
        """Check every file of shard `i` against the manifest's recorded
        size and sha256 (streamed). (ok, reason)."""
        rec = self.meta["shards"][i]["files"]
        for name in SHARD_ARRAYS:
            p = self.shard_path(i, name)
            want = rec[name]
            try:
                size = p.stat().st_size
            except OSError:
                return False, f"{p.name}: missing"
            if size != int(want["bytes"]):
                return False, (f"{p.name}: {size} bytes on disk, "
                               f"{want['bytes']} recorded")
            got = _file_sha256(p)
            if got != want["sha256"]:
                return False, (f"{p.name}: sha256 {got[:12]}… != recorded "
                               f"{want['sha256'][:12]}…")
        return True, "ok"

    def load_shard(self, i: int) -> Dict[str, np.ndarray]:
        """Memmap one verified shard's arrays (verify first — this does not
        re-check)."""
        return {
            name: np.load(self.shard_path(i, name), mmap_mode="r")
            for name in SHARD_ARRAYS
        }

    def load_global(self, name: str) -> Optional[np.ndarray]:
        rec = (self.meta.get("globals") or {}).get(name)
        if rec is None:
            return None
        p = self.dir / f"{name}.npy"
        data = p.read_bytes()
        if compute_digest(data) != rec["sha256"]:
            raise ValueError(f"{p.name}: sha256 mismatch vs manifest")
        return np.load(io.BytesIO(data), allow_pickle=False)

    def restore_shard(self, i: int, arrays: Dict[str, np.ndarray]) -> bool:
        """Re-store one shard from freshly re-decoded arrays. The manifest
        is the identity: the rewritten bytes must reproduce the recorded
        digests exactly (same source npz → same decode → same .npy bytes);
        a mismatch means the entry no longer matches its source and the
        caller should invalidate it. Returns True on a verified repair."""
        a, b = self.meta["shards"][i]["cols"]
        rec = self.meta["shards"][i]["files"]
        for name in SHARD_ARRAYS:
            arr = arrays[name]
            data = _npy_bytes(arr[:, a:b])
            if compute_digest(data) != rec[name]["sha256"]:
                return False
            write_verified(self.shard_path(i, name), data)
        return True


def store_chunked(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]],
    arrays: Dict[str, Optional[np.ndarray]],
    width: Optional[int] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
) -> Optional[Path]:
    """Persist one split's decoded arrays as a chunked entry (see module
    docstring layout). `arrays` uses the same names as :func:`store`:
    returns/individual/mask are sharded along the stock axis, dates/macro/
    variable_names stay global. Atomic at entry level (tmp dir + rename,
    manifest written last) AND per file (``reliability.verified``); returns
    the entry dir, or None when caching is disabled or the write fails."""
    if not cache_enabled():
        return None
    try:
        w = shard_width(width)
        key, fps = chunked_entry_key(char_path, macro_path, w)
        root = cache_root()
        root.mkdir(parents=True, exist_ok=True)
        final = root / key
        if (final / "meta.json").exists():
            return final  # concurrent writer beat us; entry is complete
        returns = np.asarray(arrays["returns"])
        n = returns.shape[1]
        bounds = shard_bounds(n, w)
        tmp = Path(tempfile.mkdtemp(dir=root, prefix=f".{key}."))
        try:
            (tmp / SHARD_DIRNAME).mkdir()
            shards_meta = []
            for i, (a, b) in enumerate(bounds):
                files = {}
                for name in SHARD_ARRAYS:
                    arr = np.asarray(arrays[name])
                    data = _npy_bytes(arr[:, a:b])
                    sha = write_verified(
                        tmp / SHARD_DIRNAME / f"s{i:05d}.{name}.npy", data
                    )
                    files[name] = {"sha256": sha, "bytes": len(data)}
                shards_meta.append({"cols": [a, b], "files": files})
            globals_meta = {}
            shapes = {
                name: list(np.asarray(arrays[name]).shape)
                for name in SHARD_ARRAYS
            }
            for name in GLOBAL_ARRAYS:
                a = arrays.get(name)
                if a is None:
                    continue
                data = _npy_bytes(np.asarray(a))
                sha = write_verified(tmp / f"{name}.npy", data)
                globals_meta[name] = {"sha256": sha, "bytes": len(data)}
                shapes[name] = list(np.asarray(a).shape)
            meta = {
                "version": CACHE_VERSION,
                "kind": "chunked",
                "shard_width": w,
                "n_shards": len(bounds),
                "fingerprints": fps,
                "shapes": shapes,
                "shards": shards_meta,
                "globals": globals_meta,
                **(extra_meta or {}),
            }
            # manifest LAST: its presence marks a complete entry
            write_verified(
                tmp / "meta.json",
                json.dumps(meta, indent=1).encode(),
            )
            _evict_stale(root, fps["char"], keep=key)
            os.rename(tmp, final)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return final
    except Exception:
        return None


def load_chunked(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]] = None,
    width: Optional[int] = None,
) -> Optional[ChunkedEntry]:
    """Open a chunked entry for (char, macro) at this shard width, or None
    on miss. Only the MANIFEST is read and verified here; shards verify
    individually via :meth:`ChunkedEntry.verify_shard` when loaded, so a
    corrupt shard a consumer never touches costs nothing. An unreadable or
    corrupt manifest deletes the entry and reports a miss."""
    if not cache_enabled():
        return None
    try:
        key, _ = chunked_entry_key(char_path, macro_path, width)
    except (OSError, zipfile.BadZipFile):
        return None  # unreadable SOURCE: let the npz path raise its own error
    d = _entry_dir(key)
    if not (d / "meta.json").exists():
        return None
    try:
        meta, _ = load_verified(
            d / "meta.json",
            parse=lambda data: json.loads(data.decode()),
            warn=False,
        )
        if meta.get("version") != CACHE_VERSION or meta.get("kind") != "chunked":
            raise ValueError(f"not a chunked v{CACHE_VERSION} entry")
        if len(meta["shards"]) != int(meta["n_shards"]):
            raise ValueError("manifest shard count mismatch")
        return ChunkedEntry(dir=d, meta=meta)
    except Exception:
        shutil.rmtree(d, ignore_errors=True)
        return None


def clear() -> int:
    """Delete every cache entry; returns the number removed."""
    root = cache_root()
    if not root.is_dir():
        return 0
    n = 0
    for d in root.iterdir():
        if d.is_dir():
            shutil.rmtree(d, ignore_errors=True)
            n += 1
    return n


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m deeplearninginassetpricing_paperreplication_tpu."
             "data.diskcache",
        description="Inspect or clear the decoded-panel disk cache",
    )
    p.add_argument("--clear", action="store_true", help="delete all entries")
    args = p.parse_args(argv)
    root = cache_root()
    if args.clear:
        print(f"removed {clear()} entries from {root}")
        return 0
    entries = sorted(d for d in root.iterdir() if d.is_dir()) if root.is_dir() else []
    total = 0
    for d in entries:
        size = sum(f.stat().st_size for f in d.iterdir() if f.is_file())
        total += size
        src = "?"
        try:
            meta = json.loads((d / "meta.json").read_text())
            src = meta["fingerprints"]["char"]["path"]
        except Exception:
            pass
        print(f"  {d.name}  {size / (1 << 20):8.1f} MiB  {src}")
    print(f"{len(entries)} entries, {total / (1 << 20):.1f} MiB in {root}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
