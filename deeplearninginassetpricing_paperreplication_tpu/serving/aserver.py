"""Asyncio HTTP/1.1 front end: the production-concurrency serving path.

One event loop accepts connections, parses requests, and awaits the
:class:`~.batcher.ContinuousBatcher` — no thread per request, no GIL
convoy of handler threads contending on one dispatcher (the measured
failure mode of the deprecated ``ThreadingHTTPServer`` path: throughput
*dropped* from c1 to c4, BENCH_SERVING.json). Connections are keep-alive
(HTTP/1.1 default), so a steady client pays connection setup once, and the
listener can bind with ``SO_REUSEPORT`` so R replica processes share one
port — the kernel spreads new connections across live listeners, and a
dead replica's connections fail fast onto the survivors (clients retry; see
``loadgen``).

The HTTP surface is deliberately minimal (request line + headers +
Content-Length JSON bodies — what the serving API needs), stdlib-only, and
instrumented: the ``serve/accept`` fault site fires per accepted
connection and ``serve/replica_kill`` per request with the replica label as
its path context, so a fault plan can kill one targeted replica mid-flight
under load (the tier-1 fleet fault matrix).
"""

from __future__ import annotations

import asyncio
import json
import socket
import time
from typing import Optional, Tuple

from ..observability.tracecontext import TraceContext
from ..reliability.faults import inject
from .server import (
    BINARY_CONTENT_TYPE,
    DEADLINE_HEADER,
    PRIORITY_HEADER,
    ServingService,
)

MAX_BODY_BYTES = 64 * 1024 * 1024  # one month of a ~10k-stock panel is ~5 MB
MAX_HEADER_LINES = 64


def pick_free_port(host: str = "127.0.0.1") -> int:
    """A currently-free TCP port (bind-0 probe). Racy by nature — callers
    use it to pre-agree a port for an SO_REUSEPORT replica fleet, where
    port 0 would scatter the replicas across different ephemeral ports."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


async def _read_request(reader) -> Optional[Tuple[str, str, dict, bytes]]:
    """(method, path, headers, body) or None on clean EOF / bad preamble."""
    line = await reader.readline()
    if not line:
        return None
    try:
        method, path, _version = line.decode("latin-1").split(None, 2)
    except ValueError:
        return None
    headers = {}
    for _ in range(MAX_HEADER_LINES):
        h = await reader.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        name, _, value = h.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    else:
        return None  # header section never ended: drop, don't desync
    try:
        length = int(headers.get("content-length") or 0)
    except ValueError:
        return None  # garbage Content-Length: malformed preamble
    if not 0 <= length <= MAX_BODY_BYTES:
        return None
    body = await reader.readexactly(length) if length else b""
    return method, path, headers, body


async def _handle_conn(service: ServingService, reader, writer,
                       admin: bool = False) -> None:
    inject("serve/accept", path=service.replica_label or "")
    rec: dict = {}
    try:
        while True:
            rec = {}
            req = await _read_request(reader)
            if req is None:
                break
            method, path, headers, body = req
            # fault site: kills/hangs THIS replica with a request (and
            # typically a whole flush) in the air; matched by replica
            # label so a plan can target one member of the fleet
            inject("serve/replica_kill", path=service.replica_label or "")
            # request-scoped trace context: continue the client's
            # traceparent (retries reuse one trace id) or mint a fresh
            # edge context; malformed headers fall back, never 500
            trace = TraceContext.from_header(headers.get("traceparent"))
            # admission contract headers (server.priority_for /
            # deadline_from_header resolve them; absent → path defaults)
            priority = headers.get(PRIORITY_HEADER)
            deadline_ms = headers.get(DEADLINE_HEADER)
            serialize_s = 0.0
            ctype = b"application/json"
            if (headers.get("content-type") == BINARY_CONTENT_TYPE
                    and method == "POST"
                    and path.split("?", 1)[0].rstrip("/") == "/v1/weights"):
                # raw-f32 hot wire: no JSON anywhere on the path
                status, data = await service.handle_binary_async(
                    body, trace=trace, rec=rec, priority=priority,
                    deadline_ms=deadline_ms)
                if status == 200:
                    ctype = BINARY_CONTENT_TYPE.encode()
                else:
                    ctype = b"text/plain"
            else:
                t_parse = time.monotonic()
                payload, parse_error = None, False
                if body:
                    try:
                        payload = json.loads(body)
                    except json.JSONDecodeError:
                        parse_error = True
                pre_parse_s = time.monotonic() - t_parse
                if parse_error:
                    status, resp = 400, {
                        "error": "request body is not valid JSON"}
                else:
                    rec["pre_parse_s"] = pre_parse_s
                    status, resp = await service.handle_async(
                        method, path, payload, raw_body=body or None,
                        trace=trace, rec=rec, admin=admin,
                        priority=priority, deadline_ms=deadline_ms)
                t_ser = time.monotonic()
                if isinstance(resp, dict) and "_raw_text" in resp:
                    # non-JSON response (Prometheus text exposition)
                    data = resp["_raw_text"].encode()
                    ctype = resp.get(
                        "_content_type", "text/plain").encode()
                else:
                    if isinstance(resp, dict):
                        resp.pop("_retry_after", None)
                    data = json.dumps(resp).encode()
                serialize_s = time.monotonic() - t_ser
            keep = headers.get("connection", "").lower() != "close"
            # shed/overload responses carry the Retry-After the admission
            # layer computed (rec["retry_after"]: whole seconds)
            retry_after = rec.get("retry_after")
            extra_hdr = (b"Retry-After: %d\r\n" % int(retry_after)
                         if retry_after is not None else b"")
            t_write = time.monotonic()
            writer.write(
                b"HTTP/1.1 %d %s\r\n"
                b"Content-Type: %s\r\n"
                b"Content-Length: %d\r\n"
                % (status, _REASONS.get(status, b"OK"), ctype, len(data))
                + extra_hdr
                + b"Connection: %s\r\n\r\n"
                % (b"keep-alive" if keep else b"close")
                + data)
            await writer.drain()
            if "status" in rec:
                # the deferred request-row emission: the transport's
                # serialize + socket-write segments land on the same row
                # the service filled (parse/queue/dispatch)
                service.emit_request(
                    rec, serialize_s=serialize_s,
                    write_s=time.monotonic() - t_write)
            if not keep:
                break
    except (ConnectionError, asyncio.IncompleteReadError,
            asyncio.TimeoutError):
        pass  # client went away mid-request; nothing to answer
    except Exception:
        # malformed preamble / transport surprise: drop THIS connection
        # quietly — an unhandled task exception answers nobody and spams
        # the loop's exception handler
        pass
    finally:
        # a connection dropped mid-request must not leak its in-flight
        # flight-recorder entry (the dump would name it forever)
        if rec.get("token") is not None and not rec.get("_finished"):
            service.abort_request(rec)
        try:
            writer.close()
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass


_REASONS = {
    200: b"OK", 400: b"Bad Request", 404: b"Not Found",
    405: b"Method Not Allowed", 409: b"Conflict",
    429: b"Too Many Requests",
    500: b"Internal Server Error", 501: b"Not Implemented",
    503: b"Service Unavailable",
}


async def serve_async(
    service: ServingService,
    host: str = "127.0.0.1",
    port: int = 0,
    reuse_port: bool = False,
    ready: Optional[asyncio.Event] = None,
    port_out: Optional[list] = None,
    admin_port: Optional[int] = None,
    admin_port_out: Optional[list] = None,
):
    """Run the asyncio server until cancelled. ``port_out`` (a list)
    receives the bound port; ``ready`` is set once accepting.

    ``admin_port``: also bind the SAME handler on a private 127.0.0.1
    port (never ``SO_REUSEPORT``-shared). In a replica fleet every
    replica shares the serving port — the kernel picks who answers — so
    the rolling-update path needs a per-replica address to target ONE
    replica's ``/v1/reload`` and health-gate ITS ``/metrics``."""
    service.start_async()
    server = await asyncio.start_server(
        lambda r, w: _handle_conn(service, r, w),
        host=host, port=port, reuse_port=reuse_port)
    bound = server.sockets[0].getsockname()[1]
    loop = asyncio.get_running_loop()

    def _close_public():
        try:
            server.close()
        except Exception:
            pass  # already closing / loop shutting down

    # graceful-drain hook (admin /v1/drain, autoscaler scale-down): close
    # the public listener SHORTLY AFTER the drain response is written —
    # the kernel stops routing new SO_REUSEPORT connections here, and
    # close() cancels serve_forever, whose unwind drains the continuous
    # batcher (aclose) and exits the process CLEANLY (rc 0: the
    # supervisor records success instead of restarting the replica)
    service._drain_hook = lambda: loop.call_soon_threadsafe(
        loop.call_later, 0.5, _close_public)
    admin_server = None
    if admin_port is not None:
        # admin connections unlock the /v1/debug/* surface (profiler
        # capture, flight-recorder dump) — private loopback port only
        admin_server = await asyncio.start_server(
            lambda r, w: _handle_conn(service, r, w, admin=True),
            host="127.0.0.1", port=admin_port)
        admin_bound = admin_server.sockets[0].getsockname()[1]
        if admin_port_out is not None:
            admin_port_out.append(admin_bound)
        print(f"admin endpoint on http://127.0.0.1:{admin_bound}"
              + (f" ({service.replica_label})" if service.replica_label
                 else ""), flush=True)
    if port_out is not None:
        port_out.append(bound)
    if ready is not None:
        ready.set()
    service.accepting = True
    if service.heartbeat is not None:
        service.heartbeat.beat("serve/accepting")
    print(f"serving {service.engine.n_members} members on "
          f"http://{host}:{bound} (async"
          + (f", {service.replica_label}" if service.replica_label else "")
          + f", config {service.engine.config_hash[:12]})", flush=True)
    async with server:
        try:
            await server.serve_forever()
        finally:
            if admin_server is not None:
                admin_server.close()
            if service.cbatcher is not None:
                await service.cbatcher.aclose()


def run_async_server(service: ServingService, host: str = "127.0.0.1",
                     port: int = 0, reuse_port: bool = False,
                     admin_port: Optional[int] = None) -> None:
    """Blocking entry: own event loop, runs until KeyboardInterrupt."""
    try:
        asyncio.run(serve_async(service, host, port, reuse_port=reuse_port,
                                admin_port=admin_port))
    except asyncio.CancelledError:
        pass


class AsyncServerThread:
    """Test/bench harness: the async server on a background thread.

    ``start()`` blocks until the socket accepts and returns the bound
    port; ``stop()`` cancels the loop and joins the thread.
    """

    def __init__(self, service: ServingService, host: str = "127.0.0.1",
                 port: int = 0, reuse_port: bool = False):
        self.service = service
        self.host, self.port = host, port
        self.reuse_port = reuse_port
        self._loop = None
        self._thread = None
        self._task = None

    def start(self, timeout: float = 30.0) -> int:
        import threading

        started = threading.Event()
        port_out: list = []

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            ready = asyncio.Event()

            async def body():
                self._task = asyncio.current_task()
                await serve_async(self.service, self.host, self.port,
                                  reuse_port=self.reuse_port, ready=ready,
                                  port_out=port_out)

            async def waiter():
                t = self._loop.create_task(body())
                await ready.wait()
                started.set()
                try:
                    await t
                except asyncio.CancelledError:
                    pass

            try:
                self._loop.run_until_complete(waiter())
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serving-async")
        self._thread.start()
        if not started.wait(timeout):
            raise RuntimeError("async server failed to start")
        self.port = port_out[0]
        return self.port

    def stop(self, timeout: float = 10.0) -> None:
        if self._loop is not None and self._task is not None:
            self._loop.call_soon_threadsafe(self._task.cancel)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
