"""The unified partition-rule sharding layer (parallel/partition.py) and the
mesh-packed sweep it powers:

  * rule matching: precedence (first match wins), scalar skip, the
    no-match error NAMING the leaf path, rank clipping;
  * mesh construction: MeshConfig validation, device slices (disjoint,
    the worker lease contract), degenerate 1-device meshes;
  * BIT-IDENTITY mesh-on vs mesh-off: a sweep bucket's (lr × seed) grid
    sharded over a 4-device ('grid',) slice — inline AND AOT-warmed
    dispatch — reproduces the unsharded run bit for bit (per-grid-point
    math has no cross-member collectives; the stock-axis GSPMD route, by
    contrast, psums over sharded N and keeps its documented seed-era
    tolerances in test_parallel/test_losses);
  * the serving engine's degenerate-mesh placement serves bit-identically
    to the offline ensemble math;
  * bf16 wire on the SHARDED transfer route (the lifted PR-7 hold-off):
    per-shard bf16 ≡ the single-device bf16 wire, and the checked-in
    PARITY_BF16.json contract still holds;
  * scheduler device-slice leases: disjoint claims, self-reclaim,
    expiry takeover, renew-after-takeover raising LeaseLost;
  * one in-process mesh-packed worker draining a device-sliced queue
    with warmed programs and a ranking identical to the in-process sweep;
  * the ruff lint gate over the new/changed modules and the BENCH_MESH
    artifact bars (its budgets ride the shipped-budgets tier-1 gate in
    test_telemetry).
"""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearninginassetpricing_paperreplication_tpu.parallel import partition
from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
    GANConfig,
    TrainConfig,
)

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "deeplearninginassetpricing_paperreplication_tpu"


# --------------------------------------------------------------------------
# rule matching
# --------------------------------------------------------------------------


def test_rule_precedence_first_match_wins():
    tree = {"sdf_net": {"kernel": jnp.ones((8, 3)), "bias": jnp.ones((8,))}}
    specs = partition.match_partition_rules(
        [(r"kernel", P("grid")), (r".*", P())], tree)
    assert specs["sdf_net"]["kernel"] == P("grid")
    assert specs["sdf_net"]["bias"] == P()
    # reversed order: the catch-all shadows the kernel rule entirely
    specs = partition.match_partition_rules(
        [(r".*", P()), (r"kernel", P("grid"))], tree)
    assert specs["sdf_net"]["kernel"] == P()


def test_rule_matching_skips_scalars_without_consulting_rules():
    tree = {"n_assets": jnp.float32(7.0), "one": jnp.ones((1,)),
            "vec": jnp.ones((4,))}
    # the only rule would SHARD everything — scalars (0-d and single-
    # element) must come back replicated anyway
    specs = partition.match_partition_rules([(r".*", P("grid"))], tree)
    assert specs["n_assets"] == P()
    assert specs["one"] == P()
    assert specs["vec"] == P("grid")


def test_rule_no_match_error_names_the_leaf_path():
    tree = {"outer": {"mystery_leaf": jnp.ones((4, 2))}}
    with pytest.raises(ValueError, match="outer/mystery_leaf"):
        partition.match_partition_rules([(r"^only_this$", P("grid"))], tree)


def test_tree_shardings_clips_specs_beyond_leaf_rank():
    mesh = partition.create_mesh(8)
    # returns-family rule is rank-2; a rank-1 leaf with trailing None
    # entries clips, but one naming a mesh axis past the rank is an error
    sh = partition.tree_shardings(
        mesh, {"x": jnp.ones((4,))}, [(r".*", P(None, None))])
    assert sh["x"].spec == P(None)  # clipped to the leaf's rank, replicated
    with pytest.raises(ValueError, match="beyond the leaf's rank"):
        partition.tree_shardings(
            mesh, {"x": jnp.ones((4,))}, [(r".*", P(None, "stocks"))])


def test_batch_shardings_layout_matches_contract():
    mesh = partition.create_mesh(8)
    sh = partition.batch_shardings(mesh)
    assert sh["returns"].spec == P(None, "stocks")
    assert sh["mask"].spec == P(None, "stocks")
    assert sh["individual"].spec == P(None, "stocks", None)
    assert sh["individual_t"].spec == P(None, None, "stocks")
    assert sh["macro"].spec == P()
    assert sh["n_assets"].spec == P()


def test_stack_tree_shardings_naive_fallback():
    """A leaf whose leading dim the stack axis does not divide replicates
    (SNIPPETS.md [3] naive sharding) — layout changes, values never do."""
    mesh = partition.grid_slice_mesh(0, 2)  # 4 devices
    sh = partition.stack_tree_shardings(
        mesh, {"ok": jnp.ones((8, 2)), "ragged": jnp.ones((6, 2)),
               "scalar": jnp.float32(1.0)})
    assert sh["ok"].spec == P("grid")
    assert sh["ragged"].spec == P()
    assert sh["scalar"].spec == P()


# --------------------------------------------------------------------------
# mesh construction + device slices
# --------------------------------------------------------------------------


def test_mesh_config_builds_and_validates():
    m = partition.MeshConfig((("grid", 2), ("stocks", 4))).build()
    assert m.shape == {"grid": 2, "stocks": 4}
    m = partition.MeshConfig((("members", 2), ("stocks", -1))).build()
    assert m.shape["members"] == 2 and m.shape["stocks"] == 4
    with pytest.raises(ValueError, match="at most one -1"):
        partition.MeshConfig((("a", -1), ("b", -1))).build()
    with pytest.raises(ValueError, match="needs 16 devices"):
        partition.MeshConfig((("grid", 16),)).build()


def test_device_slices_are_disjoint_and_validated():
    s0 = partition.slice_devices(0, 2)
    s1 = partition.slice_devices(1, 2)
    assert len(s0) == len(s1) == 4
    assert not set(d.id for d in s0) & set(d.id for d in s1)
    with pytest.raises(ValueError, match="not in"):
        partition.slice_devices(2, 2)
    with pytest.raises(ValueError, match="exceed"):
        partition.slice_devices(0, 2, width=8)
    mesh = partition.grid_slice_mesh(1, 2)
    assert [d.id for d in mesh.devices.ravel()] == [d.id for d in s1]


def test_device_sharding_is_degenerate_mesh_and_dispatch_equivalent():
    """The old SingleDeviceSharding call sites now get a 1-device mesh:
    programs lowered from one accept arrays committed with the other."""
    sh = partition.device_sharding()
    assert dict(sh.mesh.shape) == {"stocks": 1}
    assert sh.spec == P()
    struct = jax.ShapeDtypeStruct((4,), jnp.float32, sharding=sh)
    compiled = jax.jit(lambda x: x * 2).lower(struct).compile()
    out = compiled(jax.device_put(np.ones(4, np.float32)))  # plain placement
    np.testing.assert_array_equal(np.asarray(out), 2.0)


def test_member_sharding_resolves_stack_axis():
    assert partition.member_sharding(
        partition.create_2d_mesh(2, 4)).spec == P("batch")
    assert partition.member_sharding(
        partition.grid_slice_mesh(0, 2)).spec == P("grid")
    with pytest.raises(ValueError, match="no member-ish axis"):
        partition.member_sharding(partition.create_mesh(8))


# --------------------------------------------------------------------------
# mesh-on vs mesh-off bit-identity (the tier-1 acceptance criterion)
# --------------------------------------------------------------------------


def _tiny_batch(T=12, N=64, F=6, M=3, seed=2):
    rng = np.random.default_rng(seed)
    mask = (rng.random((T, N)) > 0.3).astype(np.float32)
    return {
        "individual": jnp.asarray(
            (rng.standard_normal((T, N, F)) * mask[:, :, None]
             ).astype(np.float32)),
        "returns": jnp.asarray(
            (rng.standard_normal((T, N)) * 0.05 * mask).astype(np.float32)),
        "mask": jnp.asarray(mask),
        "macro": jnp.asarray(rng.standard_normal((T, M)).astype(np.float32)),
    }


def test_sweep_bucket_mesh_on_off_bit_identical():
    """THE bit-identity bar: one architecture bucket's (lr × seed) grid
    sharded over a 4-device ('grid',) slice — inline-compiled AND
    dispatching AOT-warmed executables — must reproduce the unsharded
    bucket BIT FOR BIT (per-grid-point math has no cross-member
    collectives, so the partition only changes placement)."""
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        train_bucket,
        warm_bucket_programs,
    )

    batch = _tiny_batch()
    cfg = GANConfig(macro_feature_dim=3, individual_feature_dim=6,
                    hidden_dim=(8,), dropout=0.0)
    tcfg = TrainConfig(num_epochs_unc=4, num_epochs_moment=2, num_epochs=6,
                       ignore_epoch=0)
    kw = dict(lrs=[1e-3, 5e-4], seeds=[42, 7, 11, 22], train_batch=batch,
              valid_batch=batch, tcfg=tcfg)
    mesh = partition.grid_slice_mesh(0, 2)  # 4 devices, grid width 8

    off = train_bucket(cfg, **kw)
    on = train_bucket(cfg, **kw, grid_mesh=mesh)
    np.testing.assert_array_equal(off["best_valid_sharpe"],
                                  on["best_valid_sharpe"])
    for a, b in zip(jax.tree.leaves(off["params"]),
                    jax.tree.leaves(on["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    progs = warm_bucket_programs(cfg, kw["lrs"], kw["seeds"], batch, batch,
                                 tcfg, grid_mesh=mesh)
    assert set(progs) == {("unconditional", 4), ("moment", 2),
                          ("conditional", 6)}
    warm = train_bucket(cfg, **kw, programs=progs, grid_mesh=mesh)
    np.testing.assert_array_equal(off["best_valid_sharpe"],
                                  warm["best_valid_sharpe"])
    for a, b in zip(jax.tree.leaves(off["params"]),
                    jax.tree.leaves(warm["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_serving_engine_partition_placement_bit_identical(tmp_path, splits):
    """The serve leg of the mesh-on/off criterion: the engine (now placed
    by partition.device_sharding — the degenerate mesh) must serve the
    paper-protocol weights bit-identically to the offline ensemble math."""
    from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
    from deeplearninginassetpricing_paperreplication_tpu.parallel.ensemble import (
        member_weights,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.engine import (
        InferenceEngine,
        InferenceRequest,
    )
    from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
        save_params,
    )

    train_ds, _valid, test_ds = splits
    cfg = GANConfig(macro_feature_dim=train_ds.macro_feature_dim,
                    individual_feature_dim=train_ds.individual_feature_dim,
                    hidden_dim=(8,), num_units_rnn=(3,),
                    num_condition_moment=4)
    gan = GAN(cfg)
    dirs = []
    for i, seed in enumerate((5, 6)):
        d = tmp_path / f"m{i}"
        d.mkdir()
        cfg.save(d / "config.json")
        save_params(d / "best_model_sharpe.msgpack",
                    gan.init(jax.random.key(seed)))
        dirs.append(str(d))

    macro = np.asarray(train_ds.macro, np.float32)
    eng = InferenceEngine(dirs, macro_history=macro,
                          stock_buckets=(64,), batch_buckets=(1,))
    assert dict(eng._sharding.mesh.shape) == {"stocks": 1}

    month = 3
    batch = {k: jnp.asarray(v) for k, v in train_ds.full_batch().items()}
    vparams = jax.vmap(lambda k: gan.init(k))(
        jnp.stack([jax.random.key(s) for s in (5, 6)]))
    w_ref = np.asarray(member_weights(gan, vparams, batch))[:, month, :]
    avg = w_ref.mean(axis=0)
    mask = np.asarray(batch["mask"])[month]
    s = np.abs(avg * mask).sum()
    if s > 1e-8:
        avg = avg / s
    res = eng.infer([InferenceRequest(
        individual=np.asarray(batch["individual"])[month],
        mask=mask, month=month)])[0]
    np.testing.assert_array_equal(
        res.weights.astype(np.float32), (avg * mask).astype(np.float32))


def test_train_step_mesh_on_off(splits):
    """The train leg of the mesh-on/off criterion, tier-1-fast: one full
    conditional train step with the panel stock-sharded over the 8-device
    mesh (partition.shard_batch + replicated params) vs unsharded. The
    sharded BATCH ARRAYS are bit-identical to the host values
    (placement-only); the step's outputs agree to the stock-GSPMD
    tolerance documented since seed (the masked cross-sectional sums
    become psums whose reduction order differs from the serial sum — the
    ONE mesh surface where bit-identity is physically off the table; the
    grid/member axes above have no cross-device reductions and are
    asserted exact)."""
    from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
    from deeplearninginassetpricing_paperreplication_tpu.training.steps import (
        make_optimizer,
        make_train_step,
    )

    train_ds = splits[0]
    batch = {k: jnp.asarray(v) for k, v in train_ds.full_batch().items()}
    cfg = GANConfig(macro_feature_dim=train_ds.macro_feature_dim,
                    individual_feature_dim=train_ds.individual_feature_dim,
                    hidden_dim=(8,), num_units_rnn=(3,),
                    num_condition_moment=4, dropout=0.0)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(0))
    tx = make_optimizer(1e-3)
    step = make_train_step(gan, "conditional", tx)
    opt = tx.init(params["sdf_net"])
    ref_p, _, ref_m = jax.jit(step)(params, opt, batch, jax.random.key(5))

    mesh = partition.create_mesh(8)
    sharded = partition.shard_batch(batch, mesh)
    for k in batch:  # placement only: the sharded bytes ARE the host bytes
        np.testing.assert_array_equal(np.asarray(sharded[k]),
                                      np.asarray(batch[k]))
    p_r = jax.device_put(params, partition.replicated(mesh))
    opt_r = jax.device_put(opt, partition.replicated(mesh))
    sh_p, _, sh_m = jax.jit(step)(p_r, opt_r, sharded, jax.random.key(5))
    np.testing.assert_allclose(float(sh_m["loss"]), float(ref_m["loss"]),
                               rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(sh_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


# --------------------------------------------------------------------------
# bf16 wire on the sharded route (PR-7 hold-off lifted)
# --------------------------------------------------------------------------


def test_sharded_bf16_wire_matches_single_device_wire(splits):
    """stream_batch_sharded(bf16_wire=True): each shard's `individual`
    span ships bfloat16 and upcasts in place — the assembled panel must be
    BIT-identical to the single-device bf16 wire (casting is elementwise,
    so per-shard ≡ whole-panel), and every other field must match the f32
    sharded route exactly."""
    from deeplearninginassetpricing_paperreplication_tpu.data.pipeline import (
        stream_batch_sharded,
    )
    from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
        device_put_batch,
    )

    train_ds = splits[0].pad_stocks(8)
    batch = train_ds.full_batch()
    mesh = partition.create_mesh(8)
    ref = device_put_batch(batch, bf16_wire=True, packed=False)
    got = stream_batch_sharded(batch, mesh, bf16_wire=True)
    assert got["individual"].dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(got["individual"]),
                                  np.asarray(ref["individual"]))
    f32 = stream_batch_sharded(batch, mesh)
    for k in ("returns", "mask", "macro"):
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(f32[k]))
        assert got[k].sharding == f32[k].sharding, k
    assert got["individual"].sharding.spec == P(None, "stocks", None)


def test_parity_bf16_artifact_contract_holds():
    """The checked-in PARITY_BF16.json the sharded wire is anchored to:
    the bf16 execution route's end-to-end Sharpe deltas stayed inside the
    tolerance and the artifact says pass — lifting the wire onto the
    sharded route rides THIS evidence, so the test locks it."""
    parity = json.loads((REPO / "PARITY_BF16.json").read_text())
    assert parity["pass"] is True
    tol = float(parity["tolerance"])
    assert float(parity["abs_delta_sharpe"]["valid"]) <= tol
    assert float(parity["abs_delta_sharpe"]["test"]) <= tol


# --------------------------------------------------------------------------
# scheduler device-slice leases
# --------------------------------------------------------------------------


def _slice_queue(tmp_path, **kw):
    from deeplearninginassetpricing_paperreplication_tpu.reliability.scheduler import (
        WorkQueue,
    )
    from deeplearninginassetpricing_paperreplication_tpu.reliability.supervisor import (
        RestartPolicy,
    )

    kw.setdefault("lease_timeout_s", 30.0)
    kw.setdefault("backoff", RestartPolicy(backoff_base_s=0.0,
                                           backoff_max_s=0.0,
                                           jitter_frac=0.0))
    return WorkQueue(tmp_path, **kw)


def test_device_slice_leases_disjoint_and_reclaimable(tmp_path):
    import time as _time

    from deeplearninginassetpricing_paperreplication_tpu.reliability.scheduler import (
        LeaseLost,
    )

    q = _slice_queue(tmp_path, lease_timeout_s=0.2)
    assert q.claim_device_slice("w0", 2) == 0
    assert q.claim_device_slice("w1", 2) == 1
    assert q.claim_device_slice("w2", 2) is None  # all held, live
    # self-reclaim: a restarted worker gets ITS slice back, not a new one
    assert q.claim_device_slice("w1", 2) == 1
    q.renew_device_slice(0, "w0")
    _time.sleep(0.25)  # both leases stale
    # expiry takeover: w2 takes the first expired slice
    assert q.claim_device_slice("w2", 2) == 0
    with pytest.raises(LeaseLost, match="slice 0"):
        q.renew_device_slice(0, "w0")  # w0 was presumed dead
    q.release_device_slice(1, "w1")
    assert q.claim_device_slice("w3", 2) == 1  # released slice is free
    # release by a non-owner is a no-op
    q.release_device_slice(1, "w1")
    assert q.claim_device_slice("w3", 2) == 1


def test_lease_keeper_renews_device_slice_and_flags_loss(tmp_path):
    import time as _time

    from deeplearninginassetpricing_paperreplication_tpu.reliability.ledger import (
        bucket_key,
    )
    from deeplearninginassetpricing_paperreplication_tpu.reliability.scheduler import (
        LeaseKeeper,
    )

    q = _slice_queue(tmp_path, lease_timeout_s=0.3)
    key = bucket_key({"h": 1}, [1e-3], [42], {})
    q.write_manifest([{"key": key, "index": 0}], {})
    assert q.claim("w0")[0] == "claimed"
    assert q.claim_device_slice("w0", 1) == 0
    with LeaseKeeper(q, key, "w0", slice_index=0) as keeper:
        _time.sleep(0.7)  # several renewal ticks past the timeout
        assert not keeper.lost and not keeper.slice_lost
        q.renew_device_slice(0, "w0")  # both leases live: renewed
    # now steal the slice: the keeper must flag slice_lost and stop
    assert q.claim("w1")[0] == "wait"
    with LeaseKeeper(q, key, "w0", slice_index=0) as keeper:
        import json as _json
        (q.slices_dir / "slice0.json").write_text(
            _json.dumps({"worker": "w_thief", "ts": _time.time()}))
        deadline = _time.time() + 5.0
        while not keeper.slice_lost and _time.time() < deadline:
            _time.sleep(0.05)
        assert keeper.slice_lost


def test_mesh_packed_worker_drains_device_sliced_queue(tmp_path):
    """One in-process mesh-packed worker: leases a device slice from the
    manifest, AOT-warms each bucket's programs over its slice mesh, drains
    the queue, and the ledger-reconstructed ranking equals the in-process
    (mesh-off) sweep's — with the slice released at drain."""
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        bucket_work_items,
        grid_configs,
        ranking_from_ledger,
        run_sweep,
        run_sweep_worker,
    )

    batch = _tiny_batch()
    base = GANConfig(macro_feature_dim=3, individual_feature_dim=6,
                     hidden_dim=(8,), dropout=0.0)
    configs = grid_configs(base, hidden_dims=((8,),), rnn_units=((4,),),
                           num_moments=(8,), dropouts=(0.0,),
                           lrs=(1e-3, 5e-4))
    tcfg = TrainConfig(num_epochs_unc=2, num_epochs_moment=1, num_epochs=3,
                       ignore_epoch=0)
    seeds = [42, 7, 11, 22]
    q = _slice_queue(tmp_path)
    items = bucket_work_items(configs, seeds, tcfg)
    import dataclasses

    q.write_manifest(items, {
        "tcfg": dataclasses.asdict(tcfg), "seeds": seeds,
        "device_slices": 2, "slice_width": 4,
    })
    trained = run_sweep_worker(q, "w0", batch, batch, verbose=False)
    assert trained == len(items) == 1
    ranked, coverage = ranking_from_ledger(q)
    assert coverage["complete"]
    ref = run_sweep(configs, seeds, batch, batch, tcfg=tcfg, top_k=None,
                    verbose=False)
    assert [(r["lr"], r["seed"], r["valid_sharpe"]) for r in ranked] == \
        [(r["lr"], r["seed"], r["valid_sharpe"]) for r in ref]
    # drained: the slice lease was released
    assert not q.slice_path(0).exists() and not q.slice_path(1).exists()


# --------------------------------------------------------------------------
# BENCH_MESH artifact bars + lint gate
# --------------------------------------------------------------------------


def test_bench_mesh_artifact_bars():
    bench = json.loads((REPO / "BENCH_MESH.json").read_text())
    assert bench["bars"]["met"] is True
    assert bench["value"] >= bench["bars"]["speedup_min"]
    assert bench["fault_ranking_bit_identical"] == 1
    assert bench["steady_state_recompiles"] == 0
    assert bench["programs_recorded"] >= 6
    assert (bench["mesh_vs_sequential_max_sharpe_delta"]
            <= bench["bars"]["sharpe_delta_max"])


LINTED_PARTITION = [
    PKG / "parallel" / "partition.py",
    PKG / "parallel" / "mesh.py",
    PKG / "parallel" / "sweep.py",
    PKG / "parallel" / "sequence.py",
    PKG / "parallel" / "multihost_worker.py",
    PKG / "reliability" / "scheduler.py",
    PKG / "serving" / "engine.py",
    PKG / "data" / "pipeline.py",
    PKG / "refit.py",
    PKG / "sweep.py",
    PKG / "train.py",
    REPO / "bench.py",
]


def test_partition_modules_lint_clean():
    from test_observability import _ast_unused_imports

    try:
        import ruff  # noqa: F401

        proc = subprocess.run(
            [sys.executable, "-m", "ruff", "check",
             *[str(p) for p in LINTED_PARTITION]],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
    except ImportError:
        problems = {}
        for path in LINTED_PARTITION:
            unused = _ast_unused_imports(path)
            if unused:
                problems[path.name] = unused
        assert not problems, f"unused imports: {problems}"
