"""Recurrent cells as `lax.scan` loops — the TPU-native LSTM.

The reference wraps ``torch.nn.LSTM`` (``/root/reference/src/model.py:21-84``)
to summarize the macro time series into a tiny hidden state (paper: 4 units).
Here the LSTM is an explicit `lax.scan` over time with PyTorch's exact cell
semantics and parameterization so that (a) weights exported from a reference
checkpoint drop straight in, and (b) XLA compiles the whole sequence into one
fused on-chip loop (T ≤ 300 steps of a 4-unit cell — negligible next to the
panel FFN, but it must not force host sync).

PyTorch LSTM conventions replicated:
  * parameters per layer l: ``w_ih_l{l}`` [4H, I], ``w_hh_l{l}`` [4H, H],
    ``b_ih_l{l}`` [4H], ``b_hh_l{l}`` [4H]
  * gate order i, f, g, o (input, forget, cell, output)
  * all parameters initialized U(-k, k) with k = 1/sqrt(H)
  * inter-layer dropout only when num_layers > 1 (model.py:44)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def _uniform_init(bound: float):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init


def lstm_cell(params, carry, x_t):
    """One PyTorch-semantics LSTM cell step.

    params: dict with w_ih [4H, I], w_hh [4H, H], b_ih [4H], b_hh [4H].
    carry: (h [H], c [H]);  x_t: [I].
    """
    h, c = carry
    z = x_t @ params["w_ih"].T + params["b_ih"] + h @ params["w_hh"].T + params["b_hh"]
    return _gates(z, c)


def _gates(z, c):
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


# The recurrence is latency-bound on TPU (hundreds of sequential tiny steps
# per forward), so the scan body is kept minimal: the input projection
# x @ W_ih^T (+ both biases) for ALL timesteps is hoisted into ONE [T, I] x
# [I, 4H] MXU matmul before the scan, and the loop is unrolled so XLA can
# software-pipeline the per-step [H] x [H, 4H] recurrent matmuls. Identical
# math to torch's step-by-step cell (same gate order, same accumulation per
# step) up to matmul reassociation.
#
# Measured at the real workload (240x10k panel, full 3-phase schedule):
# with the fused Pallas FFN carrying the panel math, unroll=1 runs the
# whole schedule ~19% faster than unroll=4 (11.3 s vs 13.9 s) AND halves
# the conditional phase's temp memory (1.2 GB vs 2.4 GB) — the unrolled
# recurrence bought nothing once the FFN left the XLA graph. Overridable
# via DLAP_LSTM_UNROLL for experiments.
import os as _os

try:
    _SCAN_UNROLL = max(1, int(_os.environ.get("DLAP_LSTM_UNROLL", "1")))
except ValueError:
    _SCAN_UNROLL = 1


def lstm_layer(params, x):
    """Full-sequence LSTM layer: x [T, I] → h sequence [T, H]."""
    ys, _ = lstm_scan(params, x)
    return ys


def lstm_scan(params, x, carry=None):
    """Full-sequence LSTM layer that ALSO returns the final (h, c) carry.

    Identical math (and identical op structure — the hoisted [T, I] x
    [I, 4H] input projection feeding the same scan body) to what
    :class:`TorchLSTM` runs, so the h sequence is bit-equal to the training
    forward's. The carry is what :func:`lstm_step` continues from — the
    cell/carry split the serving engine's incremental macro state rides on.
    """
    H = params["w_hh"].shape[1]
    zx = x @ params["w_ih"].T + (params["b_ih"] + params["b_hh"])  # [T, 4H]
    w_hh_t = params["w_hh"].T

    def step(carry, zx_t):
        h, c = carry
        return _gates(zx_t + h @ w_hh_t, c)

    if carry is None:
        carry = (jnp.zeros((H,), x.dtype), jnp.zeros((H,), x.dtype))
    carry, ys = jax.lax.scan(step, carry, zx, unroll=_SCAN_UNROLL)
    return ys, carry


def lstm_step(params, carry, x_t):
    """One O(1) incremental cell step continuing a :func:`lstm_scan` carry.

    Same hoisted-bias formulation as the scan body (x @ W_ih^T + (b_ih +
    b_hh), then the recurrent matmul inside the gates), so stepping month
    T+1 matches re-scanning months [0, T+1] up to the row-block matmul
    reassociation of computing one [1, I] row instead of T rows.
    """
    zx_t = x_t @ params["w_ih"].T + (params["b_ih"] + params["b_hh"])
    return _gates(zx_t + carry[0] @ params["w_hh"].T, carry[1])


def _layer_params(lstm_tree, num_layers):
    """Per-layer param dicts from the checkpoint subtree
    ``sdf_net/macro_lstm`` (keys ``w_ih_l{l}``, ...)."""
    return [
        {
            "w_ih": lstm_tree[f"w_ih_l{li}"],
            "w_hh": lstm_tree[f"w_hh_l{li}"],
            "b_ih": lstm_tree[f"b_ih_l{li}"],
            "b_hh": lstm_tree[f"b_hh_l{li}"],
        }
        for li in range(num_layers)
    ]


def stacked_lstm_scan(lstm_tree, x, num_layers):
    """Deterministic stacked-LSTM scan from checkpoint params: x [T, M] →
    (h sequence of the LAST layer [T, H], per-layer final carries).

    Matches ``TorchLSTM`` in eval mode (inter-layer dropout is identity
    there), reading the same param layout the checkpoints store, so serving
    needs no Flax module apply to summarize the macro history.
    """
    carries = []
    for p in _layer_params(lstm_tree, num_layers):
        x, carry = lstm_scan(p, x)
        carries.append(carry)
    return x, carries


def stacked_lstm_step(lstm_tree, carries, x_t, num_layers):
    """One incremental month through the stacked LSTM: (new last-layer h
    [H], new per-layer carries). The O(1) continuation of
    :func:`stacked_lstm_scan` — each new macro month costs one cell step
    per layer instead of a T-month re-scan."""
    new_carries = []
    for li, p in enumerate(_layer_params(lstm_tree, num_layers)):
        carry, x_t = lstm_step(p, carries[li], x_t)
        new_carries.append(carry)
    return x_t, new_carries


class TorchLSTM(nn.Module):
    """Stacked LSTM over a [T, input_dim] sequence → [T, hidden_sizes[-1]].

    Equivalent to ``torch.nn.LSTM(batch_first=True)`` applied to a single
    sequence (the reference adds/strips a fake batch dim, model.py:65-71).
    """

    hidden_sizes: Tuple[int, ...]
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        num_layers = len(self.hidden_sizes)
        # torch uses a single hidden size across layers (hidden_sizes[-1]);
        # we honor per-layer sizes but the paper config is a single [4].
        for li, H in enumerate(self.hidden_sizes):
            I = x.shape[-1]
            k = float(H) ** -0.5
            params = {
                "w_ih": self.param(f"w_ih_l{li}", _uniform_init(k), (4 * H, I)),
                "w_hh": self.param(f"w_hh_l{li}", _uniform_init(k), (4 * H, H)),
                "b_ih": self.param(f"b_ih_l{li}", _uniform_init(k), (4 * H,)),
                "b_hh": self.param(f"b_hh_l{li}", _uniform_init(k), (4 * H,)),
            }
            x = lstm_layer(params, x)
            if li < num_layers - 1 and self.dropout > 0.0:
                x = nn.Dropout(rate=self.dropout)(x, deterministic=deterministic)
        return x
