"""Backfill round-5 diagnostics into existing PARITY_*.json artifacts.

The r4 width-parity artifacts (PARITY_W500 / PARITY_MID / PARITY_W4000)
predate the parity tool's diagnostics. Their torch anchors (ref_runs/*)
and panels are on disk, and three of the new fields are EVAL-only — no
retraining needed, so they can be computed on CPU:

  * reference_sharpe_full_precision — the anchor's final_model.pt through
    the reference's own torch eval path at 6 decimals (the CLI prints 3);
  * selection_sensitivity — all three anchor checkpoints in our evaluator:
    the train-Sharpe spread across selection-equivalent models vs their
    valid/test agreement, the measured core of the train-divergence story;
  * train_divergence_analysis — the cause paragraph, instantiated with
    this shape's numbers.

The trajectory diagnostic needs OUR run's history (not saved in r4) — it
is added by a TPU re-run of tools/parity_vs_reference.py, not here.

    JAX_PLATFORMS=cpu python tools/augment_parity_artifacts.py
"""

from __future__ import annotations

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

spec = importlib.util.spec_from_file_location(
    "parity_tool", REPO / "tools" / "parity_vs_reference.py")
tool = importlib.util.module_from_spec(spec)
spec.loader.exec_module(tool)

JOBS = (
    ("PARITY_W500.json", "bench_data_w500", "ref_runs/w500"),
    ("PARITY_MID.json", "bench_data_mid", "ref_runs/mid2000"),
    ("PARITY_W4000.json", "bench_data_w4000", "ref_runs/w4000"),
)


def main():
    from deeplearninginassetpricing_paperreplication_tpu.utils.platform import (
        apply_env_platforms,
    )

    apply_env_platforms()
    for artifact, data_dir, ref_dir in JOBS:
        apath = REPO / artifact
        data_dir = REPO / data_dir
        ref_dir = REPO / ref_dir
        if not apath.exists() or not (ref_dir / "final_model.pt").exists():
            print(f"[augment] skip {artifact}: missing artifact or anchor")
            continue
        if not (data_dir / "char" / "Char_train.npz").exists():
            print(f"[augment] skip {artifact}: panel {data_dir} not on disk")
            continue
        report = json.loads(apath.read_text())
        print(f"[augment] {artifact}: evaluating anchors on {data_dir.name}",
              flush=True)
        # shared eval context (parity_vs_reference.make_eval_context):
        # pinned f32-panel / pallas-off, so the spreads are the bit-closest
        # comparison to torch regardless of the host backend
        ctx = tool.make_eval_context(data_dir)
        ref_full = tool.ref_full_precision_eval(ref_dir, data_dir)
        sel = tool.selection_sensitivity(ref_dir, ctx)
        sel["eval_route"] = "f32-xla"
        report["reference_sharpe_full_precision"] = ref_full
        # ours sharpes in the r4 artifacts are recorded at 4 decimals, so
        # this delta is exact on the reference side, 1e-4-quantized on ours
        report["abs_delta_sharpe_full_precision"] = {
            k: round(abs(report["ours"]["sharpe"][k] - ref_full[k]), 6)
            for k in ("train", "valid", "test")
        }
        report["abs_delta_full_precision_note"] = (
            "reference side at full precision (its own torch eval re-run on "
            "final_model.pt); ours side as recorded at 4 decimals in this "
            "artifact, so deltas are bounded below ~5e-5 quantization")
        report["selection_sensitivity"] = sel
        report["train_divergence_analysis"] = tool.train_divergence_text(
            report.get("workload", artifact),
            report["abs_delta_sharpe"]["train"], sel, eval_route="f32-xla")
        apath.write_text(json.dumps(report, indent=2))
        print(f"[augment] {artifact}: train spread "
              f"{sel.get('train_spread_across_checkpoints')} valid "
              f"{sel.get('valid_spread_across_checkpoints')} test "
              f"{sel.get('test_spread_across_checkpoints')}; "
              f"delta_full {report['abs_delta_sharpe_full_precision']}")


if __name__ == "__main__":
    main()
