"""Headline benchmark: full 3-phase GAN-SDF training wall-clock.

Workload: the reference's bundled synthetic panel shape (train 120×500×46,
valid 30, test 60, 8 macro series) with the paper's full schedule
(256 + 64 + 1024 epochs, seed 42) — the exact run the PyTorch reference
completes in ~294 s on this machine's CPU (measured: `python -m src.train
--data_dir data/synthetic_data` at /root/reference, 2026-07-29).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline = reference_seconds / our_seconds (higher is better).
"""

import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp

REFERENCE_CPU_SECONDS = 294.0  # measured reference wall-clock, same workload
DATA_DIR = Path(__file__).parent / "bench_data"


def _ensure_data():
    if not (DATA_DIR / "char" / "Char_train.npz").exists():
        from deeplearninginassetpricing_paperreplication_tpu.data.synthetic import (
            generate_all_splits,
        )

        generate_all_splits(
            DATA_DIR,
            n_periods_train=120, n_periods_valid=30, n_periods_test=60,
            n_stocks=500, n_features=46, n_macro=8, seed=42, verbose=False,
        )
    return DATA_DIR


def main():
    from deeplearninginassetpricing_paperreplication_tpu.utils.cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()

    from deeplearninginassetpricing_paperreplication_tpu.data.panel import load_splits
    from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
        train_3phase,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    data_dir = _ensure_data()
    train_ds, valid_ds, test_ds = load_splits(data_dir)

    def batch(ds):
        return {k: jax.device_put(jnp.asarray(v)) for k, v in ds.full_batch().items()}

    train_b, valid_b, test_b = batch(train_ds), batch(valid_ds), batch(test_ds)

    cfg = GANConfig(
        macro_feature_dim=train_ds.macro_feature_dim,
        individual_feature_dim=train_ds.individual_feature_dim,
    )
    tcfg = TrainConfig()  # paper defaults: 256/64/1024, lr 1e-3, seed 42

    t0 = time.time()
    gan, final_params, history, trainer = train_3phase(
        cfg, train_b, valid_b, test_b, tcfg=tcfg, verbose=False
    )
    jax.block_until_ready(jax.tree.leaves(final_params))
    wall = time.time() - t0

    test_metrics = trainer.final_eval(final_params, test_b)
    print(
        json.dumps(
            {
                "metric": "3phase_train_wallclock_synthetic_120x500_1344ep",
                "value": round(wall, 2),
                "unit": "s",
                "vs_baseline": round(REFERENCE_CPU_SECONDS / wall, 2),
                "test_sharpe": round(test_metrics["sharpe"], 4),
                "device": str(jax.devices()[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
