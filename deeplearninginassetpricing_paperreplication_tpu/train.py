"""Training CLI — flag-for-flag parity with the reference's ``python -m
src.train`` (``/root/reference/src/train.py:429-609``), running the fully
on-device 3-phase trainer.

    python -m deeplearninginassetpricing_paperreplication_tpu.train \
        --data_dir data/synthetic_data --save_dir ./checkpoints
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp

from .data.panel import load_splits
from .observability import (
    EventLog,
    Heartbeat,
    RunLogger,
    set_run_logger,
    write_manifest,
)
from .parallel.mesh import create_mesh, shard_batch
from .utils.config import ExecutionConfig, GANConfig, TrainConfig


def profile_trace_nonempty(trace_dir) -> bool:
    """Did ``jax.profiler.trace`` actually write anything under `trace_dir`?
    (A wedged backend can exit the context without producing a trace; the
    CLI must not claim success then.)"""
    trace_dir = Path(trace_dir)
    if not trace_dir.is_dir():
        return False
    return any(p.is_file() for p in trace_dir.rglob("*"))


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Train Asset Pricing GAN (TPU-native)")
    p.add_argument("--config", type=str, help="Path to config JSON")
    p.add_argument("--data_dir", type=str, required=True)
    p.add_argument("--save_dir", type=str, default="./checkpoints")

    # 3-phase schedule (paper defaults)
    p.add_argument("--epochs_unc", type=int, default=256)
    p.add_argument("--epochs_moment", type=int, default=64)
    p.add_argument("--epochs", type=int, default=1024)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--print_freq", type=int, default=128)
    p.add_argument("--ignore_epoch", type=int, default=64)
    p.add_argument("--save_best_freq", type=int, default=128,
                   help="Accepted for reference-CLI parity "
                        "(src/train.py:442) and, like the reference — which "
                        "plumbs it but never reads it in the training loop — "
                        "it has no effect: best params are tracked on device "
                        "every epoch and persisted at phase boundaries (use "
                        "--checkpoint_every for mid-phase persistence)")

    # data options
    p.add_argument("--small_sample", action="store_true")
    p.add_argument("--n_periods", type=int, default=100)
    p.add_argument("--n_stocks", type=int, default=500)

    # model options (paper defaults)
    p.add_argument("--use_lstm", action="store_true", default=True)
    p.add_argument("--no_lstm", action="store_false", dest="use_lstm")
    p.add_argument("--hidden_dim", type=int, nargs="+", default=[64, 64])
    p.add_argument("--rnn_dim", type=int, nargs="+", default=[4])
    p.add_argument("--num_moments", type=int, default=8)
    p.add_argument("--dropout", type=float, default=0.05)
    p.add_argument("--hidden_dim_moment", type=int, nargs="+", default=[])
    p.add_argument("--rnn_dim_moment", type=int, nargs="+", default=[32])
    p.add_argument("--seed", type=int, default=42)

    # TPU-native extras (no reference counterpart)
    p.add_argument("--shard_stocks", action="store_true",
                   help="Shard the [T,N,F] panel along N over all devices")
    p.add_argument("--resume", action="store_true",
                   help="Continue from the last resume point recorded in "
                        "save_dir (a phase boundary, or a mid-phase segment "
                        "boundary when --checkpoint_every was used)")
    p.add_argument("--checkpoint_every", type=int, default=None, metavar="K",
                   help="Persist a resumable state every K epochs within "
                        "each phase (epoch-granular fault tolerance); "
                        "bit-identical to an uninterrupted run")
    p.add_argument("--stop_after_epochs", type=int, default=None, metavar="E",
                   help="Run at most E more train epochs this invocation "
                        "(checked at segment boundaries), save the mid-phase "
                        "state, and exit — combine with --resume to continue")
    p.add_argument("--profile", type=str, default=None, metavar="TRACE_DIR",
                   help="Capture a jax.profiler trace of the training run "
                        "into TRACE_DIR (view with TensorBoard/XProf)")
    p.add_argument("--share_sdf_program", action="store_true",
                   help="Compile ONE program for phases 1 and 3 (saves a "
                        "~6-10 s compile + an executable upload on one-shot "
                        "cold runs; costs ~1.6 ms/epoch execute — see "
                        "Trainer.share_sdf_program)")
    p.add_argument("--pallas", choices=["auto", "on", "off"], default="auto",
                   help="Fused Pallas SDF-FFN kernel (auto: on for TPU); "
                        "under --shard_stocks it runs per-device via "
                        "shard_map")
    p.add_argument("--no_pipeline", action="store_true",
                   help="Disable the overlapped startup pipeline (decoded-"
                        "panel disk cache + streamed transfer + early AOT "
                        "compile; data/pipeline.py) and load sequentially. "
                        "Results are bit-identical either way; this exists "
                        "for A/B timing and debugging")
    p.add_argument("--metrics_port", type=int, default=None, metavar="PORT",
                   help="Serve live Prometheus metrics (counters, gauges, "
                        "span-latency histograms with derived p50/p95/p99) "
                        "on http://127.0.0.1:PORT/metrics while the run "
                        "trains — a read-only stdlib sidecar fed from the "
                        "same call sites as events.jsonl (port 0 picks a "
                        "free one, printed at startup)")
    p.add_argument("--diag_stride", type=int, default=None, metavar="K",
                   help="Fold the model-health diagnostics "
                        "(ops/diagnostics.py: per-moment violation norms, "
                        "SDF/portfolio stats, adversarial gap) into the "
                        "compiled phase scans every K epochs, landing as "
                        "diag_* history.npz fields. Observationally free: "
                        "trained params and best checkpoints are "
                        "bit-identical with the knob on or off "
                        "(BENCH_HEALTH.json gates the <=5%% throughput "
                        "cost)")
    p.add_argument("--no_divergence_guard", action="store_false",
                   dest="divergence_guard",
                   help="Disable the per-segment non-finite loss/grad check "
                        "(reliability/guard.py). Outputs are bit-identical "
                        "either way; the guard only decides whether a NaN "
                        "blowup aborts cleanly or poisons the checkpoints")
    p.add_argument("--guard_max_trips", type=int, default=3, metavar="K",
                   help="Consecutive non-finite segments before the "
                        "divergence guard aborts the run")
    return p


def main(argv=None):
    from .utils.platform import apply_env_platforms

    apply_env_platforms()
    from .utils.cache import enable_compilation_cache

    enable_compilation_cache()
    args = build_arg_parser().parse_args(argv)
    save_dir = Path(args.save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)

    # telemetry sinks for this run dir: structured events, bench-compatible
    # phase-tagged heartbeats, and the process-0-gated logger
    events = EventLog(save_dir)
    hb = Heartbeat(save_dir / "heartbeat.json", events=events)
    logger = set_run_logger(RunLogger(events=events))
    hb.beat("setup")

    sidecar = None
    if args.metrics_port is not None:
        from .observability import MetricsSidecar

        sidecar = MetricsSidecar([events.metrics], port=args.metrics_port)
        port = sidecar.start()
        logger.info(f"metrics sidecar: http://127.0.0.1:{port}/metrics "
                    "(Prometheus text)")

    logger.info("Deep Learning Asset Pricing — TPU-native (JAX/XLA)")
    logger.info(f"Devices: {jax.devices()}")

    tcfg = TrainConfig(
        num_epochs_unc=args.epochs_unc,
        num_epochs_moment=args.epochs_moment,
        num_epochs=args.epochs,
        lr=args.lr,
        ignore_epoch=args.ignore_epoch,
        seed=args.seed,
        print_freq=args.print_freq,
    )

    def make_cfg(macro_dim, individual_dim):
        if args.config:
            return GANConfig.load(args.config)
        return GANConfig(
            macro_feature_dim=macro_dim,
            individual_feature_dim=individual_dim,
            hidden_dim=tuple(args.hidden_dim),
            use_rnn=args.use_lstm,
            num_units_rnn=tuple(args.rnn_dim),
            hidden_dim_moment=tuple(args.hidden_dim_moment),
            num_condition_moment=args.num_moments,
            num_units_rnn_moment=tuple(args.rnn_dim_moment),
            dropout=args.dropout,
        )

    # the overlapped startup pipeline serves the whole-panel path AND the
    # --shard_stocks mesh path (chunked store + per-shard streamed transfer,
    # data/pipeline.py); only --small_sample (which reshapes the data after
    # decode) and --no_pipeline fall back to the sequential path
    use_pipeline = not (args.small_sample or args.no_pipeline)
    mesh = create_mesh() if args.shard_stocks else None
    pre_trainer = None

    if use_pipeline:
        from .data.pipeline import (
            StartupPipeline,
            probe_split_shapes,
            trainer_precompile_fn,
        )

        logger.info("Loading data (overlapped startup pipeline"
                    + (", stock-sharded" if mesh is not None else "")
                    + ")...")
        if mesh is not None:
            logger.info(f"Sharding stock axis over {mesh.devices.size} "
                        "devices (chunked store, per-shard transfer)")
        # shapes from npz headers at t≈0: the phase-program compiles start
        # NOW, on a worker thread, and hide under the load+transfer window
        shapes = probe_split_shapes(args.data_dir)
        cfg = make_cfg(
            shapes["train"].get("macro", (0, 0))[1],
            shapes["train"]["individual"][2],
        )
        exec_cfg = ExecutionConfig(pallas_ffn=args.pallas, shard_mesh=mesh)
        # bf16 wire on BOTH routes: single-device transfers ship the packed
        # bf16 payload, and the sharded route streams each owning device's
        # `individual` span bfloat16 with an in-place upcast — values
        # identical to the f32 wire up to the bf16 rounding PARITY_BF16.json
        # validates end-to-end (the PR-7 hold-off is lifted)
        bf16_wire = exec_cfg.bf16_wire_ok(cfg)
        # --resume: the dispatched program sizes depend on the on-disk
        # resume state (completed phase / mid-phase epoch), so an early
        # whole-phase compile would build programs that never run and block
        # startup on them — skip it; the cache + streamed transfer still
        # apply, and Trainer.train precompiles the right programs itself
        compile_fn = None if args.resume else trainer_precompile_fn(
            cfg, tcfg, exec_cfg, args.seed,
            share_sdf_program=args.share_sdf_program,
            events=events, heartbeat=hb,
            checkpoint_every=args.checkpoint_every,
            stop_after_epochs=args.stop_after_epochs,
            divergence_guard=args.divergence_guard,
            guard_max_trips=args.guard_max_trips,
            mesh=mesh,
            diag_stride=args.diag_stride,
        )
        with events.span("startup/pipeline"):
            res = StartupPipeline(
                args.data_dir, bf16_wire=bf16_wire, events=events,
                compile_fn=compile_fn, shapes=shapes, mesh=mesh,
            ).start().result()
        train_ds, valid_ds, test_ds = res.datasets
        train_b, valid_b, test_b = res.batches
        pre_trainer = res.compiled
        hits = sum(res.cache_hits.values())
        logger.info(f"  panel cache: {hits}/{len(res.cache_hits)} split hits")
    else:
        logger.info("Loading data...")
        with events.span("data/load"):
            if args.no_pipeline:
                train_ds, valid_ds, test_ds = load_splits(args.data_dir)
            else:
                from .data.pipeline import load_splits_cached

                train_ds, valid_ds, test_ds = load_splits_cached(
                    args.data_dir, events=events
                )

        if args.small_sample:
            logger.info(f"Using small sample: {args.n_periods} periods, "
                        f"{args.n_stocks} stocks")
            train_ds = train_ds.subsample(args.n_periods, args.n_stocks)
            valid_ds = valid_ds.subsample(min(args.n_periods, valid_ds.T), args.n_stocks)
            test_ds = test_ds.subsample(min(args.n_periods, test_ds.T), args.n_stocks)

        if mesh is not None:
            n_dev = mesh.devices.size
            train_ds = train_ds.pad_stocks(n_dev)
            valid_ds = valid_ds.pad_stocks(n_dev)
            test_ds = test_ds.pad_stocks(n_dev)
            logger.info(f"Sharding stock axis over {n_dev} devices")

        cfg = make_cfg(train_ds.macro_feature_dim,
                       train_ds.individual_feature_dim)

        # under --shard_stocks the kernel runs per-device via shard_map; the
        # stock shards stay local and replicated params get psum'd gradients
        exec_cfg = ExecutionConfig(pallas_ffn=args.pallas, shard_mesh=mesh)

        from .data.transfer import device_put_batch

        # ship the panel bf16 over the wire only when every panel consumer
        # reads it at bf16 anyway — halves the dominant host→device payload
        # with zero change to computed values (ExecutionConfig.bf16_wire_ok)
        bf16_wire = exec_cfg.bf16_wire_ok(cfg)

        def to_device(ds):
            if mesh is not None:
                batch = {k: jnp.asarray(v) for k, v in ds.full_batch().items()}
                return shard_batch(batch, mesh)
            # unsharded: mask-packed transfer (only valid entries ship;
            # scattered into zeros on device, bit-exact with a dense put)
            return device_put_batch(ds.full_batch(), bf16_wire=bf16_wire)

        with events.span("data/transfer"):
            train_b, valid_b, test_b = (
                to_device(train_ds), to_device(valid_ds), to_device(test_ds)
            )

    logger.info(f"  Train: {train_ds.T} x {train_ds.N} | Valid: {valid_ds.T} x {valid_ds.N} "
                f"| Test: {test_ds.T} x {test_ds.N}")
    logger.info(f"  Features: {train_ds.individual_feature_dim} individual, "
                f"{train_ds.macro_feature_dim} macro")

    # startup manifest: the run dir is self-describing from this point on,
    # whatever happens to the training that follows
    write_manifest(
        save_dir, "train", events=events,
        config=cfg, tcfg=tcfg, seed=args.seed,
        data_dir=args.data_dir, argv=argv, mesh=mesh,
        extra={"resume": bool(args.resume),
               "share_sdf_program": bool(args.share_sdf_program),
               "startup_pipeline": bool(use_pipeline),
               "diag_stride": args.diag_stride},
    )

    # the train panel's reference profile (observability/drift.py): the
    # data fingerprint every later panel / serving request / promotion
    # candidate is drift-scored against — written before training so even
    # a crashed run leaves it, and referenced from the manifest
    from .observability.drift import PROFILE_FILENAME, reference_profile, write_profile

    with events.span("health/reference_profile"):
        write_profile(save_dir, reference_profile(
            train_ds.full_batch(), source=str(args.data_dir)))
    from .observability import update_manifest

    update_manifest(save_dir, reference_profile=PROFILE_FILENAME)

    t0 = time.time()
    from .training.trainer import train_3phase

    import contextlib

    profile_ctx = (
        jax.profiler.trace(args.profile, create_perfetto_link=False)
        if args.profile
        else contextlib.nullcontext()
    )
    with profile_ctx:
        gan, final_params, history, trainer = train_3phase(
            cfg, train_b, valid_b, test_b, tcfg=tcfg, save_dir=str(save_dir),
            seed=args.seed, resume=args.resume, exec_cfg=exec_cfg,
            checkpoint_every=args.checkpoint_every,
            stop_after_epochs=args.stop_after_epochs,
            share_sdf_program=args.share_sdf_program,
            events=events, heartbeat=hb,
            divergence_guard=args.divergence_guard,
            guard_max_trips=args.guard_max_trips,
            diag_stride=args.diag_stride,
            # pipeline path: the Trainer whose phase programs AOT-compiled
            # under the load+transfer window — dispatch straight into them
            trainer=pre_trainer,
        )
    if args.profile:
        # only claim a trace exists after checking the directory: a wedged
        # backend can exit the profiler context without writing anything
        if profile_trace_nonempty(args.profile):
            logger.info(f"Profiler trace written to {args.profile}")
        else:
            logger.warning(
                f"--profile: no trace files found under {args.profile} — "
                "the profiler produced no output", trace_dir=str(args.profile))
    wall = time.time() - t0
    # late provenance: XLA cost/memory analysis of every AOT phase program
    # this run compiled (absent only when every program was lazily jitted,
    # e.g. --resume into an exotic schedule)
    if trainer.program_analyses:
        from .observability import update_manifest

        update_manifest(save_dir, xla_programs=trainer.program_analyses)
    if trainer.stopped_midphase:
        # a --stop_after_epochs exit returns the RUNNING params, not a
        # best-model selection — reporting them as final would mislead, and
        # writing final_metrics.json would clobber a previous complete run's
        logger.info(f"\nStopped mid-phase after {wall:.1f}s; resumable state "
                    f"in {save_dir} — continue with --resume")
        # terminal beat: a watchdog must see a PLANNED stop, not a death
        # attributed to whatever phase the last training beat named
        hb.beat("stopped")
        if sidecar is not None:
            sidecar.stop()
        events.close()
        return
    logger.info("\nBest Model Performance (normalized weights):")
    results = {}
    for name, b in (("train", train_b), ("valid", valid_b), ("test", test_b)):
        with events.span(f"eval/{name}"):
            m = trainer.final_eval(final_params, b)
        results[name] = m
        logger.info(f"  {name:5s} - Sharpe: {m['sharpe']:7.3f}, "
                    f"MaxDD: {m['max_drawdown']:7.2%}")
    (save_dir / "final_metrics.json").write_text(
        json.dumps({**results, "wall_clock_s": wall, **trainer.timings()}, indent=2)
    )
    logger.info(f"\nTotal wall-clock: {wall:.1f}s — checkpoints in {save_dir}")
    if sidecar is not None:
        sidecar.stop()
    events.close()


if __name__ == "__main__":
    main()
