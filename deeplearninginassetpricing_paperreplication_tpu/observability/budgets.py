"""Declarative perf budgets: the machine-enforced guard over BENCH_*.

A budget file (the repo ships ``budgets.json``) declares per-metric bounds
with tolerances::

    {
      "schema": 1,
      "budgets": [
        {"name": "serving_async_c32_rps",
         "file": "BENCH_SERVING.json",
         "metric": "async_replicated.closed_loop_c32_bin.throughput_rps",
         "min": 457.77, "tolerance": 0.20},
        {"name": "serving_recompiles",
         "file": "BENCH_SERVING.json",
         "metric": "async_replicated.steady_state_recompiles.replica0",
         "equals": 0},
        {"name": "train_epochs_per_s",
         "metric": "phases.phase3_conditional.epochs_per_s",
         "min": 2.0, "tolerance": 0.25}
      ]
    }

Each entry names a dotted ``metric`` path (list indices allowed:
``trials.0.p99_ms``) into either a JSON artifact (``file``, resolved
relative to the budget file — the checked-in ``BENCH_*.json`` trajectory)
or, when ``file`` is absent, the report CLI's run-dir summary. Bounds:

  * ``min``: pass when ``value >= min * (1 - tolerance)``;
  * ``max``: pass when ``value <= max * (1 + tolerance)``;
  * ``equals``: pass when ``abs(value - equals) <= tolerance`` (absolute —
    the canonical use is ``steady_state_recompiles == 0``, where a
    relative band around zero would be vacuous).

A missing file, unresolvable metric path, or non-numeric value FAILS the
entry — a regression gate that can silently skip is not a gate. Exposed as
``report --budget budgets.json [run_dirs...]`` (exit non-zero on any
failure) and wrapped by ``tools/check_budgets.py`` for tier-1.

Pure stdlib; no jax import anywhere on this path.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional

BUDGET_SCHEMA_VERSION = 1


class BudgetSpecError(ValueError):
    """The budget file itself is malformed (a broken gate must fail loudly,
    not pass vacuously)."""


def load_budgets(path) -> Dict[str, Any]:
    """Read + validate a budget file; raises :class:`BudgetSpecError` on
    any malformation."""
    path = Path(path)
    try:
        spec = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise BudgetSpecError(f"budget file unreadable: {path}: {e}") from e
    entries = spec.get("budgets")
    if not isinstance(entries, list) or not entries:
        raise BudgetSpecError(
            f"{path}: 'budgets' must be a non-empty list of entries")
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise BudgetSpecError(f"{path}: budgets[{i}] is not an object")
        where = f"{path}: budgets[{i}] ({e.get('name', '?')})"
        if not isinstance(e.get("name"), str) or not e["name"]:
            raise BudgetSpecError(f"{where}: requires a 'name'")
        if not isinstance(e.get("metric"), str) or not e["metric"]:
            raise BudgetSpecError(f"{where}: requires a 'metric' path")
        bounds = [k for k in ("min", "max", "equals") if k in e]
        if not bounds:
            raise BudgetSpecError(
                f"{where}: requires at least one of min/max/equals")
        for k in bounds:
            if not isinstance(e[k], (int, float)):
                raise BudgetSpecError(f"{where}: '{k}' must be a number")
        tol = e.get("tolerance", 0)
        if not isinstance(tol, (int, float)) or tol < 0:
            raise BudgetSpecError(
                f"{where}: 'tolerance' must be a non-negative number")
    return spec


def resolve_metric(doc: Any, dotted: str) -> Any:
    """Walk a dotted path (dict keys / list indices) through a JSON doc.
    Raises KeyError naming the first segment that fails to resolve."""
    cur = doc
    walked: List[str] = []
    for seg in dotted.split("."):
        walked.append(seg)
        if isinstance(cur, dict) and seg in cur:
            cur = cur[seg]
        elif isinstance(cur, list) and seg.lstrip("-").isdigit() \
                and -len(cur) <= int(seg) < len(cur):
            cur = cur[int(seg)]
        else:
            raise KeyError(
                f"metric path {dotted!r} failed at {'.'.join(walked)!r}")
    return cur


def check_entry(entry: Dict[str, Any], doc: Any,
                source: str) -> Dict[str, Any]:
    """One budget entry against one metric document → the check record."""
    out: Dict[str, Any] = {
        "name": entry["name"], "metric": entry["metric"], "source": source,
    }
    tol = float(entry.get("tolerance", 0))
    try:
        value = resolve_metric(doc, entry["metric"])
    except KeyError as e:
        out.update(ok=False, reason=f"missing metric: {e.args[0]}")
        return out
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        out.update(ok=False,
                   reason=f"metric is not a number: {value!r}")
        return out
    value = float(value)
    out["value"] = value
    ok = True
    reasons: List[str] = []
    if "min" in entry:
        floor = float(entry["min"]) * (1.0 - tol)
        out["min_allowed"] = round(floor, 6)
        if value < floor:
            ok = False
            reasons.append(
                f"{value:g} < min {entry['min']:g} (tolerance {tol:g} "
                f"-> floor {floor:g})")
    if "max" in entry:
        ceil = float(entry["max"]) * (1.0 + tol)
        out["max_allowed"] = round(ceil, 6)
        if value > ceil:
            ok = False
            reasons.append(
                f"{value:g} > max {entry['max']:g} (tolerance {tol:g} "
                f"-> ceiling {ceil:g})")
    if "equals" in entry:
        target = float(entry["equals"])
        if abs(value - target) > tol:
            ok = False
            reasons.append(
                f"{value:g} != {target:g} (abs tolerance {tol:g})")
    out["ok"] = ok
    if reasons:
        out["reason"] = "; ".join(reasons)
    return out


def check_budgets(
    budget_path,
    run_summaries: Optional[Dict[str, Dict[str, Any]]] = None,
    file_overrides: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Run the whole gate: ``file`` entries against their JSON artifacts
    (relative to the budget file), run-scoped entries against every run
    summary given. ``file_overrides`` maps a budget entry's ``file`` name
    to an actual path — how ``bench.py --check_budgets --out X`` gates the
    artifact it JUST wrote instead of the checked-in copy. Returns
    ``{"ok": bool, "checks": [...]}`` — ``ok`` only when EVERY check
    passed; run-scoped entries with no run dir to check against fail (the
    gate never silently skips)."""
    budget_path = Path(budget_path)
    spec = load_budgets(budget_path)
    run_summaries = run_summaries or {}
    file_overrides = file_overrides or {}
    checks: List[Dict[str, Any]] = []
    file_docs: Dict[str, Any] = {}
    for entry in spec["budgets"]:
        file_rel = entry.get("file")
        if file_rel:
            if file_rel not in file_docs:
                fpath = Path(file_overrides.get(
                    file_rel, budget_path.parent / file_rel))
                try:
                    file_docs[file_rel] = json.loads(fpath.read_text())
                except (OSError, json.JSONDecodeError) as e:
                    file_docs[file_rel] = BudgetSpecError(
                        f"artifact unreadable: {fpath}: {e}")
            doc = file_docs[file_rel]
            if isinstance(doc, BudgetSpecError):
                checks.append({
                    "name": entry["name"], "metric": entry["metric"],
                    "source": file_rel, "ok": False, "reason": str(doc),
                })
            else:
                checks.append(check_entry(entry, doc, file_rel))
        elif run_summaries:
            for run_dir, summary in sorted(run_summaries.items()):
                checks.append(check_entry(entry, summary, run_dir))
        else:
            checks.append({
                "name": entry["name"], "metric": entry["metric"],
                "source": "<run dir>", "ok": False,
                "reason": "run-scoped budget but no run dir was given",
            })
    return {"ok": all(c["ok"] for c in checks),
            "budget_file": str(budget_path),
            "checks": checks}


def format_budget_report(result: Dict[str, Any]) -> str:
    """Human-readable gate output, one line per check."""
    lines = [f"budget gate: {result['budget_file']} — "
             + ("PASS" if result["ok"] else "REGRESSION")]
    for c in result["checks"]:
        status = "ok  " if c["ok"] else "FAIL"
        value = f"{c['value']:g}" if "value" in c else "n/a"
        line = (f"  [{status}] {c['name']}: {c['source']}:{c['metric']}"
                f" = {value}")
        if not c["ok"]:
            line += f"  ({c.get('reason', 'failed')})"
        lines.append(line)
    return "\n".join(lines)
