"""``python -m deeplearninginassetpricing_paperreplication_tpu.report`` —
aggregate run-dir telemetry into a compile/execute/memory report.

Thin module-runner shim; the implementation lives in
:mod:`.observability.report` (pure file reading — no JAX backend touched).
"""

from .observability.report import build_arg_parser, main  # noqa: F401

if __name__ == "__main__":
    raise SystemExit(main())
