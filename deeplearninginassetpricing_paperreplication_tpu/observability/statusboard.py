"""Cross-plane ops console: one view over pointer, fleet, autoscaler,
ledger, model health, probes, and SLO alerts.

Every plane so far reports through its own artifact family — the promotion
pointer (PR 9), ``fleet.json`` + autoscaler events (PR 12), refit ledger
(PR 9), health/drift counters (PR 14), probe/alert rows (PR 15). During an
incident nobody has time to join six files by hand; ``python -m ….ops``
does the join:

  * ``status`` — the CURRENT posture: pointer head + per-replica serving
    generation, fleet layout, autoscaler scale counts, refit/ledger
    coverage, health/drift/canary counters, SLO budget burn and firing
    alerts, probe totals.
  * ``timeline`` — the recent HISTORY: promotions, rollbacks, scale
    events, hot-swaps, canary verdicts, probe failures, and alert
    transitions from the run dir's whole event-file family, merged on the
    PR-8 clock alignment (per-(file, run_id) ``median(ts - mono)``
    anchors), so cross-process order is wall-true.

Both commands are BYTE-DETERMINISTIC: they read only on-disk artifacts
(event files, ``fleet.json``, the pointer, heartbeat files — raw recorded
timestamps, never ages against "now"), so two invocations over the same
run dir print identical bytes, and ``--json`` emits a machine document a
pager bot can diff. Strictly read-only file access — no live scrapes, no
device init (the package import itself is the only weight) — so it is
safe to point at a LIVE run dir from any box with the filesystem mounted.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

from .heartbeat import read_state
from .trace import _aligned_ts, _group_offsets, read_jsonl, trace_file_paths

# counter-row names that belong on the operations timeline (kind "alert"
# and kind "probe" rows are always included)
TIMELINE_COUNTERS = frozenset({
    "promote/advance",
    "promote/reject",
    "promote/rollback",
    "promote/fleet_rollback",
    "promote/fleet_rollback_failed",
    "promote/fleet_converged",
    "fleet/scale",
    "supervise/death",
    "supervise/restart",
    "supervise/outcome",
    "serve/generation",
    "serve/canary",
    "serve/drain",
    "serve/flightrecorder",
    "sweep/lease_takeover",
    "sweep/quarantine",
    "guard/trip",
    "fault/injected",
    "model/drift_alert",
    "probe/digest_change",
    "probe/layout_unreadable",
})

# bounded per-row detail: the keys worth a timeline column, in render order
_DETAIL_KEYS = (
    "objective", "window", "severity", "state", "burn_long", "burn_short",
    "target", "error", "consecutive", "direction", "reason", "replica",
    "generation", "pointer_generation", "fingerprint", "swapped", "site",
    "action", "section", "rc", "outcome", "max_weight_delta",
    "max_sdf_delta", "finite", "month",
)


def _fmt_val(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _detail(row: Dict[str, Any]) -> str:
    parts = [f"{k}={_fmt_val(row[k])}" for k in _DETAIL_KEYS
             if row.get(k) is not None]
    return " ".join(parts)


# -- the SLO-posture scan (shared with report._slo_summary) ------------------


def scan_slo_rows(rows) -> Dict[str, Any]:
    """ONE walk over event rows extracting the SLO plane's posture: the
    last alert transition per (objective, window) (which decides
    firing/resolved), transition totals, the last burn-rate /
    budget-remaining gauge per key, and the probe counters. The ops
    console and the report CLI both render from THIS scan, so the two
    can never drift on what the rows mean."""
    out: Dict[str, Any] = {
        "last_state": {}, "burn": {}, "budget": {},
        "firings": 0, "resolves": 0,
        "probe_checks": 0, "probe_failures": 0, "digest_changes": 0,
        "layout_unreadable": 0, "failure_targets": {},
    }
    for r in rows:
        kind = r.get("kind")
        name = str(r.get("name", ""))
        if kind == "alert":
            key = (str(r.get("objective")), str(r.get("window")))
            out["last_state"][key] = r
            if name == "alert/firing":
                out["firings"] += 1
            elif name == "alert/resolved":
                out["resolves"] += 1
        elif kind == "probe" and name == "probe/failure":
            out["probe_failures"] += 1
            t = str(r.get("target"))
            out["failure_targets"][t] = (
                out["failure_targets"].get(t, 0) + 1)
        elif kind == "counter":
            if name == "probe/check":
                out["probe_checks"] += int(r.get("value") or 0)
            elif name == "probe/digest_change":
                out["digest_changes"] += int(r.get("value") or 0)
            elif name == "probe/layout_unreadable":
                out["layout_unreadable"] += int(r.get("value") or 0)
        elif kind == "gauge":
            key = (str(r.get("objective")), str(r.get("window")))
            if name == "alert/burn_rate":
                out["burn"][key] = r.get("value")
            elif name == "alert/budget_remaining":
                out["budget"][key] = r.get("value")
    return out


# -- timeline ----------------------------------------------------------------


def gather_timeline(run_dir, limit: Optional[int] = None
                    ) -> List[Dict[str, Any]]:
    """The run dir's operations timeline: selected rows from the whole
    event-file family, wall-aligned (PR-8 anchors), deterministically
    ordered (aligned µs, file, seq). ``limit`` keeps only the newest N."""
    run_dir = Path(run_dir)
    rows_out: List[Dict[str, Any]] = []
    t0: Optional[float] = None
    collected = []
    for path in trace_file_paths(run_dir):
        rows = read_jsonl(path)
        offsets = _group_offsets(rows)
        label = str(path.relative_to(run_dir))
        for row in rows:
            kind = row.get("kind")
            name = str(row.get("name", ""))
            if kind in ("alert", "probe"):
                pass
            elif kind == "counter" and name in TIMELINE_COUNTERS:
                pass
            else:
                continue
            at = _aligned_ts(row, offsets)
            if at is None:
                continue
            t0 = at if t0 is None else min(t0, at)
            collected.append((at, label, int(row.get("seq") or 0),
                              kind, name, row))
    collected.sort(key=lambda r: (int(round(r[0] * 1e6)), r[1], r[2],
                                  r[4]))
    for at, label, seq, kind, name, row in collected:
        rows_out.append({
            "t_s": round(int(round((at - t0) * 1e6)) / 1e6, 6),
            "file": label,
            "kind": kind,
            "name": name,
            "detail": _detail(row),
        })
    if limit is not None and limit > 0:
        rows_out = rows_out[-limit:]
    return rows_out


def format_timeline(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "timeline: (no operations events)"
    lines = [f"timeline ({len(rows)} events, t=0 at first event):"]
    for r in rows:
        detail = f"  {r['detail']}" if r["detail"] else ""
        lines.append(
            f"  +{r['t_s']:12.6f}s  {r['name']:<28} [{r['file']}]{detail}")
    return "\n".join(lines)


# -- status ------------------------------------------------------------------


def _pointer_status(pointer_root) -> Optional[Dict[str, Any]]:
    from ..reliability.promotion import read_pointer

    try:
        pointer = read_pointer(pointer_root)
    except Exception:
        return {"error": "unreadable pointer"}
    if not pointer:
        return None
    return {
        "generation": pointer.get("generation"),
        "params_fingerprint": str(
            pointer.get("params_fingerprint") or "")[:16],
        "source": pointer.get("source"),
        "promoted_at": pointer.get("promoted_at"),
        "members": len(pointer.get("members") or []),
        "history": len(pointer.get("history") or []),
        "rolled_back_from": pointer.get("rolled_back_from"),
    }


def _ledger_status(run_dir: Path,
                   pointer_root) -> Optional[Dict[str, Any]]:
    """Refit/ledger coverage: completed bucket records (and quarantines)
    under a ``sweep_ledger`` next to the run dir or the pointer root."""
    candidates = [run_dir / "sweep_ledger"]
    if pointer_root:
        root = Path(pointer_root)
        if root.name.endswith(".json"):
            root = root.parent
        candidates.append(root / "sweep_ledger")
    for ledger_dir in candidates:
        records = ledger_dir / "records"
        if not records.is_dir():
            continue
        done = sorted(p.name for p in records.glob("*.json")
                      if not p.name.endswith(".sha256"))
        quarantined = sorted(
            p.name for p in ledger_dir.glob("quarantine/*.json"))
        return {"dir": ledger_dir.name, "records": len(done),
                "quarantined": len(quarantined)}
    return None


def _replica_status(run_dir: Path) -> List[Dict[str, Any]]:
    out = []
    for rdir in sorted(run_dir.glob("replica*")):
        if not rdir.is_dir():
            continue
        rows = read_jsonl(rdir / "events.jsonl")
        generation = fingerprint = None
        for row in rows:
            if (row.get("kind") == "counter"
                    and row.get("name") == "serve/generation"):
                generation = row.get("generation")
                fingerprint = row.get("fingerprint")
        hb = read_state(rdir / "heartbeat.json").get("heartbeat") or {}
        sup = read_jsonl(
            run_dir / f"events.supervisor.{rdir.name}.jsonl")
        restarts = sum(1 for r in sup
                       if r.get("kind") == "counter"
                       and r.get("name") == "supervise/restart")
        out.append({
            "replica": rdir.name,
            "generation": generation,
            "fingerprint": fingerprint,
            "heartbeat_section": hb.get("section"),
            "heartbeat_ts": hb.get("ts"),
            "restarts": restarts,
        })
    return out


def _count(rows, kind: str, name: str) -> int:
    return sum(1 for r in rows
               if r.get("kind") == kind and r.get("name") == name)


def gather_status(run_dir, pointer_root=None) -> Dict[str, Any]:
    """The current cross-plane posture of one fleet/serving run dir,
    derived ONLY from on-disk artifacts (byte-deterministic)."""
    run_dir = Path(run_dir)
    from ..serving.fleet import read_fleet_json

    fleet = read_fleet_json(run_dir)
    if pointer_root is None and fleet:
        pointer_root = fleet.get("pointer")
    rows: List[Dict[str, Any]] = []
    for path in trace_file_paths(run_dir):
        rows.extend(read_jsonl(path))

    scale_ups = scale_downs = scale_failed = 0
    replicas_gauge = None
    for r in rows:
        if r.get("name") == "fleet/scale" and r.get("kind") == "counter":
            d = str(r.get("direction") or "")
            if d == "up":
                scale_ups += 1
            elif d == "down":
                scale_downs += 1
            else:
                scale_failed += 1
        elif (r.get("name") == "fleet/replicas"
                and r.get("kind") == "gauge"):
            replicas_gauge = r.get("value")

    # SLO posture from the durable alert rows: the last transition per
    # (objective, window) decides firing/resolved; burn gauges report the
    # last recorded value per (objective, window)
    scan = scan_slo_rows(rows)
    firing = []
    resolved = 0
    for (objective, window), row in sorted(scan["last_state"].items()):
        if row.get("name") == "alert/firing":
            firing.append({
                "objective": objective, "window": window,
                "severity": row.get("severity"),
                "burn_long": row.get("burn_long"),
                "ts": row.get("ts"),
            })
        else:
            resolved += 1
    slo = None
    if (scan["last_state"] or scan["burn"] or scan["probe_checks"]
            or scan["probe_failures"] or scan["layout_unreadable"]):
        slo = {
            "firing": firing,
            "alerts_resolved": resolved,
            "burn_rates": {
                f"{o} {w}": v
                for (o, w), v in sorted(scan["burn"].items())},
            "budget_remaining": {
                f"{o} {w}": v
                for (o, w), v in sorted(scan["budget"].items())},
            "probe": {
                "checks": scan["probe_checks"],
                "failures": scan["probe_failures"],
                "digest_changes": scan["digest_changes"],
                "layout_unreadable": scan["layout_unreadable"],
            },
        }

    health = None
    drift_alerts = _count(rows, "counter", "model/drift_alert")
    canaries = [r for r in rows
                if r.get("kind") == "counter"
                and r.get("name") == "serve/canary"]
    guard_trips = _count(rows, "counter", "guard/trip")
    if drift_alerts or canaries or guard_trips:
        last = canaries[-1] if canaries else {}
        health = {
            "drift_alerts": drift_alerts,
            "canary_swaps": len(canaries),
            "last_canary": {
                k: last.get(k) for k in
                ("max_weight_delta", "max_sdf_delta", "finite")
                if last.get(k) is not None} or None,
            "guard_trips": guard_trips,
        }

    return {
        "run_dir": str(run_dir),
        "fleet": fleet,
        "pointer": (_pointer_status(pointer_root)
                    if pointer_root else None),
        "replicas": _replica_status(run_dir),
        "autoscaler": ({
            "scale_ups": scale_ups, "scale_downs": scale_downs,
            "scale_failed": scale_failed,
            "replicas_gauge": replicas_gauge,
        } if (scale_ups or scale_downs or scale_failed
              or replicas_gauge is not None) else None),
        "ledger": _ledger_status(run_dir, pointer_root),
        "model_health": health,
        "slo": slo,
        "promotions": {
            "advances": _count(rows, "counter", "promote/advance"),
            "rejections": _count(rows, "counter", "promote/reject"),
            "rollbacks": (_count(rows, "counter", "promote/rollback")
                          + _count(rows, "counter",
                                   "promote/fleet_rollback")),
        },
    }


def format_status(s: Dict[str, Any]) -> str:
    lines = [f"ops status: {s['run_dir']}"]
    fleet = s.get("fleet")
    if fleet:
        ids = ",".join(str(i) for i in fleet.get("replica_ids") or [])
        lines.append(
            f"  fleet: {fleet.get('replicas')} live (ids {ids or '-'}) "
            f"on {fleet.get('host')}:{fleet.get('port')}  "
            f"ever={fleet.get('total_replicas_ever')}")
    else:
        lines.append("  fleet: (no fleet.json)")
    ptr = s.get("pointer")
    if ptr:
        if ptr.get("error"):
            lines.append(f"  pointer: {ptr['error']}")
        else:
            rb = (f"  rolled_back_from={ptr['rolled_back_from']}"
                  if ptr.get("rolled_back_from") is not None else "")
            lines.append(
                f"  pointer: generation {ptr.get('generation')} "
                f"fp {ptr.get('params_fingerprint')} "
                f"members={ptr.get('members')} "
                f"history={ptr.get('history')}{rb}")
    for rep in s.get("replicas") or []:
        lines.append(
            f"  {rep['replica']}: generation={rep.get('generation')} "
            f"fp={rep.get('fingerprint')} "
            f"hb={rep.get('heartbeat_section')} "
            f"restarts={rep.get('restarts')}")
    auto = s.get("autoscaler")
    if auto:
        lines.append(
            f"  autoscaler: ups={auto['scale_ups']} "
            f"downs={auto['scale_downs']} failed={auto['scale_failed']} "
            f"replicas_gauge={auto.get('replicas_gauge')}")
    ledger = s.get("ledger")
    if ledger:
        lines.append(
            f"  ledger: {ledger['records']} records "
            f"({ledger['quarantined']} quarantined) [{ledger['dir']}]")
    health = s.get("model_health")
    if health:
        lines.append(
            f"  model health: drift_alerts={health['drift_alerts']} "
            f"canary_swaps={health['canary_swaps']} "
            f"guard_trips={health['guard_trips']}")
    promos = s.get("promotions") or {}
    if any(promos.values()):
        lines.append(
            f"  promotions: advances={promos['advances']} "
            f"rejections={promos['rejections']} "
            f"rollbacks={promos['rollbacks']}")
    slo = s.get("slo")
    if slo:
        if slo["firing"]:
            for a in slo["firing"]:
                burn = (f" burn={a['burn_long']:.4g}"
                        if isinstance(a.get("burn_long"),
                                      (int, float)) else "")
                lines.append(
                    f"  ALERT FIRING: {a['objective']} [{a['window']}] "
                    f"severity={a['severity']}{burn}")
        else:
            lines.append(
                f"  slo: no firing alerts "
                f"({slo['alerts_resolved']} resolved)")
        for key, v in (slo.get("budget_remaining") or {}).items():
            if isinstance(v, (int, float)):
                lines.append(f"    budget remaining {key}: {v:.4g}")
        probe = slo.get("probe") or {}
        lines.append(
            f"  probe: {probe.get('checks', 0)} checks, "
            f"{probe.get('failures', 0)} failures, "
            f"{probe.get('digest_changes', 0)} digest changes")
    elif slo is None:
        lines.append("  slo: (no probe/alert telemetry)")
    return "\n".join(lines)


# -- CLI ---------------------------------------------------------------------


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearninginassetpricing_paperreplication_tpu"
             ".ops",
        description="Cross-plane ops console over one serving/fleet run "
                    "dir (read-only, byte-deterministic)")
    sub = p.add_subparsers(dest="cmd", required=True)
    st = sub.add_parser("status", help="current cross-plane posture")
    st.add_argument("run_dir")
    st.add_argument("--pointer", type=str, default=None,
                    help="promotion pointer root (default: the one "
                         "fleet.json records)")
    st.add_argument("--json", action="store_true", dest="as_json")
    tl = sub.add_parser("timeline", help="merged operations timeline")
    tl.add_argument("run_dir")
    tl.add_argument("--limit", type=int, default=None,
                    help="only the newest N events")
    tl.add_argument("--json", action="store_true", dest="as_json")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if not Path(args.run_dir).is_dir():
        print(f"ops: no such run dir: {args.run_dir}", file=sys.stderr)
        return 2
    if args.cmd == "status":
        s = gather_status(args.run_dir, pointer_root=args.pointer)
        if args.as_json:
            print(json.dumps(s, indent=2, sort_keys=True))
        else:
            print(format_status(s))
        return 0
    rows = gather_timeline(args.run_dir, limit=args.limit)
    if args.as_json:
        print(json.dumps(rows, indent=2, sort_keys=True))
    else:
        print(format_timeline(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
