"""Durable sweep ledger: one verified record per completed bucket.

The 384-config search's unit of work is the architecture bucket (~96 of
them), but until this module its unit of RECOVERY was the whole search: a
crash anywhere lost every completed bucket because the only resume point
was the finished ranking JSON. The ledger makes the bucket the unit of
recovery (the TorchElastic / Ray-Tune trial-level fault-tolerance shape,
PAPERS.md): every completed bucket lands as one atomic, sha256-sidecar JSON
record — written through :mod:`reliability.verified`, so a kill mid-write
can never corrupt it — keyed by the content that determines the bucket's
result (architecture signature + lr grid + seeds + TrainConfig). A
restarted sweep consults the ledger and re-trains nothing it already holds;
rankings are reconstructed from records alone.

Layout under ``<run_dir>/sweep_ledger/``::

    queue.json             — the work manifest (bucket list + shared
                             schedule), written once by the coordinating
                             process; workers derive ALL work from it
    records/<key>.json     — one verified record per completed bucket
    quarantine/<key>.json  — poison buckets (killed K consecutive workers)
    leases/<key>.json      — live worker leases (see scheduler.py)
    attempts/<key>.json    — per-bucket claim/failure history

Records never hold params (they are JSON): ledger-backed sweeps run with
``keep_params=False`` — the protocol path, which retrains winners anyway.

IMPORTANT: module level must stay stdlib-only (like ``faults.py`` /
``verified.py``): report tooling and thin parents read ledgers without
paying the jax import.
"""

from __future__ import annotations

import hashlib
import json
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from .faults import inject
from .verified import clear_generations, load_verified, verified_exists, write_verified

LEDGER_DIRNAME = "sweep_ledger"
QUEUE_FILENAME = "queue.json"


def bucket_key(
    config: Dict[str, Any],
    lrs: List[float],
    seeds: List[int],
    tcfg: Dict[str, Any],
) -> str:
    """Content key of one bucket's work: sha256 over the canonical JSON of
    everything that determines its result — the architecture config dict,
    the lr grid (ORDER KEPT: it fixes the vmapped grid layout), the seeds,
    and the training schedule. Two runs computing the same key would train
    bit-identical buckets, so a record under this key is safe to reuse."""
    blob = json.dumps(
        {
            "config": config,
            "lrs": [float(lr) for lr in lrs],
            "seeds": [int(s) for s in seeds],
            "tcfg": tcfg,
        },
        sort_keys=True,
        default=str,
    ).encode()
    return hashlib.sha256(blob).hexdigest()


def _finite_or_none(x) -> Optional[float]:
    """JSON-safe scalar (mirrors sweep.py's _finite: non-finite → null)."""
    import math

    x = float(x)
    return x if math.isfinite(x) else None


def make_record(
    key: str,
    index: int,
    config: Dict[str, Any],
    lrs: List[float],
    seeds: List[int],
    grid,
    best_valid_sharpe,
    worker: Optional[str] = None,
    seconds: Optional[float] = None,
) -> Dict[str, Any]:
    """Assemble one bucket's ledger record from a ``train_bucket`` output.

    ``grid`` is the [(lr, seed)] array, ``best_valid_sharpe`` the matching
    Sharpe vector; floats round-trip JSON exactly (repr round-trip), so a
    ranking reconstructed from records is bit-identical to the in-process
    one. Non-finite Sharpes (never-updated trackers) map to null and back
    to -inf on read, the same convention as ``sweep_ranking.json``."""
    return {
        "key": key,
        "index": int(index),
        "config": config,
        "lrs": [float(lr) for lr in lrs],
        "seeds": [int(s) for s in seeds],
        "grid": [[float(lr), float(s)] for lr, s in grid],
        "best_valid_sharpe": [_finite_or_none(s) for s in best_valid_sharpe],
        "worker": worker,
        "seconds": round(float(seconds), 3) if seconds is not None else None,
        "completed_at": round(time.time(), 3),
    }


class SweepLedger:
    """Verified per-bucket records + quarantine markers for one sweep.

    All writes go through :func:`reliability.verified.write_verified`
    (atomic + sha256 sidecar), all reads through :func:`load_verified`
    (digest-checked, clear errors naming the file). Instance counters
    (``hits`` / ``writes``) carry the zero-retrain evidence the fault-matrix
    tests assert on."""

    def __init__(self, root: Union[str, Path]):
        self.root = Path(root)
        self.records_dir = self.root / "records"
        self.quarantine_dir = self.root / "quarantine"
        self.hits = 0
        self.writes = 0

    # -- records --------------------------------------------------------------

    def record_path(self, key: str) -> Path:
        return self.records_dir / f"{key}.json"

    def has(self, key: str) -> bool:
        return verified_exists(self.record_path(key))

    def load(self, key: str) -> Dict[str, Any]:
        """Digest-verified record read; counts as a ledger hit."""
        path = self.record_path(key)

        def parse(data: bytes) -> Dict[str, Any]:
            try:
                return json.loads(data.decode())
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ValueError(
                    f"corrupt sweep-ledger record {path}: {e}") from e

        record, _ = load_verified(path, parse)
        self.hits += 1
        return record

    def write(self, key: str, record: Dict[str, Any]) -> None:
        """Verified write of one completed bucket's record. The fault site
        fires BEFORE any byte lands: a kill here loses the record (the
        bucket re-trains after restart) but never corrupts the ledger."""
        path = self.record_path(key)
        inject("sweep/ledger_write", path=str(path), bucket=key)
        write_verified(path, json.dumps(record, indent=2).encode())
        self.writes += 1

    def keys(self) -> List[str]:
        # "*.json" cannot match sidecars (.json.sha256), generations
        # (.json.g1), or in-flight tmp files (.json.tmp)
        if not self.records_dir.exists():
            return []
        return sorted(p.stem for p in self.records_dir.glob("*.json"))

    # -- quarantine -----------------------------------------------------------

    def quarantine_path(self, key: str) -> Path:
        return self.quarantine_dir / f"{key}.json"

    def quarantine(self, key: str, info: Dict[str, Any]) -> None:
        info = dict(info, key=key, quarantined_at=round(time.time(), 3))
        write_verified(self.quarantine_path(key),
                       json.dumps(info, indent=2).encode())

    def is_quarantined(self, key: str) -> bool:
        return verified_exists(self.quarantine_path(key))

    def quarantined(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        if not self.quarantine_dir.exists():
            return out
        for p in sorted(self.quarantine_dir.glob("*.json")):
            try:
                out[p.stem] = json.loads(p.read_text())
            except (OSError, json.JSONDecodeError):
                out[p.stem] = {"key": p.stem, "error": "unreadable marker"}
        return out

    # -- lifecycle ------------------------------------------------------------

    def reset(self) -> None:
        """Drop every record, quarantine marker, lease, and attempt file —
        a NON-resuming sweep must not silently reuse a predecessor's work."""
        import shutil

        for sub in ("records", "quarantine", "leases", "attempts"):
            shutil.rmtree(self.root / sub, ignore_errors=True)
        clear_generations(self.root / QUEUE_FILENAME)
