"""Tier-1 coverage for the live telemetry plane (PR 8).

Covers the four tentpole pieces end to end, CPU-only:
  * trace assembly (observability/trace.py): multi-file merge determinism,
    per-process/per-thread lanes, clock alignment, dangling-span synthesis,
    instant events, counter tracks, and ``report --trace`` on a REAL
    supervised multi-process training run (the acceptance criterion);
  * streaming metrics (observability/metrics.py): registry semantics, the
    Prometheus text wire format parsed back, histogram bucket monotonicity,
    derived percentiles, the EventLog→registry bridge, the read-only
    scrape sidecar, and ``/metrics?format=prom`` on the async server
    agreeing with the report CLI on the same run;
  * XLA program introspection (observability/xla.py): cost/memory analysis
    captured into ``manifest.json`` for trainer phase programs and serving
    bucket programs, shown by the report CLI;
  * the budget gate (observability/budgets.py): pass, fail, missing
    metric, tolerance edges, malformed specs, ``report --budget`` exit
    codes, and the tier-1 validation of the shipped ``budgets.json``
    against the checked-in BENCH_*.json artifacts.

Plus the crash-consistency satellite (span_end/counter fsync policy) and
the ruff lint gate extended to the new modules.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
from deeplearninginassetpricing_paperreplication_tpu.observability import (
    EventLog,
    MetricsRegistry,
    MetricsSidecar,
    parse_prom_text,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.budgets import (
    BudgetSpecError,
    check_budgets,
    check_entry,
    format_budget_report,
    load_budgets,
    resolve_metric,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.metrics import (
    DEFAULT_BUCKETS_S,
    prom_name,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
    format_summary,
    latency_percentiles_ms,
    load_run,
    summarize_run,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
    main as report_main,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.trace import (
    assemble_trace,
    write_trace,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.xla import (
    analyze_compiled,
    record_program,
)
from deeplearninginassetpricing_paperreplication_tpu.serving import (
    AsyncServerThread,
    InferenceEngine,
    ServingService,
)
from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
    save_params,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import GANConfig

REPO = Path(__file__).resolve().parents[1]
PKG = "deeplearninginassetpricing_paperreplication_tpu"


# --------------------------------------------------------------------------
# metrics registry + Prometheus wire format
# --------------------------------------------------------------------------

def test_prom_name_mapping():
    assert prom_name("serve/requests", "counter") == "dlap_serve_requests_total"
    assert prom_name("startup/peak_rss", "gauge") == "dlap_startup_peak_rss"
    assert prom_name("serve/request", "span") == "dlap_span_serve_request_seconds"
    # arbitrary characters sanitize instead of producing invalid series
    assert prom_name("a b/c-d", "gauge") == "dlap_a_b_c_d"


def test_registry_counter_gauge_histogram_roundtrip():
    reg = MetricsRegistry()
    reg.counter("dlap_x_total", 2, {"endpoint": "/v1/weights"})
    reg.counter("dlap_x_total", 3, {"endpoint": "/v1/weights"})
    reg.counter("dlap_x_total", 1, {"endpoint": "/v1/sdf"})
    reg.gauge("dlap_g", 7.5)
    for v in (0.0004, 0.003, 0.003, 0.2, 50.0, 500.0):
        reg.observe("dlap_lat_seconds", v)
    text = reg.render_prom()
    assert text == reg.render_prom()  # deterministic byte-for-byte
    parsed = parse_prom_text(text)
    assert parsed["dlap_x_total"][(("endpoint", "/v1/weights"),)] == 5
    assert parsed["dlap_x_total"][(("endpoint", "/v1/sdf"),)] == 1
    assert parsed["dlap_g"][()] == 7.5
    assert parsed["dlap_lat_seconds_count"][()] == 6
    assert parsed["dlap_lat_seconds_sum"][()] == pytest.approx(550.2064)


def test_histogram_buckets_monotone_and_complete():
    reg = MetricsRegistry()
    rng = np.random.default_rng(0)
    values = rng.exponential(0.05, size=200)
    for v in values:
        reg.observe("dlap_lat_seconds", float(v))
    parsed = parse_prom_text(reg.render_prom())
    cums = [parsed["dlap_lat_seconds_bucket"][(("le", le),)]
            for le in [str(b).rstrip("0").rstrip(".")
                       if float(b) != int(b) else str(int(b))
                       for b in DEFAULT_BUCKETS_S]]
    # cumulative counts never decrease; +Inf equals the total count
    assert cums == sorted(cums)
    assert parsed["dlap_lat_seconds_bucket"][(("le", "+Inf"),)] == 200
    assert cums[-1] <= 200


def test_derived_percentiles_bucket_consistent():
    reg = MetricsRegistry()
    values = [0.002] * 90 + [0.3] * 9 + [2.0]
    for v in values:
        reg.observe("dlap_lat_seconds", v)
    # exact nearest-rank vs the histogram's bucket-resolution answer: the
    # derived percentile is the upper bound of the bucket holding the rank
    exact = latency_percentiles_ms(values)
    parsed = parse_prom_text(reg.render_prom())
    for p in (50, 95, 99):
        derived_s = parsed[f"dlap_lat_seconds_p{p}"][()]
        exact_s = exact[f"p{p}_ms"] / 1e3
        expected = next(b for b in DEFAULT_BUCKETS_S if exact_s <= b)
        assert derived_s == pytest.approx(expected)


def test_parse_prom_rejects_malformed_line():
    with pytest.raises(ValueError, match="malformed"):
        parse_prom_text("this is { not a metric\n")


def test_prom_label_escaping_roundtrips():
    # backslash-then-n, quote, and a REAL newline: render escapes, parse
    # must invert in ONE pass (sequential replaces corrupt r'\\n' into
    # backslash + LF)
    nasty = 'a\\nb"c\nd'
    reg = MetricsRegistry()
    reg.counter("dlap_x_total", 1, {"endpoint": nasty})
    parsed = parse_prom_text(reg.render_prom())
    assert parsed["dlap_x_total"][(("endpoint", nasty),)] == 1


def test_eventlog_feeds_registry(tmp_path):
    log = EventLog(tmp_path)
    with log.span("serve/request", endpoint="/v1/weights"):
        pass
    log.counter("serve/requests", endpoint="/v1/weights", status=200)
    log.counter("serve/requests", endpoint="/v1/weights", status=200)
    log.gauge("queue_depth", 3)
    log.close()
    parsed = parse_prom_text(log.metrics.render_prom())
    key = (("endpoint", "/v1/weights"), ("status", "200"))
    assert parsed["dlap_serve_requests_total"][key] == 2
    assert parsed["dlap_queue_depth"][()] == 3
    assert parsed["dlap_span_serve_request_seconds_count"][
        (("endpoint", "/v1/weights"), ("status", "ok"))] == 1


def test_eventlog_rows_carry_small_thread_ids(tmp_path):
    log = EventLog(tmp_path)
    log.counter("a")
    t = threading.Thread(target=lambda: log.counter("a"))
    t.start()
    t.join()
    log.close()
    rows = [json.loads(line) for line in
            (log.path).read_text().splitlines()]
    assert sorted({r["tid"] for r in rows}) == [0, 1]


def test_eventlog_fsync_policy(tmp_path, monkeypatch):
    # interval 0: every span_end/counter row is fsync'd — the row must be
    # durable on disk immediately, without close()
    monkeypatch.setenv("DLAP_EVENTS_FSYNC_S", "0")
    log = EventLog(tmp_path)
    log.counter("durable/row")
    on_disk = (tmp_path / "events.jsonl").read_text()
    assert '"durable/row"' in on_disk
    log.close()
    # negative disables fsync but rows still flush per line
    monkeypatch.setenv("DLAP_EVENTS_FSYNC_S", "-1")
    log2 = EventLog(tmp_path, filename="events.nofsync.jsonl")
    assert log2._fsync_interval == -1
    log2.counter("x")
    log2.close()


def test_metrics_sidecar_scrape():
    reg = MetricsRegistry()
    reg.counter("dlap_jobs_total", 4, {"worker": "w0"})
    sidecar = MetricsSidecar([reg])
    port = sidecar.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            parsed = parse_prom_text(resp.read().decode())
        assert parsed["dlap_jobs_total"][(("worker", "w0"),)] == 4
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert json.loads(resp.read())["ok"] is True
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
    finally:
        sidecar.stop()


# --------------------------------------------------------------------------
# trace assembly (synthetic run dirs: fast, exhaustive)
# --------------------------------------------------------------------------

def _write_rows(path, rows):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))


def _row(kind, name, ts, mono, run_id="r1", tid=0, **extra):
    return {"kind": kind, "name": name, "ts": ts, "mono": mono,
            "run_id": run_id, "tid": tid, "process_index": 0, **extra}


def test_trace_multi_file_merge_deterministic(tmp_path):
    log = EventLog(tmp_path)
    with log.span("phase/one"):
        log.counter("epochs_dispatched", value=4, phase="p1")
    log.gauge("startup/peak_rss", 100)
    log.close()
    _write_rows(tmp_path / "events.proc1.jsonl", [
        _row("span_begin", "worker/load", 1000.0, 5.0, run_id="w"),
        _row("span_end", "worker/load", 1001.0, 6.0, run_id="w",
             duration_s=1.0),
    ])
    _write_rows(tmp_path / "events.supervisor.jsonl", [
        _row("counter", "supervise/restart", 1000.5, 0.5, run_id="s",
             section="phase1", value=1),
    ])
    _write_rows(tmp_path / "replica0" / "events.jsonl", [
        _row("span_end", "serve/request", 1002.0, 9.0, run_id="q",
             duration_s=0.25, endpoint="/v1/weights"),
    ])
    out1, out2 = tmp_path / "t1.json", tmp_path / "t2.json"
    info = write_trace(tmp_path, out1)
    write_trace(tmp_path, out2)
    assert out1.read_bytes() == out2.read_bytes()  # deterministic
    assert info["n_files"] == 4  # every process's file is covered
    trace = json.loads(out1.read_text())
    events = trace["traceEvents"]
    names = {(e["ph"], e["name"]) for e in events}
    assert ("X", "phase/one") in names
    assert ("X", "worker/load") in names
    assert ("X", "serve/request") in names
    assert ("i", "supervise/restart") in names  # restart → instant mark
    assert ("C", "epochs_dispatched") in names
    assert ("C", "startup/peak_rss") in names
    # one pid per file, metadata names them
    pids = {e["pid"] for e in events}
    assert len(pids) == 4
    proc_names = {e["args"]["name"] for e in events
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert proc_names == {"events.jsonl", "events.proc1.jsonl",
                          "events.supervisor.jsonl",
                          "replica0/events.jsonl"}


def test_trace_clock_alignment_across_processes(tmp_path):
    # two processes, wildly different monotonic bases, overlapping wall
    # clocks: alignment must order spans by WALL time
    _write_rows(tmp_path / "events.jsonl", [
        _row("span_end", "a/first", ts=100.0, mono=5000.0, duration_s=1.0),
    ])
    _write_rows(tmp_path / "events.proc1.jsonl", [
        _row("span_end", "b/second", ts=103.0, mono=7.0, run_id="p1",
             duration_s=1.0),
    ])
    trace = assemble_trace(tmp_path)
    spans = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    # a/first ran [99, 100], b/second [102, 103] on the wall clock
    assert spans["a/first"]["ts"] < spans["b/second"]["ts"]
    assert spans["b/second"]["ts"] - spans["a/first"]["ts"] == pytest.approx(
        3e6, abs=1e4)


def test_trace_synthesizes_dangling_span_ends(tmp_path):
    # a SIGKILLed writer: span_begin with no end, then more rows
    _write_rows(tmp_path / "events.jsonl", [
        _row("span_begin", "phase/killed", 10.0, 1.0),
        _row("counter", "epochs_dispatched", 12.0, 3.0, value=2),
    ])
    trace = assemble_trace(tmp_path)
    synth = [e for e in trace["traceEvents"]
             if e["ph"] == "X" and e["args"].get("synthesized_end")]
    assert len(synth) == 1
    assert synth[0]["name"] == "phase/killed"
    # truncated bar runs from the begin to the file's last timestamp
    assert synth[0]["dur"] == pytest.approx(2e6, abs=1e4)
    assert trace["otherData"]["n_synthesized_ends"] == 1
    # a CLOSED span must not also be synthesized
    _write_rows(tmp_path / "events.jsonl", [
        _row("span_begin", "phase/ok", 10.0, 1.0),
        _row("span_end", "phase/ok", 11.0, 2.0, duration_s=1.0),
    ])
    trace = assemble_trace(tmp_path)
    assert trace["otherData"]["n_synthesized_ends"] == 0


def test_trace_threads_get_separate_lanes(tmp_path):
    _write_rows(tmp_path / "events.jsonl", [
        _row("span_end", "compile/a", 10.0, 1.0, tid=1, duration_s=0.5),
        _row("span_end", "compile/b", 10.1, 1.1, tid=2, duration_s=0.5),
    ])
    trace = assemble_trace(tmp_path)
    lanes = {e["name"]: e["tid"] for e in trace["traceEvents"]
             if e["ph"] == "X"}
    assert lanes["compile/a"] != lanes["compile/b"]


def test_trace_fault_rows_without_mono_align_by_wall(tmp_path):
    _write_rows(tmp_path / "events.jsonl", [
        _row("span_end", "phase/x", 100.0, 50.0, duration_s=1.0),
    ])
    # fault-injector append: ts only, no mono, no run_id
    (tmp_path / "events.faults.jsonl").write_text(json.dumps(
        {"kind": "counter", "name": "fault/injected", "value": 1,
         "site": "trainer/epoch_loop", "action": "kill",
         "ts": 100.5}) + "\n")
    trace = assemble_trace(tmp_path)
    instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert len(instants) == 1
    assert instants[0]["args"] == {"site": "trainer/epoch_loop",
                                   "action": "kill"}
    span = next(e for e in trace["traceEvents"] if e["ph"] == "X")
    # the kill mark lands inside the span's wall window
    assert span["ts"] < instants[0]["ts"] <= span["ts"] + span["dur"] + 1e6


def test_trace_empty_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="nothing to trace"):
        assemble_trace(tmp_path)


def test_report_trace_with_json_keeps_stdout_parseable(tmp_path, capsys):
    run = tmp_path / "run"
    _write_rows(run / "events.jsonl", [
        _row("span_end", "phase/x", 100.0, 50.0, duration_s=1.0),
    ])
    out = tmp_path / "t.json"
    assert report_main([str(run), "--trace", str(out), "--json"]) == 0
    captured = capsys.readouterr()
    json.loads(captured.out)  # --json owns stdout: must stay pure JSON
    assert "trace written to" in captured.err


# --------------------------------------------------------------------------
# XLA program introspection
# --------------------------------------------------------------------------

def test_analyze_compiled_on_cpu_program(tmp_path):
    compiled = (
        jax.jit(lambda x: (x @ x).sum())
        .lower(jax.ShapeDtypeStruct((32, 32), np.float32))
        .compile()
    )
    analyses = {}
    log = EventLog(tmp_path)
    a = record_program(log, "toy", compiled, analyses_out=analyses,
                       program="toy")
    log.close()
    assert analyses["toy"] is a
    assert a["cost_available"] is True and a["flops"] > 0
    assert a["bytes_accessed"] > 0
    assert a["memory_available"] is True
    assert a["peak_memory_bytes"] > 0
    # the event row carries the same analysis (the report CLI's fallback)
    rows = [json.loads(line) for line in log.path.read_text().splitlines()]
    prog_rows = [r for r in rows if r["kind"] == "program"]
    assert prog_rows and prog_rows[0]["analysis"]["flops"] == a["flops"]


def test_analyze_compiled_absent_with_reason():
    class NoAPIs:
        def cost_analysis(self):
            raise NotImplementedError("no cost analysis on this backend")

        def memory_analysis(self):
            return None

    a = analyze_compiled(NoAPIs())
    assert a["cost_available"] is False
    assert "NotImplementedError" in a["cost_reason"]
    assert a["memory_available"] is False
    assert a["memory_reason"] == "memory_analysis returned None"


# --------------------------------------------------------------------------
# budget gate
# --------------------------------------------------------------------------

def test_resolve_metric_dotted_paths():
    doc = {"a": {"b": [10, {"c": 7}]}}
    assert resolve_metric(doc, "a.b.0") == 10
    assert resolve_metric(doc, "a.b.1.c") == 7
    with pytest.raises(KeyError, match="failed at 'a.z'"):
        resolve_metric(doc, "a.z.c")


def test_check_entry_bounds_and_tolerance_edges():
    # min with 10% tolerance: floor is 90 — 90 passes, just under fails
    e = {"name": "n", "metric": "v", "min": 100, "tolerance": 0.1}
    assert check_entry(e, {"v": 90.0}, "f")["ok"] is True
    assert check_entry(e, {"v": 89.999}, "f")["ok"] is False
    # max with tolerance: ceiling 110
    e = {"name": "n", "metric": "v", "max": 100, "tolerance": 0.1}
    assert check_entry(e, {"v": 110.0}, "f")["ok"] is True
    assert check_entry(e, {"v": 110.01}, "f")["ok"] is False
    # equals is an ABSOLUTE band (recompiles == 0 must not be vacuous)
    e = {"name": "n", "metric": "v", "equals": 0}
    assert check_entry(e, {"v": 0}, "f")["ok"] is True
    bad = check_entry(e, {"v": 1}, "f")
    assert bad["ok"] is False and "!=" in bad["reason"]
    # missing metric and non-numeric values fail loudly
    assert "missing metric" in check_entry(e, {}, "f")["reason"]
    assert check_entry(e, {"v": "fast"}, "f")["ok"] is False
    assert check_entry(e, {"v": True}, "f")["ok"] is False


def test_budget_spec_validation(tmp_path):
    p = tmp_path / "b.json"
    p.write_text("{}")
    with pytest.raises(BudgetSpecError, match="non-empty list"):
        load_budgets(p)
    p.write_text(json.dumps({"budgets": [{"name": "x", "metric": "m"}]}))
    with pytest.raises(BudgetSpecError, match="min/max/equals"):
        load_budgets(p)
    p.write_text(json.dumps(
        {"budgets": [{"name": "x", "metric": "m", "min": 1,
                      "tolerance": -0.5}]}))
    with pytest.raises(BudgetSpecError, match="tolerance"):
        load_budgets(p)


def test_check_budgets_missing_file_and_runscoped(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"budgets": [
        {"name": "gone", "file": "nope.json", "metric": "x", "min": 1},
        {"name": "run", "metric": "wall_clock_s", "max": 100},
    ]}))
    result = check_budgets(p)
    assert result["ok"] is False
    by_name = {c["name"]: c for c in result["checks"]}
    assert "unreadable" in by_name["gone"]["reason"]
    assert "no run dir" in by_name["run"]["reason"]
    # with a run summary, the run-scoped entry resolves
    result = check_budgets(p, {"rd": {"wall_clock_s": 50}})
    assert by_name["gone"]["ok"] is False
    assert {c["name"]: c["ok"] for c in result["checks"]}["run"] is True
    assert "REGRESSION" in format_budget_report(result)


def test_check_budgets_file_overrides(tmp_path):
    """bench.py --out X --check_budgets gates the artifact it JUST wrote:
    an override redirects a named file entry away from the checked-in
    copy next to the budget file."""
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"budgets": [
        {"name": "x", "file": "BENCH_X.json", "metric": "v", "min": 4}]}))
    (tmp_path / "BENCH_X.json").write_text(json.dumps({"v": 100}))  # stale
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps({"v": 3}))  # regressed re-bench
    assert check_budgets(p)["ok"] is True
    assert check_budgets(
        p, file_overrides={"BENCH_X.json": fresh})["ok"] is False


def test_shipped_budgets_pass_against_checked_in_benches():
    """THE tier-1 wiring: the repo's budgets.json validates against the
    checked-in BENCH_*.json trajectory (and the wrapper exits zero)."""
    result = check_budgets(REPO / "budgets.json")
    assert result["ok"], format_budget_report(result)
    assert report_main(["--budget", str(REPO / "budgets.json")]) == 0
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_budgets as wrapper
        assert wrapper.main([]) == 0
    finally:
        sys.path.pop(0)


def test_report_budget_exits_nonzero_on_injected_regression(tmp_path):
    budget = {"budgets": [{
        "name": "impossible_rps", "file": "BENCH_SERVING.json",
        "metric": "async_replicated.closed_loop_c32_bin.throughput_rps",
        "min": 1e9}]}
    p = tmp_path / "regressed.json"
    p.write_text(json.dumps(budget))
    # file paths resolve relative to the budget file: point at the repo
    (tmp_path / "BENCH_SERVING.json").write_text(
        (REPO / "BENCH_SERVING.json").read_text())
    assert report_main(["--budget", str(p)]) == 1
    # malformed spec: distinct exit code, never a silent pass
    p.write_text("{}")
    assert report_main(["--budget", str(p)]) == 2


# --------------------------------------------------------------------------
# serving: /metrics?format=prom + manifest xla_programs + metrics.prom
# --------------------------------------------------------------------------

T, N, F, M = 10, 48, 7, 5
SEEDS = (1, 2)


def _member(root, cfg, seed):
    d = root / f"seed_{seed}"
    d.mkdir(parents=True, exist_ok=True)
    cfg.save(d / "config.json")
    save_params(d / "best_model_sharpe.msgpack",
                GAN(cfg).init(jax.random.key(seed)))
    return str(d)


@pytest.fixture(scope="module")
def serve_run(tmp_path_factory):
    """A warmed async server that served real traffic, then shut down —
    one fixture feeding the prom-endpoint, manifest, metrics.prom, and
    report cross-check assertions."""
    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F,
                    hidden_dim=(8,), num_units_rnn=(4,))
    root = tmp_path_factory.mktemp("telemetry_serving")
    members = [_member(root, cfg, s) for s in SEEDS]
    run_dir = root / "run"
    rng = np.random.default_rng(3)
    macro = rng.standard_normal((T, M)).astype(np.float32)
    events = EventLog(run_dir)
    engine = InferenceEngine(members, macro_history=macro,
                             stock_buckets=(64,), batch_buckets=(1, 2),
                             events=events)
    service = ServingService(engine, run_dir=str(run_dir), events=events,
                             mode="async", cache_size=0)
    service.warmup()
    server = AsyncServerThread(service)
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    n_posts = 7
    for i in range(n_posts):
        body = json.dumps({
            "individual": rng.standard_normal((N, F)).tolist(),
            "month": -1}).encode()
        req = urllib.request.Request(f"{url}/v1/weights", data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 200
    with urllib.request.urlopen(f"{url}/metrics?format=prom",
                                timeout=30) as resp:
        prom_ctype = resp.headers["Content-Type"]
        prom_text = resp.read().decode()
    with urllib.request.urlopen(f"{url}/metrics", timeout=30) as resp:
        metrics_json = json.loads(resp.read())
    server.stop()
    service.close()
    events.close()
    return {"run_dir": run_dir, "engine": engine, "service": service,
            "prom_text": prom_text, "prom_ctype": prom_ctype,
            "metrics_json": metrics_json, "n_posts": n_posts}


def test_async_server_prom_endpoint_agrees_with_report(serve_run):
    assert serve_run["prom_ctype"].startswith("text/plain")
    parsed = parse_prom_text(serve_run["prom_text"])
    key = (("endpoint", "/v1/weights"), ("status", "200"))
    assert parsed["dlap_serve_requests_total"][key] == serve_run["n_posts"]
    # the JSON endpoint and the scrape agree on the same counter
    assert (serve_run["metrics_json"]["requests"]["/v1/weights 200"]
            == serve_run["n_posts"])
    # histogram cumulative counts monotone; steady-state gauge present
    assert parsed["dlap_serve_steady_state_recompiles"][()] == 0
    # percentile agreement with the report CLI on the same run: the exact
    # nearest-rank p99 from events must land exactly in the bucket the
    # derived prom percentile names
    summary = summarize_run(load_run(serve_run["run_dir"]))
    sv = summary["serving"]
    assert sv["requests"]["/v1/weights 200"] == serve_run["n_posts"]
    exact_s = sv["latency"]["p99_ms"] / 1e3
    derived = parsed["dlap_span_serve_request_seconds_p99"][()]
    expected_bucket = next(
        (b for b in DEFAULT_BUCKETS_S if exact_s <= b), None)
    assert derived == pytest.approx(expected_bucket)


def test_serving_manifest_carries_bucket_program_analysis(serve_run):
    manifest = json.loads(
        (serve_run["run_dir"] / "manifest.json").read_text())
    progs = manifest["xla_programs"]
    # every AOT program of the warmup: 1 stock bucket × 2 batch buckets
    # forwards + the macro LSTM step
    assert set(progs) == {"fwd_64x1", "fwd_64x2", "macro_step"}
    for a in progs.values():
        assert a["cost_available"] is True and a["flops"] > 0
        assert a["memory_available"] is True
    # report CLI renders the table
    summary = summarize_run(load_run(serve_run["run_dir"]))
    text = format_summary(summary)
    assert "AOT programs (XLA cost/memory analysis)" in text
    assert "fwd_64x2" in text


def test_metrics_prom_snapshot_crosschecks_clean(serve_run):
    # close() left the final scrape-format snapshot in the run dir
    snap = (serve_run["run_dir"] / "metrics.prom").read_text()
    parsed = parse_prom_text(snap)
    assert parsed["dlap_serve_steady_state_recompiles"][()] == 0
    summary = summarize_run(load_run(serve_run["run_dir"]))
    mc = summary["metrics_check"]
    assert mc["requests_agree"] is True
    assert mc["recompiles_agree"] is True
    assert mc["steady_state_recompiles"] == 0 and mc["steady_state_ok"]
    text = format_summary(summary)
    assert "steady-state recompiles (from metrics): 0  [OK]" in text


def test_threaded_route_serves_prom_too(serve_run):
    status, body = serve_run["service"].handle(
        "GET", "/metrics?format=prom", None)
    assert status == 200 and "_raw_text" in body
    parse_prom_text(body["_raw_text"])  # wire-format valid


def test_old_run_dir_summary_stays_stable(tmp_path):
    """A pre-telemetry-plane run dir gains NO new sections or keys."""
    (tmp_path / "events.jsonl").write_text(json.dumps(
        {"kind": "span_end", "name": "phase/phase1_unconditional",
         "duration_s": 1.0, "epochs": 4, "run_id": "r", "seq": 1,
         "ts": 1.0, "mono": 1.0}) + "\n")
    summary = summarize_run(load_run(tmp_path))
    assert "xla_programs" not in summary
    assert "metrics_check" not in summary
    text = format_summary(summary)
    assert "AOT programs" not in text
    assert "metrics cross-check" not in text


# --------------------------------------------------------------------------
# the acceptance criterion: report --trace on a REAL supervised
# multi-process run (supervisor + killed/restarted training CLI)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def supervised_run(synthetic_dir, tmp_path_factory):
    run_dir = tmp_path_factory.mktemp("supervised_telemetry")
    child = [sys.executable, "-m", f"{PKG}.train",
             "--data_dir", str(synthetic_dir),
             "--save_dir", str(run_dir),
             "--epochs_unc", "3", "--epochs_moment", "2", "--epochs", "4",
             "--ignore_epoch", "0", "--hidden_dim", "8", "--rnn_dim", "4",
             "--num_moments", "4", "--dropout", "0.0",
             "--print_freq", "100", "--metrics_port", "0"]
    cmd = [sys.executable, "-m", f"{PKG}.supervise",
           "--run_dir", str(run_dir),
           "--timeout", "300", "--poll", "0.2", "--backoff", "0.1",
           "--jitter", "0", "--min_uptime", "0.5", "--max_restarts", "8",
           "--"] + child
    # kill INSIDE the first phase's open span (epoch_loop fires mid-span),
    # so the dead child leaves a dangling span_begin for trace synthesis
    plan = [{"site": "trainer/epoch_loop", "action": "kill",
             "trigger_count": 1}]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLAP_FAULT_PLAN=json.dumps(plan))
    out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=540)
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["outcome"] == "success" and summary["restarts"] == 1
    return run_dir


def test_report_trace_on_real_supervised_run(supervised_run, tmp_path):
    out1, out2 = tmp_path / "trace1.json", tmp_path / "trace2.json"
    assert report_main([str(supervised_run), "--trace", str(out1)]) == 0
    assert report_main([str(supervised_run), "--trace", str(out2)]) == 0
    # deterministic across two invocations
    assert out1.read_bytes() == out2.read_bytes()
    trace = json.loads(out1.read_text())  # valid Chrome trace JSON
    events = trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"
    # every event file in the run dir got a lane
    event_files = (sorted(supervised_run.glob("events*.jsonl")))
    assert trace["otherData"]["n_files"] == len(event_files) >= 3
    proc_names = {e["args"]["name"] for e in events
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert proc_names == {p.name for p in event_files}
    names = {e["name"] for e in events}
    # training spans, supervisor instants, and the injected kill all lane up
    assert any(n.startswith("phase/") for n in names)
    assert any(n.startswith("compile/") for n in names)
    assert "supervise/restart" in names
    assert "fault/injected" in names
    # the killed child's open spans were synthesized, not dropped
    assert trace["otherData"]["n_synthesized_ends"] >= 1
    # --trace now MERGES multiple run dirs into one timeline (PR 10):
    # lanes are prefixed with the dir name and the merge is deterministic
    out3 = tmp_path / "trace3.json"
    assert report_main([str(supervised_run), str(supervised_run),
                        "--trace", str(out3)]) == 0
    merged = json.loads(out3.read_text())
    assert merged["otherData"]["n_files"] == 2 * len(event_files)
    prefixed = {e["args"]["name"] for e in merged["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"}
    assert all("/" in name for name in prefixed)


def test_train_manifest_carries_phase_program_analysis(supervised_run):
    """Acceptance: a default (pipeline-on) CPU train run's manifest carries
    cost/memory analysis for every AOT phase program it compiled."""
    manifest = json.loads((supervised_run / "manifest.json").read_text())
    progs = manifest["xla_programs"]
    assert progs, "no xla_programs in the train manifest"
    assert any(k.startswith("phase_") for k in progs)
    for name, a in progs.items():
        assert a["cost_available"] is True, (name, a)
        assert a["flops"] > 0
        assert a["memory_available"] is True
        assert a["peak_memory_bytes"] > 0
    text = format_summary(summarize_run(load_run(supervised_run)))
    assert "AOT programs (XLA cost/memory analysis)" in text


def test_train_metrics_sidecar_started(supervised_run):
    log = (supervised_run / "supervised.log").read_text()
    assert "metrics sidecar: http://127.0.0.1:" in log


# --------------------------------------------------------------------------
# lint gate: the telemetry plane's new/changed modules stay clean
# --------------------------------------------------------------------------

def test_telemetry_modules_lint_clean():
    targets = [
        REPO / PKG / "observability",
        REPO / PKG / "serving" / "server.py",
        REPO / PKG / "serving" / "aserver.py",
        REPO / PKG / "serving" / "engine.py",
        REPO / PKG / "training" / "trainer.py",
        REPO / PKG / "parallel" / "sweep.py",
        REPO / PKG / "reliability" / "supervisor.py",
        REPO / PKG / "train.py",
        REPO / PKG / "sweep.py",
        REPO / "tools" / "check_budgets.py",
        REPO / "bench.py",
        Path(__file__),
    ]
    try:
        import ruff  # noqa: F401
    except ImportError:
        pytest.skip("ruff not installed in this container")
    out = subprocess.run(
        [sys.executable, "-m", "ruff", "check"] + [str(t) for t in targets],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
