"""Load generator for the serving stack: open/closed-loop, loopback-first.

Closed loop: ``concurrency`` workers issue back-to-back requests — measures
the service's sustainable throughput and the latency AT that throughput.
Open loop: requests are launched on a fixed-rate schedule regardless of
completions (the arrival process real traffic has) — latency then includes
queueing delay, and a rate above capacity shows up as a growing p99 rather
than a politely slowed client. Reports p50/p95/p99/mean/max latency,
sustained throughput, and error counts.

``bench_serving()`` is the self-contained benchmark ``bench.py``'s
``serving`` section (and ``BENCH_SERVING.json``) runs: it builds a small
random-init ensemble, serves it over HTTP loopback, and drives both loops.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

Payload = Union[Dict[str, Any], Callable[[int], Dict[str, Any]]]


def _post_json(url: str, payload: Dict[str, Any],
               timeout: float = 30.0) -> Dict[str, Any]:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _percentiles(latencies_s: List[float]) -> Optional[Dict[str, float]]:
    # the shared nearest-rank summary (observability.report) so loadgen,
    # /metrics, and the report CLI agree numerically; mean/max ride along
    from ..observability.report import latency_percentiles_ms

    out = latency_percentiles_ms(latencies_s)
    if out is not None:
        out["mean_ms"] = round(sum(latencies_s) / len(latencies_s) * 1e3, 3)
        out["max_ms"] = round(max(latencies_s) * 1e3, 3)
    return out


def run_loadgen(
    url: str,
    payload: Payload,
    mode: str = "closed",
    concurrency: int = 4,
    n_requests: int = 200,
    rate_rps: Optional[float] = None,
    warmup_requests: int = 4,
    timeout_s: float = 30.0,
) -> Dict[str, Any]:
    """Drive `url` (a POST endpoint) and report the latency distribution.

    `payload` is one dict reused for every request, or a callable
    ``i -> dict`` for varied traffic. Closed loop: `concurrency` workers ×
    back-to-back requests. Open loop (`mode="open"`): one launcher fires at
    `rate_rps` on a fixed schedule, completions land on worker threads.
    """
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be closed|open: {mode!r}")
    if mode == "open" and not rate_rps:
        raise ValueError("open-loop mode requires rate_rps")
    make = payload if callable(payload) else (lambda i: payload)

    # compile warmth, untimed; indices beyond the measured range so a
    # result cache in front of the server cannot pre-absorb measured traffic
    for i in range(warmup_requests):
        try:
            _post_json(url, make(n_requests + i), timeout=timeout_s)
        except Exception:
            pass

    lock = threading.Lock()
    latencies: List[float] = []
    errors: Dict[str, int] = {}

    def one(i: int) -> None:
        t0 = time.monotonic()
        try:
            _post_json(url, make(i), timeout=timeout_s)
        except Exception as e:
            with lock:
                errors[type(e).__name__] = errors.get(type(e).__name__, 0) + 1
            return
        dt = time.monotonic() - t0
        with lock:
            latencies.append(dt)

    t_start = time.monotonic()
    if mode == "closed":
        counter = {"next": 0}

        def worker():
            while True:
                with lock:
                    i = counter["next"]
                    if i >= n_requests:
                        return
                    counter["next"] = i + 1
                one(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:
        period = 1.0 / rate_rps
        threads = []
        for i in range(n_requests):
            target = t_start + i * period
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=one, args=(i,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
    wall_s = time.monotonic() - t_start

    n_ok = len(latencies)
    return {
        "mode": mode,
        "url": url,
        "concurrency": concurrency if mode == "closed" else None,
        "rate_rps": rate_rps if mode == "open" else None,
        "n_requests": n_requests,
        "n_ok": n_ok,
        "errors": errors or None,
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(n_ok / wall_s, 2) if wall_s > 0 else None,
        "latency": _percentiles(latencies),
    }


# -- self-contained serving benchmark (bench.py `serving` section) -----------


def _make_member_dirs(root, cfg, seeds):
    """Random-init member checkpoints: serving latency/throughput depend on
    shapes, not trained values, so the bench needs no training run."""
    import jax

    from ..models.gan import GAN
    from ..training.checkpoint import save_params

    gan = GAN(cfg)
    dirs = []
    for s in seeds:
        d = root / f"seed_{s}"
        d.mkdir(parents=True, exist_ok=True)
        cfg.save(d / "config.json")
        save_params(d / "best_model_sharpe.msgpack",
                    gan.init(jax.random.key(s)))
        dirs.append(str(d))
    return dirs


def bench_serving(
    n_stocks: int = 500,
    n_features: int = 46,
    n_macro: int = 8,
    n_members: int = 4,
    months: int = 60,
    n_requests: int = 200,
    seed: int = 42,
) -> Dict[str, Any]:
    """End-to-end loopback serving benchmark: random-init K-member ensemble,
    AOT-warmed engine, HTTP loopback, closed loop at c=1/c=4 plus an open
    loop near the measured capacity. Returns one JSON-able dict."""
    import tempfile
    from pathlib import Path

    from ..utils.config import GANConfig
    from .engine import InferenceEngine, bucket_for
    from .server import ServingService, make_server

    rng = np.random.default_rng(seed)
    cfg = GANConfig(macro_feature_dim=n_macro,
                    individual_feature_dim=n_features)
    macro = rng.standard_normal((months, n_macro)).astype(np.float32)

    with tempfile.TemporaryDirectory(prefix="dlap_serving_bench_") as td:
        td = Path(td)
        dirs = _make_member_dirs(td / "ckpts", cfg, range(1, n_members + 1))
        t0 = time.monotonic()
        stock_bucket = bucket_for(n_stocks, [64 * 2**i for i in range(9)])
        engine = InferenceEngine(
            dirs, macro_history=macro, stock_buckets=(stock_bucket,))
        load_s = time.monotonic() - t0
        service = ServingService(engine, run_dir=str(td / "serve_run"))
        t0 = time.monotonic()
        service.warmup()
        warmup_s = time.monotonic() - t0
        httpd = make_server(service)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        host, port = httpd.server_address[:2]
        url = f"http://{host}:{port}/v1/weights"

        def make_payload(offset: int) -> Callable[[int], Dict[str, Any]]:
            # every request of every loop is a distinct payload — the LRU
            # cache must not absorb any of the measured traffic
            def payload(i: int) -> Dict[str, Any]:
                r = np.random.default_rng(seed + 1 + offset + i)
                return {
                    "individual": r.standard_normal(
                        (n_stocks, n_features)).astype(np.float32).tolist(),
                    "month": int(i % months),
                }

            return payload

        try:
            closed_1 = run_loadgen(url, make_payload(0), mode="closed",
                                   concurrency=1, n_requests=n_requests)
            closed_4 = run_loadgen(url, make_payload(10**6), mode="closed",
                                   concurrency=4, n_requests=n_requests)
            cap = closed_4["throughput_rps"] or 1.0
            open_loop = run_loadgen(
                url, make_payload(2 * 10**6), mode="open",
                rate_rps=max(1.0, 0.8 * cap),
                n_requests=min(n_requests, int(cap * 5) or n_requests))
            stats = engine.stats()
            metrics = service.metrics()
        finally:
            httpd.shutdown()
            service.close()

    return {
        "shape": f"N={n_stocks} F={n_features} M={n_macro} "
                 f"K={n_members} months={months}",
        "stock_bucket": stock_bucket,
        "engine_load_s": round(load_s, 3),
        "warmup_compile_s": round(warmup_s, 3),
        "closed_loop_c1": closed_1,
        "closed_loop_c4": closed_4,
        "open_loop_0.8cap": open_loop,
        "compiles": stats["compiles"],
        "dispatches": stats["dispatches"],
        "batcher_flushes": metrics["batcher"]["flushes"],
        "note": "HTTP loopback, random-init members (latency depends on "
                "shapes, not trained values); compiles must not grow "
                "after warmup — steady state is recompile-free",
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Serving load generator / loopback benchmark")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bench", help="self-contained loopback benchmark")
    b.add_argument("--n_stocks", type=int, default=500)
    b.add_argument("--n_members", type=int, default=4)
    b.add_argument("--n_requests", type=int, default=200)
    d = sub.add_parser("drive", help="drive an already-running server")
    d.add_argument("--url", type=str, required=True)
    d.add_argument("--payload_json", type=str, required=True,
                   help="path to one JSON request payload")
    d.add_argument("--mode", type=str, default="closed",
                   choices=("closed", "open"))
    d.add_argument("--concurrency", type=int, default=4)
    d.add_argument("--rate_rps", type=float, default=None)
    d.add_argument("--n_requests", type=int, default=200)
    args = p.parse_args(argv)

    if args.cmd == "bench":
        from ..utils.platform import apply_env_platforms

        apply_env_platforms()
        out = bench_serving(n_stocks=args.n_stocks,
                            n_members=args.n_members,
                            n_requests=args.n_requests)
    else:
        payload = json.loads(open(args.payload_json).read())
        out = run_loadgen(args.url, payload, mode=args.mode,
                          concurrency=args.concurrency,
                          rate_rps=args.rate_rps,
                          n_requests=args.n_requests)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
