"""Production-concurrency serving tier-1 suite (CPU, loopback only).

Covers the PR-6 acceptance criteria:
  * continuous-batch coalescing is BIT-IDENTICAL to one-at-a-time dispatch
    (and to the offline `evaluate_ensemble` oracle) through the async HTTP
    path, including the compact base64 wire format;
  * backpressure under the async path is bounded: a full queue answers 503
    and pending never exceeds max_queue — no unbounded growth;
  * a replica killed under open-loop load is restarted by the supervisor
    and the fleet completes the run with ZERO unserved requests after
    client retries (the tier-1 fault matrix);
  * per-process cache shards stay correct across a checkpoint hot-swap
    (/v1/reload): no shard ever serves weights from a params generation it
    is not running;
  * zero steady-state recompiles through the continuous batcher, donated-
    input programs, and pre-pinned staging buffers;
plus ContinuousBatcher unit semantics, the loadgen rate ladder and error
accounting, the report CLI's fleet metrics, and the deprecated
``--server threaded`` escape hatch.
"""

import asyncio
import dataclasses
import json
import os
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.evaluate_ensemble import (
    stack_checkpoints,
)
from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
from deeplearninginassetpricing_paperreplication_tpu.parallel.ensemble import (
    ensemble_metrics,
)
from deeplearninginassetpricing_paperreplication_tpu.serving import (
    AsyncServerThread,
    ContinuousBatcher,
    InferenceEngine,
    InferenceRequest,
    QueueFull,
    ReplicaFleet,
    ServingService,
    pick_free_port,
    run_ladder,
    run_loadgen,
    server_child_argv,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.fleet import (
    REPLICA_POLICY,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.loadgen import (
    compact_payload_bytes,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.server import (
    build_arg_parser,
)
from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
    save_params,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import GANConfig

REPO = Path(__file__).resolve().parents[1]

T, N, F, M = 12, 64, 10, 6
SEEDS = (1, 2, 3)


def _make_cfg(**overrides):
    base = dict(macro_feature_dim=M, individual_feature_dim=F,
                hidden_dim=(8, 8), num_units_rnn=(4,))
    base.update(overrides)
    return GANConfig(**base)


def _write_member(d: Path, cfg: GANConfig, seed: int):
    d.mkdir(parents=True, exist_ok=True)
    cfg.save(d / "config.json")
    save_params(d / "best_model_sharpe.msgpack",
                GAN(cfg).init(jax.random.key(seed)))
    return str(d)


@pytest.fixture(scope="module")
def serve_cfg():
    return _make_cfg()


@pytest.fixture(scope="module")
def member_dirs(tmp_path_factory, serve_cfg):
    root = tmp_path_factory.mktemp("members_async")
    return [_write_member(root / f"seed_{s}", serve_cfg, s) for s in SEEDS]


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    return {
        "macro": rng.standard_normal((T, M)).astype(np.float32),
        "individual": rng.standard_normal((T, N, F)).astype(np.float32),
        "returns": (rng.standard_normal((T, N)) * 0.05).astype(np.float32),
        "mask": (rng.random((T, N)) > 0.15).astype(np.float32),
    }


@pytest.fixture(scope="module")
def offline(member_dirs, panel):
    gan, vparams = stack_checkpoints(member_dirs)
    import jax.numpy as jnp

    return ensemble_metrics(
        gan, vparams, {k: jnp.asarray(v) for k, v in panel.items()})


@pytest.fixture(scope="module")
def engine(member_dirs, panel):
    eng = InferenceEngine(
        member_dirs, macro_history=panel["macro"],
        stock_buckets=(64,), batch_buckets=(1, 2, 4))
    eng.warmup()
    return eng


def _run_async(coro):
    return asyncio.run(coro)


# --------------------------------------------------------------------------
# ContinuousBatcher unit semantics
# --------------------------------------------------------------------------


def test_continuous_batcher_folds_arrivals_into_next_flush():
    """While flush #1 is on the 'device', later submissions pile into the
    lane and ride flush #2 TOGETHER — the continuous-batching contract."""
    calls = []
    gate = threading.Event()

    def handler(bucket, items):
        calls.append(list(items))
        if len(calls) == 1:
            gate.wait(timeout=10)  # hold the first flush in flight
        return [i * 10 for i in items]

    async def body():
        cb = ContinuousBatcher(handler, max_batch=8)
        first = asyncio.ensure_future(cb.submit("b", 1))
        await asyncio.sleep(0.15)  # dispatcher takes flush #1
        rest = [asyncio.ensure_future(cb.submit("b", i)) for i in (2, 3, 4)]
        await asyncio.sleep(0.05)
        gate.set()
        out = await asyncio.gather(first, *rest)
        await cb.aclose()
        return out, cb

    out, cb = _run_async(body())
    assert out == [10, 20, 30, 40]
    assert calls == [[1], [2, 3, 4]]  # one coalesced flush, not three
    assert cb.flushes == 2
    assert cb.occupancy_hist == {1: 1, 3: 1}


def test_continuous_batcher_idle_device_dispatches_immediately():
    """No deadline floor: a lone request on an idle device flushes at once
    (the MicroBatcher would have waited max_delay_s)."""
    async def body():
        cb = ContinuousBatcher(lambda b, items: list(items), max_batch=8)
        t0 = time.monotonic()
        out = await cb.submit("b", "only")
        dt = time.monotonic() - t0
        await cb.aclose()
        return out, dt

    out, dt = _run_async(body())
    assert out == "only"
    assert dt < 1.0


def test_continuous_batcher_bounded_backpressure():
    gate = threading.Event()

    def handler(bucket, items):
        gate.wait(timeout=10)
        return list(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=1, max_queue=2)
        first = asyncio.ensure_future(cb.submit("b", 0))
        await asyncio.sleep(0.1)  # flush #1 in flight; queue empty again
        held = [asyncio.ensure_future(cb.submit("b", i)) for i in (1, 2)]
        await asyncio.sleep(0.05)
        with pytest.raises(QueueFull):
            await cb.submit("b", 3)
        assert cb.pending() <= cb.max_queue  # never unbounded growth
        assert cb.rejected == 1
        gate.set()
        out = await asyncio.gather(first, *held)
        await cb.aclose()
        return out

    assert _run_async(body()) == [0, 1, 2]


def test_continuous_batcher_handler_error_reaches_all_futures_and_recovers():
    def handler(bucket, items):
        if "boom" in items:
            raise RuntimeError("kaput")
        return list(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=4)
        with pytest.raises(RuntimeError, match="kaput"):
            await cb.submit("b", "boom")
        ok = await cb.submit("b", "fine")  # the dispatcher survived
        await cb.aclose()
        return ok

    assert _run_async(body()) == "fine"


def test_continuous_batcher_fifo_across_lanes():
    gate = threading.Event()
    calls = []

    def handler(bucket, items):
        calls.append((bucket, list(items)))
        if len(calls) == 1:
            gate.wait(timeout=10)
        return list(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=8)
        futs = [asyncio.ensure_future(cb.submit("warm", "w0"))]
        await asyncio.sleep(0.15)
        # y's head is OLDER than x's second item → y flushes first
        futs.append(asyncio.ensure_future(cb.submit("y", "y0")))
        await asyncio.sleep(0.02)
        futs.append(asyncio.ensure_future(cb.submit("x", "x0")))
        gate.set()
        await asyncio.gather(*futs)
        await cb.aclose()

    _run_async(body())
    assert [c[0] for c in calls] == ["warm", "y", "x"]


def test_continuous_batcher_rejects_after_close():
    async def body():
        cb = ContinuousBatcher(lambda b, items: list(items))
        await cb.submit("b", 1)
        await cb.aclose()
        with pytest.raises(RuntimeError, match="closed"):
            await cb.submit("b", 2)

    _run_async(body())


# --------------------------------------------------------------------------
# coalescing bit-identity: continuous batch ≡ one-at-a-time ≡ offline oracle
# --------------------------------------------------------------------------


def test_coalesced_flush_bit_identical_to_one_at_a_time(engine, panel,
                                                        offline):
    """Four month-queries held and released as ONE continuous flush produce
    byte-identical weights to four single dispatches (and to the offline
    batch path) — coalescing is numerically invisible."""
    months = (1, 4, 7, 9)
    reqs = {t: InferenceRequest(
        individual=panel["individual"][t], mask=panel["mask"][t],
        returns=panel["returns"][t], month=t) for t in months}
    singles = {t: engine.infer([reqs[t]])[0] for t in months}

    gate = threading.Event()
    flushed = []

    def handler(bucket, items):
        flushed.append(len(items))
        if len(flushed) == 1:
            gate.wait(timeout=30)
        return engine.infer(items)

    async def body():
        cb = ContinuousBatcher(handler, max_batch=8)
        warm = asyncio.ensure_future(cb.submit(64, reqs[months[0]]))
        await asyncio.sleep(0.15)
        rest = [asyncio.ensure_future(cb.submit(64, reqs[t]))
                for t in months[1:]]
        await asyncio.sleep(0.05)
        gate.set()
        out = await asyncio.gather(warm, *rest)
        await cb.aclose()
        return out

    results = _run_async(body())
    assert flushed == [1, 3]  # the release coalesced the other three
    for t, res in zip(months, results):
        assert res.batch_bucket == (1 if t == months[0] else 4)
        np.testing.assert_array_equal(res.weights, singles[t].weights)
        np.testing.assert_array_equal(res.weights,
                                      offline["avg_weights"][t])
        assert res.sdf == singles[t].sdf
        assert res.sdf == float(offline["ensemble_port_returns"][t])


# --------------------------------------------------------------------------
# async HTTP server: bit-identity, b64 wire, zero recompiles, backpressure
# --------------------------------------------------------------------------


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def async_http(engine, tmp_path_factory):
    run_dir = tmp_path_factory.mktemp("aserve_run")
    from deeplearninginassetpricing_paperreplication_tpu.observability import (
        EventLog,
    )

    events = EventLog(run_dir)
    engine.events = events
    service = ServingService(engine, run_dir=str(run_dir), events=events,
                             mode="async", replica_id=0)
    service.warmup()
    server = AsyncServerThread(service)
    port = server.start()
    yield {"url": f"http://127.0.0.1:{port}", "service": service,
           "engine": engine, "run_dir": run_dir}
    server.stop()
    service.close()
    events.close()


def test_async_http_bit_identical_and_zero_recompiles(async_http, panel,
                                                      offline):
    base = async_http["url"]
    eng = async_http["engine"]
    compiles0 = eng.stats()["compiles"]
    # concurrent burst across months: whatever coalescing happens, every
    # response must match the offline oracle bit-exactly
    results = {}
    def one(t):
        st, body = _post(base, "/v1/weights", {
            "individual": panel["individual"][t].tolist(),
            "mask": panel["mask"][t].tolist(), "month": int(t)})
        results[t] = (st, body)

    threads = [threading.Thread(target=one, args=(t,)) for t in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for t in range(T):
        st, body = results[t]
        assert st == 200, body
        served = np.asarray(body["weights"], np.float64).astype(np.float32)
        np.testing.assert_array_equal(served, offline["avg_weights"][t])
    stats = eng.stats()
    assert stats["compiles"] == compiles0, (
        "async continuous batching must not recompile in steady state")
    assert stats["donate_inputs"] is False  # CPU: donation resolved off
    assert stats["staging_buffers"] >= 1  # pre-pinned host staging in use


def test_async_http_b64_wire_parity(async_http, panel, offline):
    import base64 as b64mod

    base = async_http["url"]
    t = 5
    payload = json.loads(compact_payload_bytes(panel["individual"][t], t))
    st, body = _post(base, "/v1/weights", payload)
    assert st == 200
    w = np.frombuffer(b64mod.b64decode(body["weights_b64"]), np.float32)
    # mask defaults to all-valid in both paths; compare vs JSON-list route
    st2, body2 = _post(base, "/v1/weights", {
        "individual": panel["individual"][t].tolist(), "month": t})
    np.testing.assert_array_equal(
        w, np.asarray(body2["weights"], np.float64).astype(np.float32))
    # b64 sdf route
    st3, body3 = _post(base, "/v1/sdf", {
        "individual_b64": payload["individual_b64"],
        "mask_b64": b64mod.b64encode(
            np.ascontiguousarray(panel["mask"][t]).tobytes()).decode(),
        "returns_b64": b64mod.b64encode(
            np.ascontiguousarray(panel["returns"][t]).tobytes()).decode(),
        "month": t})
    assert st3 == 200
    assert body3["sdf"] == float(offline["ensemble_port_returns"][t])


def test_async_http_binary_wire_bit_identical(async_http, panel, offline):
    """The raw-f32 wire (application/x-dlap-f32) returns the same bytes
    the JSON route serializes — one engine, three encodings, zero drift."""
    from deeplearninginassetpricing_paperreplication_tpu.serving.loadgen import (
        KeepAliveClient,
        binary_payload_bytes,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.server import (
        BINARY_CONTENT_TYPE,
    )

    t = 8
    client = KeepAliveClient(async_http["url"] + "/v1/weights",
                             content_type=BINARY_CONTENT_TYPE)
    st, raw = client.post(binary_payload_bytes(panel["individual"][t], t))
    assert st == 200
    w = np.frombuffer(raw, np.float32)
    assert w.shape == (N,)
    # all-valid mask on both routes → equals the JSON route bit-exactly
    st2, body = _post(async_http["url"], "/v1/weights", {
        "individual": panel["individual"][t].tolist(), "month": t})
    np.testing.assert_array_equal(
        w, np.asarray(body["weights"], np.float64).astype(np.float32))
    # malformed bodies are 400s, not crashes
    st3, _ = client.post(b"\x00\x01")
    assert st3 == 400
    st4, _ = client.post(binary_payload_bytes(panel["individual"][t], t)[:40])
    assert st4 == 400
    st5, _ = client.post(binary_payload_bytes(
        panel["individual"][t], T + 9))  # month out of range
    assert st5 == 400
    client.close()


def test_async_http_bad_b64_is_400(async_http):
    st, body = _post(async_http["url"], "/v1/weights",
                     {"individual_b64": "!!!not-base64!!!"})
    assert st == 400 and "individual_b64" in body["error"]
    st, body = _post(async_http["url"], "/v1/weights",
                     {"individual_b64": "AAAA"})  # 1 float, not N*F
    assert st == 400


def test_async_backpressure_bounded_503(member_dirs, panel):
    """A saturated async service answers 503 from its BOUNDED queue; the
    pending count never exceeds max_queue, and service recovers after."""
    eng = InferenceEngine(
        member_dirs, macro_history=panel["macro"],
        stock_buckets=(64,), batch_buckets=(1,))
    service = ServingService(eng, mode="async", max_queue=3, max_batch=1)
    gate = threading.Event()
    real = service._handle_batch

    def slow(bucket, items):
        gate.wait(timeout=30)
        return real(bucket, items)

    service._handle_batch = slow
    server = AsyncServerThread(service)
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    # DISTINCT payloads (per-request month): identical ones would ride the
    # single-flight coalescer and never fill the queue at all
    codes = []
    lock = threading.Lock()

    def one(i):
        st, _ = _post(url, "/v1/weights", {
            "individual": panel["individual"][i % T].tolist(),
            "month": int(i % T)})
        with lock:
            codes.append(st)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(10)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    pending_under_load = service.cbatcher.pending()
    gate.set()
    for t in threads:
        t.join()
    assert pending_under_load <= 3  # bounded, never the 10 submitted
    assert codes.count(503) >= 1
    assert codes.count(200) >= 1
    assert service.cbatcher.rejected >= 1
    # the service recovers once drained
    st, _ = _post(url, "/v1/weights", {
        "individual": panel["individual"][0].tolist(), "month": 0})
    assert st == 200
    server.stop()
    service.close()


# --------------------------------------------------------------------------
# cache shards + checkpoint hot-swap
# --------------------------------------------------------------------------


def test_cache_shard_correctness_across_hot_swap(tmp_path, serve_cfg, panel):
    """Two replica shards over one checkpoint set: a hot-swap reloaded into
    ONE shard rotates that shard's params fingerprint — it serves the new
    weights immediately (no stale hit) while the other shard keeps serving
    its own loaded generation consistently, until its own reload."""
    dirs = [_write_member(tmp_path / f"m{s}", serve_cfg, s) for s in (1, 2)]
    shard_a = ServingService(InferenceEngine(
        dirs, macro_history=panel["macro"], stock_buckets=(64,),
        batch_buckets=(1,)), mode="async")
    shard_b = ServingService(InferenceEngine(
        dirs, macro_history=panel["macro"], stock_buckets=(64,),
        batch_buckets=(1,)), mode="async")
    payload = {"individual": panel["individual"][2].tolist(),
               "mask": panel["mask"][2].tolist(), "month": 2}
    st, a1 = shard_a.handle("POST", "/v1/weights", payload)
    st, b1 = shard_b.handle("POST", "/v1/weights", payload)
    assert a1["weights"] == b1["weights"]
    assert shard_a.handle("POST", "/v1/weights", payload)[1]["cached"]

    # rolling re-estimation lands a new checkpoint for member 0
    save_params(Path(dirs[0]) / "best_model_sharpe.msgpack",
                GAN(serve_cfg).init(jax.random.key(99)))
    gen = shard_a.handle("POST", "/v1/reload", {})[1]
    assert gen["params_generation"] == 1

    st, a2 = shard_a.handle("POST", "/v1/weights", payload)
    assert a2["cached"] is False  # the old entry became unreachable
    assert a2["weights"] != a1["weights"]
    # the fresh offline oracle agrees with the swapped shard
    gan, vparams = stack_checkpoints(dirs)
    import jax.numpy as jnp

    off = ensemble_metrics(
        gan, vparams, {k: jnp.asarray(v) for k, v in panel.items()})
    np.testing.assert_array_equal(
        np.asarray(a2["weights"], np.float64).astype(np.float32),
        off["avg_weights"][2])
    # shard B never reloaded: still serving ITS generation — cached and
    # equal to its own first answer (consistent, not torn)
    st, b2 = shard_b.handle("POST", "/v1/weights", payload)
    assert b2["cached"] is True and b2["weights"] == b1["weights"]
    # B's own reload converges the fleet
    shard_b.handle("POST", "/v1/reload", {})
    st, b3 = shard_b.handle("POST", "/v1/weights", payload)
    assert b3["cached"] is False and b3["weights"] == a2["weights"]
    shard_a.close()
    shard_b.close()


def test_engine_reload_rederives_macro_state(tmp_path, serve_cfg, panel):
    """reload() re-scans the macro LSTM with the NEW params over initial +
    appended months; a fresh engine over the same series agrees."""
    dirs = [_write_member(tmp_path / f"m{s}", serve_cfg, s) for s in (1, 2)]
    eng = InferenceEngine(dirs, macro_history=panel["macro"][: T - 1],
                          stock_buckets=(64,), batch_buckets=(1,))
    eng.append_month(panel["macro"][T - 1])
    save_params(Path(dirs[1]) / "best_model_sharpe.msgpack",
                GAN(serve_cfg).init(jax.random.key(123)))
    compiles0 = eng.stats()["compiles"]
    eng.reload()
    assert eng.stats()["compiles"] == compiles0  # hot-swap never recompiles
    fresh = InferenceEngine(dirs, macro_history=panel["macro"],
                            stock_buckets=(64,), batch_buckets=(1,))
    np.testing.assert_allclose(eng.macro_state_for_month(T - 1),
                               fresh.macro_state_for_month(T - 1), atol=1e-6)
    req = InferenceRequest(individual=panel["individual"][T - 1],
                           mask=panel["mask"][T - 1], month=T - 1)
    np.testing.assert_array_equal(eng.infer_one(req).weights,
                                  fresh.infer_one(req).weights)


# --------------------------------------------------------------------------
# loadgen: ladder, error accounting, retries
# --------------------------------------------------------------------------


def test_loadgen_ladder_and_error_accounting(async_http, panel):
    url = async_http["url"] + "/v1/weights"
    payload = compact_payload_bytes(panel["individual"][0], 0)
    out = run_ladder(url, lambda i: payload, rates=[30.0, 60.0],
                     warmup_s=0.2, measure_s=0.5, open_workers=8)
    assert len(out["steps"]) == 2
    for step in out["steps"]:
        assert step["errors"] == {}  # ALWAYS a dict, never null
        assert step["n_ok"] == step["n_requests"]
        assert step["latency"] is not None
        assert "late_sends" in step
    assert out["max_clean_rate_rps"] == 60.0
    # non-2xx accounting: a 404 endpoint is an error with its status code
    bad = run_loadgen(async_http["url"] + "/v1/nope", lambda i: payload,
                      mode="closed", concurrency=2, n_requests=6,
                      warmup_requests=0)
    assert bad["errors"] == {"404": 6} and bad["n_ok"] == 0


def test_loadgen_connection_errors_and_retries_counted():
    dead = f"http://127.0.0.1:{pick_free_port()}/v1/weights"
    out = run_loadgen(dead, {"x": 1}, mode="closed", concurrency=1,
                      n_requests=2, warmup_requests=0, retries=1,
                      retry_backoff_s=0.01)
    assert out["n_ok"] == 0
    assert sum(out["errors"].values()) == 2
    assert out["n_retried"] == 2  # one retry per request before giving up


# --------------------------------------------------------------------------
# report CLI: fleet metrics from multiple events.jsonl files
# --------------------------------------------------------------------------


def test_report_fleet_serving_metrics(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    def rows(replica, n_ok, n_503, flushes):
        out = []
        for i in range(n_ok):
            out.append({"kind": "counter", "name": "serve/requests",
                        "value": 1, "endpoint": "/v1/weights", "status": 200,
                        "replica": replica, "run_id": f"r-{replica}"})
            out.append({"kind": "span_end", "name": "serve/request",
                        "duration_s": 0.004, "run_id": f"r-{replica}"})
        for i in range(n_503):
            out.append({"kind": "counter", "name": "serve/requests",
                        "value": 1, "endpoint": "/v1/weights", "status": 503,
                        "replica": replica, "run_id": f"r-{replica}"})
        for occ, depth in flushes:
            out.append({"kind": "counter", "name": "serve/flush", "value": 1,
                        "occupancy": occ, "queue_depth": depth,
                        "replica": replica, "run_id": f"r-{replica}"})
        return out

    for i, (ok, bad, fl) in enumerate([(6, 1, [(1, 0), (4, 6)]),
                                       (4, 1, [(2, 2)])]):
        d = tmp_path / f"replica{i}"
        d.mkdir()
        with open(d / "events.jsonl", "w") as f:
            for r in rows(f"replica{i}", ok, bad, fl):
                f.write(json.dumps(r) + "\n")

    rc = main([str(tmp_path), "--json"])
    summary = json.loads(capsys.readouterr().out)
    assert rc == 0
    sv = summary["serving"]
    assert sv["requests_by_replica"] == {"replica0": 7, "replica1": 5}
    assert sv["rate_503"] == round(2 / 12, 4)
    assert sv["batching"]["flushes"] == 3
    assert sv["batching"]["occupancy_hist"] == {"1": 1, "2": 1, "4": 1}
    assert sv["batching"]["mean_queue_depth"] == round(8 / 3, 3)
    assert sv["latency"]["count"] == 10

    rc = main([str(tmp_path)])
    out = capsys.readouterr().out
    assert "requests by replica" in out
    assert "occupancy histogram" in out
    assert "503 rate" in out


# --------------------------------------------------------------------------
# bench artifact: the async-replicated section and its acceptance bars
# --------------------------------------------------------------------------


def test_bench_serving_async_artifact():
    data = json.loads((REPO / "BENCH_SERVING.json").read_text())
    base_rps = data["closed_loop_c4"]["throughput_rps"]  # PR-3 baseline
    a = data["async_replicated"]
    assert a["replicas"] >= 2
    # >=10x the threaded closed_c4 saturation point at c32
    assert a["closed_loop_c32_bin"]["throughput_rps"] >= 10 * base_rps
    assert a["closed_loop_c32_bin"]["errors"] == {}
    # p99 < 100 ms at >=10x baseline throughput (c16 closed bar)
    assert a["closed_loop_c16_bin"]["latency"]["p99_ms"] < 100
    assert a["closed_loop_c16_bin"]["throughput_rps"] >= 10 * base_rps
    # steady state is recompile-free on EVERY replica, with zero restarts
    assert all(v == 0 for v in a["steady_state_recompiles"].values())
    assert all(r == 0 for r in a["replica_restarts"])


# --------------------------------------------------------------------------
# deprecated threaded path + CLI surface
# --------------------------------------------------------------------------


def test_threaded_server_kept_behind_flag_and_deprecated():
    p = build_arg_parser()
    assert p.parse_args(["--checkpoint_dirs", "d"]).server == "async"
    args = p.parse_args(["--checkpoint_dirs", "d", "--server", "threaded"])
    assert args.server == "threaded"
    assert "DEPRECATED" in p.format_help()


def test_threaded_service_still_serves(member_dirs, panel, offline):
    """The legacy MicroBatcher path stays bit-correct for one release."""
    eng = InferenceEngine(member_dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1, 2))
    service = ServingService(eng, mode="threaded")
    assert service.batcher is not None and service.cbatcher is None
    st, body = service.handle("POST", "/v1/weights", {
        "individual": panel["individual"][3].tolist(),
        "mask": panel["mask"][3].tolist(), "month": 3})
    assert st == 200
    np.testing.assert_array_equal(
        np.asarray(body["weights"], np.float64).astype(np.float32),
        offline["avg_weights"][3])
    service.close()


# --------------------------------------------------------------------------
# tier-1 fault matrix: replica killed under open-loop load
# --------------------------------------------------------------------------


def test_replica_killed_under_load_fleet_serves_every_request(
        tmp_path, serve_cfg, panel):
    """2 supervised replicas on one SO_REUSEPORT port; a fault plan SIGKILLs
    replica0 mid-flight (with requests in the air). The supervisor restarts
    it, clients retry dropped connections onto the survivor, and the run
    completes with ZERO unserved requests; afterwards both replicas are
    live again and the restart is attributed in the fleet run dir."""
    dirs = [_write_member(tmp_path / f"m{s}", serve_cfg, s) for s in (1, 2)]
    np.save(tmp_path / "macro.npy", panel["macro"])
    run_dir = tmp_path / "fleet_run"
    args = build_arg_parser().parse_args([
        "--checkpoint_dirs", *dirs,
        "--macro_npy", str(tmp_path / "macro.npy"),
        "--stock_buckets", "64", "--batch_buckets", "1,4",
        "--run_dir", str(run_dir)])
    port = pick_free_port()
    argvs = [server_child_argv(args, i, run_dir / f"replica{i}", port)
             for i in range(2)]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DLAP_FAULT_PLAN"] = json.dumps([{
        "site": "serve/replica_kill", "action": "kill",
        "match": "replica0", "trigger_count": 8}])
    policy = dataclasses.replace(
        REPLICA_POLICY, backoff_base_s=0.2, min_uptime_s=0.5, poll_s=0.2)
    fleet = ReplicaFleet(argvs, run_dir, policy=policy, env=env)
    fleet.start()
    try:
        fleet.wait_ready(timeout=300)
        url = f"http://127.0.0.1:{port}/v1/weights"
        body = compact_payload_bytes(panel["individual"][0], 0)
        out = run_loadgen(
            url, lambda i: body, mode="open", rate_rps=20.0, n_requests=80,
            warmup_requests=0, retries=10, retry_backoff_s=0.3,
            timeout_s=20.0, open_workers=8)
        # THE acceptance bar: zero unserved requests through the kill
        assert out["n_ok"] == out["n_requests"], out
        assert out["errors"] == {}
        assert out["n_retried"] >= 1  # the kill really dropped connections
        # the killed replica comes back and accepts again
        fleet.wait_ready(timeout=300)
        seen = set()
        deadline = time.monotonic() + 60
        while len(seen) < 2 and time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
                    seen.add(json.loads(r.read()).get("replica"))
            except OSError:
                time.sleep(0.2)
        assert seen == {"replica0", "replica1"}
    finally:
        summaries = fleet.stop()
    assert sum((s or {}).get("restarts", 0) for s in summaries) == 1
    # exactly one kill fired, fleet-wide, and is attributed in the events
    fault_rows = [json.loads(line) for line in (
        run_dir / "events.faults.jsonl").read_text().splitlines()]
    assert len(fault_rows) == 1
    assert fault_rows[0]["site"] == "serve/replica_kill"

    # the report CLI tells the whole fleet story from the one run dir
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        load_run,
        summarize_run,
    )

    summary = summarize_run(load_run(run_dir))
    assert summary["reliability"]["restarts"] == 1
    assert summary["reliability"]["faults_injected"] == {
        "serve/replica_kill:kill": 1}
    sv = summary["serving"]
    assert set(sv["requests_by_replica"]) == {"replica0", "replica1"}
    assert sum(sv["requests_by_replica"].values()) >= 80
    assert sv["batching"]["flushes"] >= 1
