"""Append-only structured event log: spans, counters, gauges.

One ``EventLog`` per process per run directory. Every row is a single JSON
object with a monotonically increasing ``seq``, a wall-clock ``ts``
(``time.time``), a monotonic ``mono`` (``time.monotonic`` — durations are
computed from this clock, never from wall time), the run id, and the JAX
process index. Spans write a ``span_begin`` row at entry and a ``span_end``
row (with ``duration_s``) at exit; nesting is tracked per thread so the
trainer's concurrent compile pool gets correct depth/parent attribution.

The log degrades to a measuring no-op when constructed without a run
directory: ``span(...)`` still times its block (the trainer fills
``compile_seconds`` / ``phase_seconds`` from ``sp.seconds``), but nothing
touches the filesystem. Library code can therefore instrument
unconditionally and let the CLI decide whether a sink exists.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Dict, Optional

try:
    from .metrics import MetricsRegistry, feed_event
except ImportError:
    # loaded OUTSIDE the package (bench.py / supervisor.py path-load this
    # file); metrics.py is stdlib-only by contract and sits next to us
    import importlib.util as _ilu

    _spec = _ilu.spec_from_file_location(
        "_dlap_metrics", Path(__file__).resolve().parent / "metrics.py")
    _metrics = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_metrics)
    MetricsRegistry = _metrics.MetricsRegistry
    feed_event = _metrics.feed_event

SCHEMA_VERSION = 1

# Durability policy for the event file: span_end/counter/request rows
# carry the evidence trace assembly and the reliability report depend on,
# so they are fsync'd at most once per this many seconds (0 = every such
# row). A supervisor-SIGKILLed child then loses at most one window of tail
# rows instead of an arbitrary buffer. Negative disables fsync entirely
# (rows still flush to the OS per line — SIGKILL-safe, power-loss-unsafe).
# "alert" (SLO firing/resolved transitions) and "probe" (blackbox probe
# failures) are in the set for the same reason: they are exactly the rows
# written moments before a process dies, and a SIGKILL must cost at most
# one flush window of that evidence.
ENV_FSYNC = "DLAP_EVENTS_FSYNC_S"
DEFAULT_FSYNC_INTERVAL_S = 0.5
_DURABLE_KINDS = ("span_end", "counter", "request", "alert", "probe")


def new_run_id() -> str:
    """Sortable, collision-safe run identifier (UTC timestamp + random)."""
    return (
        time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        + "-"
        + uuid.uuid4().hex[:8]
    )


def _process_index() -> int:
    """This host's JAX process index; 0 when the backend is unavailable
    (report-only tooling must never force a device initialization)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class EventLog:
    """Writer for one process's ``events.jsonl`` (or a silent measurer).

    Process 0 writes ``events.jsonl``; worker processes write their own
    ``events.proc{p}.jsonl`` in the same run directory, so a multihost run
    leaves one file per process with no cross-process write contention.
    """

    def __init__(
        self,
        run_dir: Optional[os.PathLike] = None,
        run_id: Optional[str] = None,
        process_index: Optional[int] = None,
        filename: Optional[str] = None,
        metrics: Optional[MetricsRegistry] = None,
    ):
        self.run_id = run_id or new_run_id()
        self._pidx = process_index
        self._lock = threading.RLock()
        self._tls = threading.local()
        self._seq = 0
        self._f = None
        # the live metrics twin: every counter/gauge/span_end row also
        # updates this registry, so a scrape endpoint and the event file
        # can never disagree about what the process did
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # small per-log thread ids (0 = first thread seen): trace assembly
        # lanes spans by (process, thread), and raw get_ident() values are
        # neither small nor stable across runs
        self._tids: Dict[int, int] = {}
        try:
            fsync_s = float(os.environ.get(ENV_FSYNC,
                                           DEFAULT_FSYNC_INTERVAL_S))
        except ValueError:
            fsync_s = DEFAULT_FSYNC_INTERVAL_S
        self._fsync_interval = fsync_s
        self._last_fsync = 0.0
        self.path: Optional[Path] = None
        if run_dir is not None:
            pidx = self.process_index
            if filename is None:
                filename = (
                    "events.jsonl" if pidx == 0 else f"events.proc{pidx}.jsonl"
                )
            run_dir = Path(run_dir)
            run_dir.mkdir(parents=True, exist_ok=True)
            self.path = run_dir / filename
            # append-only: a crash keeps everything logged so far, a resumed
            # run appends under its own run_id (readers group by run_id)
            self._f = open(self.path, "a", buffering=1)

    @property
    def process_index(self) -> int:
        if self._pidx is None:
            self._pidx = _process_index()
        return self._pidx

    @property
    def enabled(self) -> bool:
        return self._f is not None

    # -- core emit -----------------------------------------------------------

    def emit(self, kind: str, name: str, **fields: Any) -> Dict[str, Any]:
        """Write one event row; returns it (even when the sink is off).

        The identity/clock fields are written LAST so a caller attr named
        ``run_id``/``seq``/``ts``/... can never corrupt a row's identity
        (report scoping depends on it) — telemetry must not be breakable
        from a call site."""
        fsync_fd = None
        with self._lock:
            self._seq += 1
            ident = threading.get_ident()
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
            row = dict(fields)
            row.update(
                schema=SCHEMA_VERSION,
                kind=kind,
                name=name,
                run_id=self.run_id,
                process_index=self.process_index,
                tid=tid,
                seq=self._seq,
                ts=round(time.time(), 6),
                mono=round(time.monotonic(), 6),
            )
            if self._f is not None:
                self._f.write(json.dumps(row) + "\n")
                if kind in _DURABLE_KINDS and self._fsync_interval >= 0:
                    # crash consistency: span_end/counter rows reach disk at
                    # most one interval late, so a SIGKILLed child's tail
                    # survives for trace assembly (dangling span_begins past
                    # the last sync are synthesized by observability.trace)
                    now = time.monotonic()
                    if now - self._last_fsync >= self._fsync_interval:
                        self._last_fsync = now
                        try:
                            self._f.flush()
                            fsync_fd = self._f.fileno()
                        except (OSError, ValueError):
                            pass
            feed_event(self.metrics, kind, name, row)
        if fsync_fd is not None:
            # fsync OUTSIDE the emit lock: the disk write-back (which can
            # take tens of ms on a loaded disk) must not stall every other
            # thread's emits — only the buffer flush needs the lock
            try:
                os.fsync(fsync_fd)
            except OSError:
                pass  # a concurrently closed log must not fail the emitter
        return row

    # -- the span/counter/gauge API ------------------------------------------

    def span(self, name: str, **attrs: Any) -> "Span":
        """Context manager timing a block: ``with log.span("compile/p1") as
        sp: ...`` — ``sp.seconds`` holds the monotonic duration at exit."""
        return Span(self, name, attrs)

    def counter(self, name: str, value: float = 1, **attrs: Any) -> None:
        self.emit("counter", name, value=value, **attrs)

    def gauge(self, name: str, value: float, **attrs: Any) -> None:
        self.emit("gauge", name, value=value, **attrs)

    def log(self, message: str, level: str = "info", **attrs: Any) -> None:
        self.emit("log", level, message=message, **attrs)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                # the "at most one fsync window of tail rows lost" promise
                # must also cover rows emitted AFTER the last periodic sync:
                # close() is the final chance to push them past the page cache
                if self._fsync_interval >= 0:
                    try:
                        self._f.flush()
                        os.fsync(self._f.fileno())
                    except (OSError, ValueError):
                        pass
                self._f.close()
                self._f = None

    # per-thread span stack (depth/parent attribution under thread pools)
    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st


class Span:
    """One timed block; measures even when the log has no sink."""

    def __init__(self, log: EventLog, name: str, attrs: Dict[str, Any]):
        self._log = log
        self.name = name
        self.attrs = attrs
        self.seconds: float = 0.0
        self._t0: float = 0.0

    def __enter__(self) -> "Span":
        stack = self._log._stack()
        self.depth = len(stack)
        self.parent = stack[-1] if stack else None
        stack.append(self.name)
        # attrs first, span fields last: an attr colliding with a span
        # field (e.g. `depth`) is overridden, never a TypeError — a bad
        # call site must not be able to crash an instrumented run
        fields = dict(self.attrs)
        fields.update(depth=self.depth, parent=self.parent)
        self._log.emit("span_begin", self.name, **fields)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.monotonic() - self._t0
        stack = self._log._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        fields = dict(self.attrs)
        fields.update(
            duration_s=round(self.seconds, 6),
            depth=self.depth, parent=self.parent, status="ok",
        )
        if exc_type is not None:
            fields.update(status="error", error=exc_type.__name__)
        self._log.emit("span_end", self.name, **fields)
