"""Multi-host distribution: process initialization and DCN x ICI meshes.

The reference has no inter-process communication of any kind (SURVEY §2b).
The TPU-native counterpart of an NCCL/MPI backend is: initialize the JAX
distributed runtime once per host, build ONE global mesh whose outer axis
spans hosts (slices) over DCN and whose inner axis spans the chips of each
slice over ICI, and let GSPMD place the collectives. For this workload:

  * the ensemble/sweep member axis ('batch') goes OUTER — members are
    independent (zero gradient traffic), so the slow DCN hops carry nothing
    during training;
  * the panel's stock axis ('stocks') goes INNER — the masked cross-sectional
    psums in the losses ride ICI.

Single-host runs (and the CPU test mesh) fall back transparently: the DCN
axis has size 1 and the same code compiles to a single-slice program.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import BATCH_AXIS, STOCK_AXIS


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Idempotent `jax.distributed.initialize` wrapper.

    With no arguments, relies on the environment (TPU pod metadata or the
    standard JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID
    variables); on a single host with none of those set, it is a no-op.
    Returns True when the distributed runtime is (now) initialized.
    """
    # the idempotency probe must NOT touch the backend: jax.process_count()
    # initializes it, after which jax.distributed.initialize can only fail
    # with "must be called before backends are initialized" (found by the
    # 2-process worker actually executing this path)
    if jax.distributed.is_initialized():
        return True
    env_configured = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    ) or os.environ.get("COORDINATOR_ADDRESS")
    in_pod = os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get(
        "MEGASCALE_COORDINATOR_ADDRESS"
    )
    if not env_configured and not in_pod:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "must be called before" in str(e):
            # backend already initialized — too late to join; report what we
            # actually are rather than pretending to have joined
            return jax.process_count() > 1
        # a genuinely pod-configured environment that failed to coordinate
        # must NOT silently degrade to uncoordinated per-host training
        raise
    return True


def create_hybrid_mesh(
    members_per_host_group: Optional[int] = None,
    axis_names: Tuple[str, str] = (BATCH_AXIS, STOCK_AXIS),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """('batch', 'stocks') mesh laid out DCN-outer / ICI-inner.

    On a multi-slice/multi-host topology, uses
    `jax.experimental.mesh_utils.create_hybrid_device_mesh` so the 'batch'
    axis maps to slice granularity (DCN) and 'stocks' stays within each
    slice (ICI). On one host/slice, degrades to a (1, n_devices) or
    (n_groups, n_per_group) contiguous mesh.

    `members_per_host_group`: size of the batch axis; defaults to the number
    of slices (multi-slice) or 1 (single slice).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    # DCN granule: a TPU slice when slice_index exists, else the owning
    # PROCESS — on a multi-process CPU/GPU run the process boundary IS the
    # slow (network) boundary, so the same outer-axis placement logic
    # applies (and a 2-process CPU pair exercises this exact path)
    def _granule(d):
        s = getattr(d, "slice_index", None)
        return s if s is not None else getattr(d, "process_index", 0)

    has_slices = any(
        getattr(d, "slice_index", None) is not None for d in devices
    )
    granule_ids = sorted({_granule(d) for d in devices})
    n_slices = len(granule_ids)

    n_batch = members_per_host_group or max(n_slices, 1)
    if n % n_batch != 0:
        raise ValueError(
            f"{n} devices do not split into {n_batch} member groups"
        )

    if n_slices > 1:
        if n_batch % n_slices == 0:
            # batch axis splits granule-wise: DCN hops carry only the
            # (traffic-free) member axis, ICI carries the stock psums
            if has_slices:
                from jax.experimental import mesh_utils

                grid = mesh_utils.create_hybrid_device_mesh(
                    mesh_shape=(n_batch // n_slices, n // n_batch),
                    dcn_mesh_shape=(n_slices, 1),
                    devices=devices,
                )
                return Mesh(grid.reshape(n_batch, n // n_batch), axis_names)
            # process-granule layout (no TPU slice metadata): granule-major
            # ordering puts each process's devices on contiguous outer rows,
            # so the outer axis crosses processes and the inner axis stays
            # process-local
            ordered = sorted(devices, key=lambda d: (_granule(d), d.id))
            return Mesh(
                np.array(ordered).reshape(n_batch, n // n_batch), axis_names
            )
        # batch axis does not align with granules (e.g. one global member
        # group): order devices granule-major so the trailing 'stocks' axis
        # is at least contiguous within each granule; its cross-granule psum
        # segments ride DCN, which is the user's explicit trade-off here
        ordered = sorted(devices, key=lambda d: (_granule(d), d.id))
        return Mesh(
            np.array(ordered).reshape(n_batch, n // n_batch), axis_names
        )

    if axis_names == (BATCH_AXIS, STOCK_AXIS):
        from .mesh import create_2d_mesh

        return create_2d_mesh(n_batch, n // n_batch, devices=devices)
    grid = np.array(devices).reshape(n_batch, n // n_batch)
    return Mesh(grid, axis_names)


def process_local_summary() -> dict:
    """Small observability dict for logs: who am I, what do I see."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.default_backend(),
    }
