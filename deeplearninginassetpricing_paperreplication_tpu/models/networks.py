"""SDF (generator) and Moment (discriminator) networks as Flax modules.

Architecture replicates the reference (``/root/reference/src/model.py``):

  * SDFNet (model.py:164-281): optional TorchLSTM over macro → tile per stock
    → concat [individual, macro_state] → FFN [64, 64] (ReLU + Dropout 0.05)
    → Dense(1) → mask → cross-sectional zero-mean per period.
  * MomentNet (model.py:87-161): raw macro tiled + individual → (optional FFN,
    default none) → Dense(num_moments) → tanh → [K, T, N].
  * SimpleSDF (model.py:620-694): non-adversarial baseline, FFN-only over
    [macro, individual], zero-mean weights.

TPU-first notes: Dense layers operate directly on the [T, N, D] panel (no
host-side flatten/reshape); the [T·N, D] × [D, H] matmuls are what lands on
the MXU. Initialization matches torch.nn.Linear (kaiming-uniform a=√5 ⇒
U(-1/√fan_in, 1/√fan_in) for both kernel and bias) so training dynamics and
imported reference checkpoints line up.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..utils.config import GANConfig
from .recurrent import TorchLSTM


def _torch_kernel_init(key, shape, dtype=jnp.float32):
    # flax kernel shape is [fan_in, fan_out]
    bound = float(shape[0]) ** -0.5
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def _torch_bias_init(fan_in: int):
    bound = float(fan_in) ** -0.5

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init


class TorchDense(nn.Module):
    """nn.Dense with torch.nn.Linear's default initialization."""

    features: int

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        return nn.Dense(
            self.features,
            kernel_init=_torch_kernel_init,
            bias_init=_torch_bias_init(fan_in),
        )(x)


def _ffn(x, hidden_dims, dropout, deterministic):
    for h in hidden_dims:
        x = TorchDense(h)(x)
        x = nn.relu(x)
        x = nn.Dropout(rate=dropout)(x, deterministic=deterministic)
    return x


def masked_zero_mean(weights: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Cross-sectional zero-mean per period over valid stocks (model.py:273-279)."""
    count = jnp.clip(mask.sum(axis=1, keepdims=True), 1, None)
    mean = (weights * mask).sum(axis=1, keepdims=True) / count
    return (weights - mean) * mask


class SDFNet(nn.Module):
    """Generator: per-stock portfolio weights [T, N] from the panel."""

    cfg: GANConfig

    @nn.compact
    def __call__(
        self,
        macro: Optional[jnp.ndarray],  # [T, M] or None
        individual: jnp.ndarray,  # [T, N, F]
        mask: jnp.ndarray,  # [T, N] float
        deterministic: bool = True,
    ) -> jnp.ndarray:
        cfg = self.cfg
        T, N, _ = individual.shape

        if macro is not None and cfg.use_rnn and cfg.macro_feature_dim > 0:
            macro_state = TorchLSTM(
                cfg.num_units_rnn, dropout=cfg.dropout, name="macro_lstm"
            )(macro, deterministic=deterministic)
        else:
            macro_state = macro  # may be None

        if macro_state is not None:
            tiled = jnp.broadcast_to(
                macro_state[:, None, :], (T, N, macro_state.shape[-1])
            )
            # reference concat order: [individual, macro] (model.py:255)
            x = jnp.concatenate([individual, tiled], axis=-1)
        else:
            x = individual

        x = _ffn(x, cfg.hidden_dim, cfg.dropout, deterministic)
        w = TorchDense(1, name="output_proj")(x)[..., 0]  # [T, N]
        w = w * mask
        if cfg.normalize_w:
            w = masked_zero_mean(w, mask)
        return w


class MomentNet(nn.Module):
    """Discriminator: K bounded moment functions h_k(t, i) in [-1, 1]."""

    cfg: GANConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        """x: [T, N, macro_dim + individual_dim] → moments [K, T, N]."""
        cfg = self.cfg
        x = _ffn(x, cfg.hidden_dim_moment, cfg.dropout, deterministic)
        out = TorchDense(cfg.num_condition_moment, name="output_proj")(x)
        out = jnp.tanh(out)  # [T, N, K]
        return jnp.transpose(out, (2, 0, 1))  # [K, T, N]


class AssetPricingModule(nn.Module):
    """The GAN pair as one Flax module with separable parameter subtrees.

    params tree: {'sdf_net': ..., 'moment_net': ...} — the training phases
    partition optimizers/gradients on exactly this split (the reference does
    it with two torch optimizers, train.py:210-211).
    """

    cfg: GANConfig

    def setup(self):
        self.sdf_net = SDFNet(self.cfg)
        self.moment_net = MomentNet(self.cfg)

    def __call__(self, macro, individual, mask, deterministic: bool = True):
        """Returns (weights [T, N], moments [K, T, N])."""
        weights = self.sdf_net(macro, individual, mask, deterministic)
        moments = self.moment_net(
            self.moment_input(macro, individual), deterministic
        )
        return weights, moments

    def moment_input(self, macro, individual):
        # Moment net sees RAW macro (not LSTM state), concat [macro, individual]
        # — note the order differs from the SDF net (model.py:514-518).
        T, N, _ = individual.shape
        if macro is not None:
            tiled = jnp.broadcast_to(macro[:, None, :], (T, N, macro.shape[-1]))
            return jnp.concatenate([tiled, individual], axis=-1)
        return individual

    def weights(self, macro, individual, mask, deterministic: bool = True):
        return self.sdf_net(macro, individual, mask, deterministic)

    def moments(self, macro, individual, deterministic: bool = True):
        return self.moment_net(self.moment_input(macro, individual), deterministic)


class SimpleSDF(nn.Module):
    """Non-adversarial FFN-only SDF baseline (model.py:620-694)."""

    macro_dim: int
    individual_dim: int
    hidden_dims: Tuple[int, ...] = (64, 64)
    dropout: float = 0.05

    @nn.compact
    def __call__(self, macro, individual, mask, deterministic: bool = True):
        T, N, _ = individual.shape
        if macro is not None:
            tiled = jnp.broadcast_to(macro[:, None, :], (T, N, macro.shape[-1]))
            x = jnp.concatenate([tiled, individual], axis=-1)
        else:
            x = individual
        x = _ffn(x, self.hidden_dims, self.dropout, deterministic)
        w = TorchDense(1)(x)[..., 0] * mask
        return masked_zero_mean(w, mask)
