"""Hyperparameter sweeps: the paper's 384-config search as mapped axes.

Protocol (paper §II.E; BASELINE.md): search 384 configs, keep the best few,
train 9 seeds each, ensemble. The reference has no sweep code at all — its
README points at the paper. Here a sweep is organized TPU-first:

  * configs are BUCKETED by architecture signature (every field that changes
    tensor shapes or the traced graph: hidden dims, rnn units, moment dims,
    dropout rate, loss flags). Same bucket ⇒ same compiled program.
  * within a bucket, the (config × seed) grid maps onto a `jax.vmap` axis:
    the learning rate — the only purely numeric hyperparameter — rides as a
    vmapped leaf through `optax.inject_hyperparams(adam)`, so ONE program
    trains the whole bucket's grid simultaneously.
  * buckets run sequentially in-process (different programs by
    construction) — or ELASTICALLY across N leased worker processes
    (`run_sweep_worker` against a `reliability.scheduler.WorkQueue`);
    either way every completed bucket lands as one verified record in a
    `reliability.ledger.SweepLedger`, making the bucket (not the search)
    the unit of recovery. Results merge into a ranking by best validation
    Sharpe, reconstructible from the ledger alone (`ranking_from_ledger`)
    bit-identically to the in-process path.

`grid_configs` builds a paper-style search space; `run_sweep` executes it.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from dataclasses import replace
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.gan import GAN
from ..observability.logging import get_run_logger
from ..observability.xla import record_program
from ..reliability.faults import inject
from ..reliability.ledger import SweepLedger, bucket_key, make_record
from ..training.steps import trainable_key
from ..training.trainer import build_phase_scan, fresh_best
from ..utils.config import ExecutionConfig, GANConfig, TrainConfig
from ..utils.rng import train_base_key
from .ensemble import (
    _run_phase_chunked,
    _segment_lens,
    _vselect,
    init_ensemble_params,
    phase_donate_argnums,
    run_member_chunks,
)
from .partition import (
    GRID_AXIS,
    device_sharding,
    grid_slice_mesh,
    replicated,
    shard_stack_tree,
    stack_tree_shardings,
)

Batch = Dict[str, jax.Array]


def architecture_signature(cfg: GANConfig) -> Tuple:
    """Everything that shapes the compiled program (lr excluded)."""
    return (
        cfg.hidden_dim, cfg.use_rnn, cfg.num_units_rnn,
        cfg.hidden_dim_moment, cfg.num_condition_moment,
        cfg.dropout, cfg.normalize_w, cfg.weighted_loss,
        cfg.residual_loss_factor,
        cfg.macro_feature_dim, cfg.individual_feature_dim,
    )


def grid_configs(
    base: GANConfig,
    hidden_dims: Sequence[Sequence[int]] = ((64, 64), (128, 128), (64, 64, 64), (32, 32)),
    rnn_units: Sequence[Sequence[int]] = ((4,), (8,), (16,), (32,)),
    num_moments: Sequence[int] = (4, 8),
    dropouts: Sequence[float] = (0.05, 0.01, 0.1),
    lrs: Sequence[float] = (1e-3, 5e-4, 2e-3, 1e-4),
) -> List[Tuple[GANConfig, float]]:
    """Cartesian search space; defaults give 4*4*2*3*4 = 384 combos, echoing
    the paper's 384-model search."""
    out = []
    for hd, ru, nm, dr, lr in itertools.product(
        hidden_dims, rnn_units, num_moments, dropouts, lrs
    ):
        out.append(
            (
                replace(
                    base,
                    hidden_dim=tuple(hd),
                    num_units_rnn=tuple(ru),
                    num_condition_moment=nm,
                    dropout=dr,
                ),
                lr,
            )
        )
    return out


def bucketize(
    configs_and_lrs: Sequence[Tuple[GANConfig, float]],
) -> Dict[Tuple, Dict]:
    """Group a (config, lr) search space into ordered architecture buckets
    — THE single bucketing used by the in-process sweep, the elastic
    coordinator's work manifest, and the worker loop, so all three always
    agree on bucket identity and order (order fixes ranking tie-breaks)."""
    buckets: Dict[Tuple, Dict] = {}
    for cfg, lr in configs_and_lrs:
        sig = architecture_signature(cfg)
        b = buckets.setdefault(sig, {"cfg": cfg, "lrs": []})
        if lr not in b["lrs"]:
            b["lrs"].append(lr)
    return buckets


def bucket_work_items(
    configs_and_lrs: Sequence[Tuple[GANConfig, float]],
    seeds: Sequence[int],
    tcfg: "TrainConfig",
) -> List[Dict[str, Any]]:
    """The ordered, JSON-ready work manifest items for an elastic sweep:
    one entry per bucket with its content key (ledger.bucket_key), index,
    config dict, and lr grid."""
    tcfg_dict = dataclasses.asdict(tcfg)
    return [
        {
            "key": bucket_key(b["cfg"].to_dict(), b["lrs"], list(seeds),
                              tcfg_dict),
            "index": i,
            "config": b["cfg"].to_dict(),
            "lrs": [float(lr) for lr in b["lrs"]],
        }
        for i, b in enumerate(bucketize(configs_and_lrs).values())
    ]


def _entries_from_record(cfg: GANConfig, record: Dict[str, Any]) -> List[Dict]:
    """One ledger record → its ranking entries (null Sharpe — a
    never-updated tracker — maps back to -inf, as in load_ranking)."""
    return [
        {
            "config": cfg,
            "lr": float(g[0]),
            "seed": int(g[1]),
            "valid_sharpe": float(s) if s is not None else float("-inf"),
        }
        for g, s in zip(record["grid"], record["best_valid_sharpe"])
    ]


def _make_injectable_optimizer(grad_clip: float):
    return optax.inject_hyperparams(
        lambda learning_rate: optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8),
        )
    )(learning_rate=1e-3)


def train_bucket(
    cfg: GANConfig,
    lrs: Sequence[float],
    seeds: Sequence[int],
    train_batch: Batch,
    valid_batch: Batch,
    tcfg: TrainConfig,
    member_chunk: Optional[int] = None,
    exec_cfg: Optional[ExecutionConfig] = None,
    programs: Optional[Dict] = None,
    grid_mesh=None,
) -> Dict[str, np.ndarray]:
    """Train the (lr × seed) grid of one architecture bucket as ONE vmapped
    3-phase program per phase. Returns best-valid-sharpe per grid point.

    Grid layout: axis 0 enumerates lr-major (lr_i, seed_j) pairs.

    `member_chunk`: cap the vmapped grid width per program (sequential
    chunks, concatenated). On the default fused-kernel route members cost
    ~0.1 GB each at the real panel shape, so a 16 GB chip fits tens of grid
    points; the plain-XLA route (pallas off / non-TPU) needs ~2.1 GB per
    member and wants chunks of ~5 (see parallel/ensemble.py).

    `grid_mesh`: a ('grid',) mesh (``partition.grid_slice_mesh``) to lay
    the (lr × seed) axis over — grid-stacked trees shard their leading
    axis across the mesh's devices (naive-sharding fallback: a leaf the
    axis does not divide is replicated) while the panel replicates per
    device. Per-point math is independent (no cross-grid collectives), so
    outputs are BIT-IDENTICAL to the unsharded run — tier-1 asserts it.
    """
    grid = [(lr, s) for lr in lrs for s in seeds]
    if member_chunk is not None and 0 < member_chunk < len(grid):
        # warmed programs were lowered for the FULL grid width — chunked
        # sub-grids have different member axes, so they compile inline
        return run_member_chunks(
            lambda sub: _train_grid(
                cfg, sub, train_batch, valid_batch, tcfg, exec_cfg,
                grid_mesh=grid_mesh),
            grid, member_chunk,
        )
    return _train_grid(cfg, grid, train_batch, valid_batch, tcfg, exec_cfg,
                       programs=programs, grid_mesh=grid_mesh)


def _setup_arrays(gan: GAN, grid: Sequence[Tuple[float, int]], tx):
    """Array-only per-bucket setup (shared by the runner and, via
    jax.eval_shape, the compile warmer): stacked member params, per-phase
    RNG keys, per-point optimizer states with injected lrs, and the two
    best-tracker trees."""
    from functools import partial

    vparams = init_ensemble_params(gan, [s for _, s in grid])
    lr_vec = jnp.asarray([lr for lr, _ in grid], jnp.float32)
    keys = jnp.stack([train_base_key(s * 7919 + 13) for _, s in grid])
    phase_keys = jax.vmap(lambda k: jax.random.split(k, 3))(keys)

    def init_opt_with_lr(p, lr):
        # Rebuild the state immutably: mutating InjectHyperparamsState's
        # hyperparams dict in place relies on an optax-internal representation.
        st = tx.init(p)
        return st._replace(hyperparams=dict(st.hyperparams, learning_rate=lr))

    opt_sdf = jax.vmap(init_opt_with_lr)(
        vparams[trainable_key("unconditional")], lr_vec
    )
    opt_moment = jax.vmap(init_opt_with_lr)(
        vparams[trainable_key("moment")], lr_vec
    )
    best1 = jax.vmap(fresh_best)(vparams)
    best2 = jax.vmap(partial(fresh_best, for_moment=True))(vparams)
    return vparams, phase_keys, opt_sdf, opt_moment, best1, best2


def _grid_setup(gan: GAN, grid: Sequence[Tuple[float, int]],
                tcfg: TrainConfig):
    tx = _make_injectable_optimizer(tcfg.grad_clip)
    vparams, phase_keys, opt_sdf, opt_moment, _b1, _b2 = _setup_arrays(
        gan, grid, tx)
    return vparams, phase_keys, tx, opt_sdf, opt_moment


def warm_bucket_programs(
    cfg: GANConfig,
    lrs: Sequence[float],
    seeds: Sequence[int],
    train_batch: Batch,
    valid_batch: Batch,
    tcfg: TrainConfig,
    exec_cfg: Optional[ExecutionConfig] = None,
    events=None,
    analyses_out: Optional[Dict[str, Dict]] = None,
    name_prefix: str = "",
    grid_mesh=None,
) -> Dict[Tuple[str, int], "jax.stages.Compiled"]:
    """AOT-compile one bucket's vmapped phase programs; return the
    executables keyed by (phase, segment_len) for _train_grid to dispatch.

    The 384-config search is COMPILE-dominated: 96 distinct architectures
    each need their own XLA programs (~tens of seconds on the remote compile
    service) while a bucket's warm execute is seconds. The service compiles
    concurrently (the same property Trainer.precompile exploits), so
    run_sweep warms upcoming buckets from a small thread pool while the main
    loop executes earlier ones and then dispatches the returned executables
    directly. (Direct handoff, NOT via the persistent cache: a program
    lowered from struct avals does not cache-key byte-identically to the
    array call — e.g. committed arrays lower with sdy sharding constraints —
    but the compiled executable itself accepts any aval-compatible args.)

    Everything here lowers from ShapeDtypeStruct avals — zero device
    allocation or compute, so warm threads cannot contend for HBM with the
    executing main loop.

    `grid_mesh`: lower for the mesh-packed dispatch — batch avals carry the
    mesh-replicated sharding and grid-stacked avals the leading-axis 'grid'
    sharding, matching exactly what ``_train_grid(grid_mesh=...)`` commits
    before dispatch. Without it, placement is the DEGENERATE 1-device mesh
    from the partition layer (device 0 as the smallest mesh — the old
    hand-rolled ``SingleDeviceSharding`` pin, now rule-routed)."""
    gan = GAN(cfg, exec_cfg or ExecutionConfig())
    if grid_mesh is None:
        repl_sharding = device_sharding()
        grid_sh = lambda tree: jax.tree.map(lambda _: repl_sharding, tree)
    else:
        repl_sharding = replicated(grid_mesh)
        grid_sh = lambda tree: stack_tree_shardings(grid_mesh, tree,
                                                    GRID_AXIS)

    def struct(tree, shardings=None):
        sh = (shardings if shardings is not None
              else jax.tree.map(lambda _: repl_sharding, tree))
        return jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                              sharding=s), tree, sh)

    tb = struct(jax.eval_shape(gan.prepare_batch, struct(train_batch)))
    vb = struct(jax.eval_shape(gan.prepare_batch, struct(valid_batch)))
    grid = [(lr, s) for lr in lrs for s in seeds]
    tx = _make_injectable_optimizer(tcfg.grad_clip)
    stacked = jax.eval_shape(lambda: _setup_arrays(gan, grid, tx))
    vparams, phase_keys, opt_sdf, opt_moment, best1, best2 = struct(
        stacked, grid_sh(stacked))
    key_aval = jax.ShapeDtypeStruct((phase_keys.shape[0],), phase_keys.dtype)
    key_vec = struct(key_aval, grid_sh(key_aval))  # phase_keys[:, k] aval
    jobs = [
        ("unconditional", tcfg.num_epochs_unc, opt_sdf, best1),
        ("moment", tcfg.num_epochs_moment, opt_moment, best2),
        ("conditional", tcfg.num_epochs, opt_sdf, best1),
    ]
    start = jax.ShapeDtypeStruct((), jnp.int32, sharding=repl_sharding)
    programs: Dict[Tuple[str, int], "jax.stages.Compiled"] = {}
    for phase, n, opt, best in jobs:
        if n <= 0:
            # uniform for every phase: the runner compiles the empty-scan
            # program inline when the (phase, seg) key is absent, so warming
            # a zero-epoch program would be pure waste for any of the three
            continue
        for seg in dict.fromkeys(_segment_lens(n)):
            run = build_phase_scan(
                gan, phase, tx, seg, tcfg.ignore_epoch, has_test=False)
            # same (opt, best) carry donation as the runner's inline
            # compiles (ensemble.phase_donate_argnums) — warmed programs
            # must be byte-for-byte the programs _train_grid dispatches
            fn = jax.jit(
                jax.vmap(run, in_axes=(0, 0, 0, None, None, None, 0, None)),
                donate_argnums=phase_donate_argnums(),
            )
            programs[(phase, seg)] = fn.lower(
                vparams, opt, best, tb, vb, vb, key_vec, start).compile()
            # XLA introspection per warmed bucket program: report-visible
            # `program` rows (and, via analyses_out, the coordinator's
            # manifest) carry its FLOPs/bytes/peak-memory roofline
            record_program(
                events if events is not None else get_run_logger().events,
                f"{name_prefix}{phase}_seg{seg}", programs[(phase, seg)],
                analyses_out=analyses_out,
                program=f"{name_prefix}{phase}_seg{seg}",
                phase=phase, epochs=seg, grid=len(grid))
    return programs


def _train_grid(
    cfg: GANConfig,
    grid: Sequence[Tuple[float, int]],
    train_batch: Batch,
    valid_batch: Batch,
    tcfg: TrainConfig,
    exec_cfg: Optional[ExecutionConfig] = None,
    programs: Optional[Dict] = None,
    grid_mesh=None,
) -> Dict[str, np.ndarray]:
    """One vmapped 3-phase run over explicit (lr, seed) grid points.

    The (lr × seed) axis vmaps through the fused Pallas kernels (see
    parallel/ensemble.py — the member-fused batching rules: one panel read
    per pass for the whole grid). `programs`: warm-compiled executables
    from warm_bucket_programs, dispatched directly when present.

    `grid_mesh`: lay the grid axis over a ('grid',) mesh — grid-stacked
    trees (params, opt states, best trackers, key vectors) commit with
    their leading-axis shardings from the partition layer, batches
    replicate per device. Every inline compile is counted as a
    ``sweep/bucket_compile`` event (warmed-program dispatches are not —
    the bench's zero-steady-state-recompile evidence).
    """
    gan = GAN(cfg, exec_cfg or ExecutionConfig())
    train_batch = gan.prepare_batch(train_batch)
    valid_batch = gan.prepare_batch(valid_batch)
    if grid_mesh is not None:
        # panel replicated across the slice's devices; the derived
        # feature-major arrays ride along (prepare_batch ran first, so the
        # put covers them too)
        train_batch = jax.device_put(train_batch, replicated(grid_mesh))
        valid_batch = jax.device_put(valid_batch, replicated(grid_mesh))
    G = len(grid)
    vparams, phase_keys, tx, opt_sdf, opt_moment = _grid_setup(gan, grid, tcfg)
    if grid_mesh is not None:
        vparams = shard_stack_tree(vparams, grid_mesh, GRID_AXIS)
        opt_sdf = shard_stack_tree(opt_sdf, grid_mesh, GRID_AXIS)
        opt_moment = shard_stack_tree(opt_moment, grid_mesh, GRID_AXIS)
    events = get_run_logger().events

    def vrun(phase, n_epochs, params, opt, best, kidx):
        def make_vmapped(seg_len):
            if programs is not None and (phase, seg_len) in programs:
                return programs[(phase, seg_len)]  # warm-compiled executable
            events.counter("sweep/bucket_compile", phase=phase, seg=seg_len,
                           grid=G, mesh=(grid_mesh is not None))
            run = build_phase_scan(
                gan, phase, tx, seg_len, tcfg.ignore_epoch, has_test=False)
            return jax.jit(
                jax.vmap(run, in_axes=(0, 0, 0, None, None, None, 0, None)),
                donate_argnums=phase_donate_argnums(),
            )

        keys = phase_keys[:, kidx]
        if grid_mesh is not None:
            # commit every grid-stacked dispatch arg with the exact
            # leading-axis shardings the (warmed) programs lowered against:
            # inter-phase selects/inits leave GSPMD-chosen layouts, and
            # device_put is a no-op when the sharding already matches
            params = shard_stack_tree(params, grid_mesh, GRID_AXIS)
            opt = shard_stack_tree(opt, grid_mesh, GRID_AXIS)
            best = shard_stack_tree(best, grid_mesh, GRID_AXIS)
            keys = shard_stack_tree(keys, grid_mesh, GRID_AXIS)
        return _run_phase_chunked(
            make_vmapped, n_epochs, params, opt, best,
            (train_batch, valid_batch, valid_batch), keys,
        )

    best1 = jax.vmap(fresh_best)(vparams)
    vparams, opt_sdf, best1, _ = vrun(
        "unconditional", tcfg.num_epochs_unc, vparams, opt_sdf, best1, 0
    )
    vparams = _vselect(best1["updated_sharpe"], best1["params_sharpe"], vparams)
    params_phase1_best = vparams
    if tcfg.num_epochs_moment > 0:
        from functools import partial

        best2 = jax.vmap(partial(fresh_best, for_moment=True))(vparams)
        vparams, opt_moment, best2, _ = vrun(
            "moment", tcfg.num_epochs_moment, vparams, opt_moment, best2, 1
        )
    best3 = jax.vmap(fresh_best)(vparams)
    vparams, opt_sdf, best3, _ = vrun(
        "conditional", tcfg.num_epochs, vparams, opt_sdf, best3, 2
    )
    # Final reload chain per member (train.py:398-400, mirroring
    # trainer.py/ensemble.py): phase-3 best-by-sharpe if it updated, else
    # phase-1 best, else the running params; report the matching sharpe.
    final = _vselect(
        best3["updated_sharpe"], best3["params_sharpe"],
        _vselect(best1["updated_sharpe"], params_phase1_best, vparams),
    )
    reported_sharpe = jnp.where(
        best3["updated_sharpe"], best3["sharpe"],
        jnp.where(best1["updated_sharpe"], best1["sharpe"], -jnp.inf),
    )

    return {
        "grid": np.asarray(grid, dtype=np.float64),  # [(lr, seed)]
        "best_valid_sharpe": np.asarray(reported_sharpe),
        "params": final,
    }


def run_sweep(
    configs_and_lrs: Sequence[Tuple[GANConfig, float]],
    seeds: Sequence[int],
    train_batch: Batch,
    valid_batch: Batch,
    tcfg: Optional[TrainConfig] = None,
    top_k: Optional[int] = 4,
    keep_params: bool = False,
    verbose: bool = True,
    member_chunk: Optional[int] = None,
    exec_cfg: Optional[ExecutionConfig] = None,
    compile_ahead: Optional[int] = None,
    stats_out: Optional[Dict] = None,
    heartbeat=None,
    ledger: Optional[SweepLedger] = None,
    consult_ledger: bool = False,
    worker_id: Optional[str] = None,
    grid_mesh=None,
) -> List[Dict]:
    """Execute a sweep: bucket → vmapped grid per bucket → global ranking.

    `grid_mesh`: mesh-packed execution — every bucket's (lr × seed) grid is
    laid over the ('grid',) mesh (see :func:`train_bucket`); warm-ahead
    compiles lower against the same shardings. Outputs bit-identical to
    mesh-off.

    Returns the top_k entries (all entries when top_k is None) as dicts with
    config, lr, seed, valid sharpe — and, when `keep_params`, the trained
    winner's final selected params (host numpy tree), so the search's work is
    not thrown away (the paper protocol retrains winners across 9 seeds, but
    the search winners themselves stay usable for warm starts / inspection).

    `compile_ahead`: warm-ahead compile workers (see warm_bucket_programs) —
    the big-grid search is compile-dominated, so upcoming buckets' programs
    compile concurrently while earlier buckets execute. Default: 3 workers
    when the sweep spans >2 buckets and no member chunking splits programs,
    else off. `stats_out`: when given, filled with per-bucket wall seconds
    (`bucket_seconds`) and the bucket count — the artifact's cold/warm
    attribution evidence.

    `ledger`: a :class:`reliability.ledger.SweepLedger` — every completed
    bucket's result lands as one verified record, making the bucket (not
    the search) the unit of recovery. With `consult_ledger` (the
    ``--resume-from-ledger`` mode) buckets already recorded are SKIPPED —
    their entries load from the ledger (counted in
    ``stats_out["ledger_hits"]`` and the ``sweep/ledger_hit`` counter), so
    a restarted search repays only unfinished buckets, never completed
    ones. Ledger records hold no params, so consult mode requires
    ``keep_params=False``.
    """
    tcfg = tcfg or TrainConfig()
    if grid_mesh is not None:
        # replicate the panel onto the mesh ONCE for the whole search —
        # per-bucket puts inside _train_grid then see matching shardings
        # and are no-ops instead of re-broadcasting a multi-GB panel up
        # to 96 times (the worker loop does the same at slice-claim time)
        train_batch = jax.device_put(train_batch, replicated(grid_mesh))
        valid_batch = jax.device_put(valid_batch, replicated(grid_mesh))
    buckets = bucketize(configs_and_lrs)
    bucket_list = list(buckets.items())

    done_records: Dict[Tuple, Dict] = {}
    bucket_keys: Dict[Tuple, str] = {}
    if ledger is not None:
        tcfg_dict = dataclasses.asdict(tcfg)
        for sig, b in bucket_list:
            bucket_keys[sig] = bucket_key(
                b["cfg"].to_dict(), b["lrs"], list(seeds), tcfg_dict)
        if consult_ledger:
            if keep_params:
                raise ValueError(
                    "consult_ledger requires keep_params=False: ledger "
                    "records are JSON and hold no params")
            for sig, _b in bucket_list:
                if ledger.has(bucket_keys[sig]):
                    done_records[sig] = ledger.load(bucket_keys[sig])

    if compile_ahead is None:
        # pipeline only when the sweep spans enough PENDING buckets to
        # overlap; member chunking re-splits programs (different
        # member-axis widths), so warmed executables wouldn't match —
        # compile inline there
        n_pending = len(buckets) - len(done_records)
        compile_ahead = (
            3 if (n_pending > 2 and member_chunk is None) else 0
        )
    warm_futures = {}
    pool = None
    # Bounded look-ahead (2× the worker count): submitting every bucket
    # upfront would (a) accumulate all completed executables in host memory
    # until their bucket runs — a 96-bucket search can hold dozens of
    # compiled programs — and (b) leave shutdown(cancel_futures=True) unable
    # to stop compiles already running on a mid-search abort. With a window,
    # at most `warm_window` buckets' programs exist at once and at most
    # `compile_ahead` compiles are in flight.
    warm_window = 2 * compile_ahead
    warm_submitted = set()
    program_analyses: Dict[str, Dict] = {}

    def _submit_warms_through(pool, limit):
        for idx, (sig2, b2) in enumerate(bucket_list[:limit]):
            if sig2 in warm_submitted or sig2 in done_records:
                continue
            warm_submitted.add(sig2)
            warm_futures[sig2] = pool.submit(
                warm_bucket_programs, b2["cfg"], b2["lrs"], seeds,
                train_batch, valid_batch, tcfg, exec_cfg,
                analyses_out=program_analyses,
                name_prefix=f"bucket{idx + 1}/",
                grid_mesh=grid_mesh,
            )

    if compile_ahead > 0:
        import concurrent.futures

        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=compile_ahead, thread_name_prefix="sweep-warm")
        _submit_warms_through(pool, warm_window)

    logger = get_run_logger()
    results = []
    bucket_seconds = []
    ledger_writes_before = ledger.writes if ledger is not None else 0
    try:
        for i, (sig, b) in enumerate(bucket_list):
            key = bucket_keys.get(sig)
            rec = done_records.get(sig)
            if rec is not None:
                # the resume payoff: a completed bucket is NEVER re-trained
                # — its entries load from the verified record
                logger.events.counter("sweep/ledger_hit", bucket=i + 1,
                                      path=key)
                logger.info(
                    f"[sweep] bucket {i+1}/{len(buckets)}: ledger hit — "
                    "reusing recorded result", verbose=verbose)
                results.extend(_entries_from_record(b["cfg"], rec))
                continue
            # fault-injection site: one hit per bucket, the search's unit of
            # work — a supervised sweep restarts here
            inject("sweep/bucket", bucket=i + 1, n_buckets=len(buckets),
                   path=key or "")
            if heartbeat is not None:
                # liveness advances once per bucket — the search's natural
                # unit of work (a stuck bucket is exactly what a watchdog
                # should attribute a hang to)
                heartbeat.beat("sweep_bucket", bucket=i + 1,
                               n_buckets=len(buckets))
            if pool is not None:
                _submit_warms_through(pool, i + 1 + warm_window)
            logger.info(
                f"[sweep] bucket {i+1}/{len(buckets)}: "
                f"hidden={b['cfg'].hidden_dim} "
                f"rnn={b['cfg'].num_units_rnn} "
                f"K={b['cfg'].num_condition_moment} "
                f"drop={b['cfg'].dropout} "
                f"× {len(b['lrs'])} lrs × {len(seeds)} seeds",
                verbose=verbose,
            )
            programs = None
            if sig in warm_futures:
                # warming is a pure optimization: a failed warm (transient
                # compile-service error) must not abort a multi-hour search —
                # the main loop simply pays that one bucket's compile itself
                try:
                    programs = warm_futures.pop(sig).result()
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        f"[sweep] warm compile for bucket {i+1} failed "
                        f"({type(e).__name__}: {e}); compiling inline",
                        bucket=i + 1,
                    )
            with logger.events.span(
                "sweep/bucket", bucket=i + 1, n_buckets=len(buckets),
            ) as sp_b:
                out = train_bucket(
                    b["cfg"], b["lrs"], seeds, train_batch, valid_batch, tcfg,
                    member_chunk=member_chunk, exec_cfg=exec_cfg,
                    programs=programs, grid_mesh=grid_mesh,
                )
            bucket_seconds.append(round(sp_b.seconds, 2))
            del programs  # free the bucket's executables before the next
            if ledger is not None:
                # durably record the completed bucket BEFORE moving on: a
                # crash after this line costs zero completed work
                ledger.write(key, make_record(
                    key, i, b["cfg"].to_dict(), b["lrs"], list(seeds),
                    out["grid"], out["best_valid_sharpe"],
                    worker=worker_id, seconds=sp_b.seconds,
                ))
                logger.events.counter("sweep/ledger_write", bucket=i + 1,
                                      path=key, worker=worker_id)
            host_params = (
                jax.tree.map(np.asarray, jax.device_get(out["params"]))
                if keep_params
                else None
            )
            for g_idx, (g, s) in enumerate(
                    zip(out["grid"], out["best_valid_sharpe"])):
                entry = {
                    "config": b["cfg"],
                    "lr": float(g[0]),
                    "seed": int(g[1]),
                    "valid_sharpe": float(s),
                }
                if keep_params:
                    entry["params"] = jax.tree.map(
                        lambda x, i=g_idx: x[i], host_params
                    )
                results.append(entry)
    finally:
        if pool is not None:
            # cancel queued warm jobs on ANY exit — a mid-search failure must
            # not leave dozens of queued compiles blocking interpreter exit
            pool.shutdown(wait=False, cancel_futures=True)
    if stats_out is not None:
        stats_out["n_buckets"] = len(buckets)
        stats_out["bucket_seconds"] = bucket_seconds
        if grid_mesh is not None:
            stats_out["grid_mesh"] = {
                "axes": dict(grid_mesh.shape),
                "devices": [d.id for d in grid_mesh.devices.ravel()],
            }
        if program_analyses:
            stats_out["program_analyses"] = dict(
                sorted(program_analyses.items()))
        stats_out["compile_ahead_workers"] = compile_ahead
        if ledger is not None:
            stats_out["ledger_hits"] = len(done_records)
            stats_out["ledger_writes"] = (
                ledger.writes - ledger_writes_before)
    results.sort(key=lambda r: -r["valid_sharpe"])
    return results if top_k is None else results[:top_k]


# -- elastic execution: leased workers over the bucket queue -----------------


def run_sweep_worker(
    queue,
    worker_id: str,
    train_batch: Batch,
    valid_batch: Batch,
    exec_cfg: Optional[ExecutionConfig] = None,
    heartbeat=None,
    verbose: bool = True,
    poll_s: float = 0.5,
) -> int:
    """One elastic sweep worker's claim → train → record loop.

    `queue` is a :class:`reliability.scheduler.WorkQueue` whose manifest
    (written by the coordinating ``sweep.py --workers N`` process) carries
    the bucket list plus the shared schedule (TrainConfig dict, seeds,
    member_chunk) — and, for a MESH-PACKED fleet, the device partitioning
    (``device_slices`` / ``slice_width``). The worker claims buckets under
    a heartbeat-stamped lease (kept alive by a background
    :class:`LeaseKeeper` thread — one bucket's vmapped dispatch can outlive
    the lease timeout), trains each with the SAME ``train_bucket`` program
    the in-process sweep uses (so results are bit-identical to a
    single-process run), records it in the ledger, and releases. A bucket
    whose training raises is released for retry (the claim already counted
    the attempt; K failed claims quarantine it — see scheduler.py);
    ``"wait"`` polls for other workers' leases to complete or expire;
    ``"drained"`` exits cleanly. Returns the number of buckets this worker
    trained.

    Mesh packing: with ``device_slices`` S in the manifest the worker first
    LEASES one of the S disjoint device slices
    (``queue.claim_device_slice``), builds a ('grid',) mesh over exactly
    that slice's devices (``partition.grid_slice_mesh``), replicates its
    batches onto it once, and trains every bucket vmapped + sharded over
    the slice — concurrent workers pack concurrent buckets onto disjoint
    sub-meshes of whatever mesh is alive. Each bucket's programs AOT-warm
    (``warm_bucket_programs(grid_mesh=...)``) before dispatch, so the
    steady state recompiles nothing and every program's XLA cost/memory
    analysis lands in the worker's events. Bucket lease takeover and
    quarantine semantics are UNCHANGED — the slice is an orthogonal lease
    renewed by the same keeper."""
    logger = get_run_logger()
    from ..reliability.scheduler import LeaseKeeper

    manifest = queue.load_manifest()
    tcfg = TrainConfig(**manifest["tcfg"])
    seeds = [int(s) for s in manifest["seeds"]]
    member_chunk = manifest.get("member_chunk")
    bucket_timeout = manifest.get("bucket_timeout_s")
    n_slices = int(manifest.get("device_slices") or 0)
    slice_width = manifest.get("slice_width")
    n_buckets = len(queue.items())
    trained = 0
    grid_mesh = None
    slice_idx: Optional[int] = None
    batches_packed = False
    while True:
        if n_slices > 0 and slice_idx is None:
            slice_idx = queue.claim_device_slice(worker_id, n_slices)
            if slice_idx is None:
                # every slice held by a live worker: wait for one to free
                if heartbeat is not None:
                    heartbeat.beat("sweep_wait")
                time.sleep(poll_s)
                continue
            grid_mesh = grid_slice_mesh(
                slice_idx, n_slices,
                width=int(slice_width) if slice_width else None)
            logger.info(
                f"[sweep:{worker_id}] leased device slice {slice_idx}/"
                f"{n_slices}: devices "
                f"{[d.id for d in grid_mesh.devices.ravel()]}",
                verbose=verbose)
            if not batches_packed:
                # one-time: replicate the panel onto the slice's devices so
                # every bucket's dispatch reads device-local copies
                train_batch = jax.device_put(train_batch,
                                             replicated(grid_mesh))
                valid_batch = jax.device_put(valid_batch,
                                             replicated(grid_mesh))
                batches_packed = True
        status, item = queue.claim(worker_id)
        if status == "drained":
            break
        if status == "wait":
            # stay live while other workers hold the remaining leases — one
            # of them may die, expiring its lease back into the pool. Sleep
            # only until the nearest lease-expiry/backoff deadline (capped
            # at poll_s): an idle worker wakes AT the expiry and takes the
            # orphan over within milliseconds instead of a poll-interval
            # later (scheduler.next_wake_delay)
            if heartbeat is not None:
                heartbeat.beat("sweep_wait")
            if slice_idx is not None:
                # an idle worker still owns its devices: keep the slice
                # lease warm so a takeover only happens on real death
                try:
                    queue.renew_device_slice(slice_idx, worker_id)
                except Exception:  # noqa: BLE001 — lost: re-claim next loop
                    slice_idx, grid_mesh = None, None
                    batches_packed = False  # re-replicate onto the new slice
            time.sleep(queue.next_wake_delay(poll_s, worker=worker_id))
            continue
        key, idx = item["key"], int(item["index"])
        cfg = GANConfig.from_dict(item["config"], strict=False)
        if heartbeat is not None:
            heartbeat.beat("sweep_bucket", bucket=idx + 1,
                           n_buckets=n_buckets)
        logger.info(
            f"[sweep:{worker_id}] bucket {idx+1}/{n_buckets} "
            f"(attempt {item['attempt']}): hidden={cfg.hidden_dim} "
            f"rnn={cfg.num_units_rnn} × {len(item['lrs'])} lrs × "
            f"{len(seeds)} seeds"
            + (f" [slice {slice_idx}]" if slice_idx is not None else ""),
            verbose=verbose)
        # mid-bucket fault site: fires with the lease HELD — a kill here
        # leaves an orphan lease that must expire and be taken over
        inject("sweep/bucket", bucket=idx + 1, n_buckets=n_buckets,
               path=key, worker=worker_id)
        try:
            # the keeper beats the heartbeat on every renewal, so the
            # supervising watchdog sees liveness through a bucket whose one
            # dispatch outlives the heartbeat timeout — bounded by the
            # per-bucket wall budget (past it, both signals go stale and
            # the worker is killed/reclaimed as hung)
            with logger.events.span("sweep/bucket", bucket=idx + 1,
                                    worker=worker_id) as sp_b, \
                    LeaseKeeper(queue, key, worker_id, heartbeat=heartbeat,
                                max_lifetime_s=bucket_timeout,
                                slice_index=slice_idx) as keeper:
                programs = None
                if grid_mesh is not None and member_chunk is None:
                    # AOT-warm the bucket's mesh-sharded programs: zero
                    # inline compiles at dispatch (asserted by the mesh
                    # bench) + per-program XLA roofline into the events
                    programs = warm_bucket_programs(
                        cfg, item["lrs"], seeds, train_batch, valid_batch,
                        tcfg, exec_cfg, events=logger.events,
                        name_prefix=f"bucket{idx + 1}/",
                        grid_mesh=grid_mesh)
                out = train_bucket(
                    cfg, item["lrs"], seeds, train_batch, valid_batch, tcfg,
                    member_chunk=member_chunk, exec_cfg=exec_cfg,
                    programs=programs, grid_mesh=grid_mesh,
                )
            if keeper.lost:
                # presumed dead and taken over mid-train: the new owner's
                # (bit-identical) result is the one the ledger records
                logger.warning(
                    f"[sweep:{worker_id}] bucket {idx+1} lease was taken "
                    "over mid-train; discarding this copy of the result")
                continue
            if keeper.slice_lost:
                # the DEVICE slice was stolen (this worker was presumed
                # dead) but the bucket lease held: the result is still
                # bit-identical — grid placement never changes values — so
                # record it below, then drop the slice state and lease a
                # fresh slice before the next bucket (training on a stolen
                # slice's devices would violate the packing contract)
                logger.warning(
                    f"[sweep:{worker_id}] device slice {slice_idx} was "
                    "taken over mid-train; keeping the (bit-identical) "
                    "result and re-leasing a slice")
                slice_idx, grid_mesh = None, None
                batches_packed = False
            queue.ledger.write(key, make_record(
                key, idx, cfg.to_dict(), item["lrs"], seeds,
                out["grid"], out["best_valid_sharpe"],
                worker=worker_id, seconds=sp_b.seconds,
            ))
            logger.events.counter("sweep/ledger_write", bucket=idx + 1,
                                  path=key, worker=worker_id)
            queue.complete(key, worker_id)
            trained += 1
        except Exception as e:  # noqa: BLE001 — any failure releases the claim
            queue.fail(key, worker_id, error=f"{type(e).__name__}: {e}")
            logger.warning(
                f"[sweep:{worker_id}] bucket {idx+1} failed "
                f"({type(e).__name__}: {e}); released for retry")
    if slice_idx is not None:
        queue.release_device_slice(slice_idx, worker_id)
    return trained


def ranking_from_ledger(queue) -> Tuple[List[Dict], Dict[str, Any]]:
    """Reconstruct the global ranking from a sweep's ledger records, in
    manifest bucket order (ranking tie-breaks match the in-process sweep
    exactly), plus the COVERAGE manifest for degraded completion: which
    buckets are quarantined (with their attempt history) or missing, and
    the completed fraction. A fully covered ledger reproduces ``run_sweep``
    (top_k=None) bit-for-bit."""
    results: List[Dict] = []
    quarantined_info = queue.ledger.quarantined()
    quarantined: List[Dict[str, Any]] = []
    missing: List[Dict[str, Any]] = []
    items = queue.items()
    for item in items:
        key = item["key"]
        if queue.ledger.has(key):
            cfg = GANConfig.from_dict(item["config"], strict=False)
            results.extend(
                _entries_from_record(cfg, queue.ledger.load(key)))
        elif key in quarantined_info or queue.ledger.is_quarantined(key):
            q = quarantined_info.get(key, {})
            quarantined.append({
                "index": item["index"], "key": key,
                "config": item["config"], "lrs": item["lrs"],
                "attempts": q.get("attempts"),
                "history": q.get("history"),
            })
        else:
            missing.append({"index": item["index"], "key": key})
    n = len(items)
    completed = n - len(quarantined) - len(missing)
    coverage = {
        "n_buckets": n,
        "completed": completed,
        "coverage": round(completed / n, 4) if n else 1.0,
        "complete": not quarantined and not missing,
        "quarantined": quarantined,
        "missing": missing,
    }
    results.sort(key=lambda r: -r["valid_sharpe"])
    return results, coverage


def open_work_queue(
    run_dir: Union[str, Path],
    events=None,
    create: bool = False,
):
    """The run dir's :class:`WorkQueue`, parameterized from its own queue
    manifest when one exists (lease timeout / max attempts / retry backoff
    are FLEET-level settings: every worker must agree on them, so they ride
    in the manifest, not per-process flags)."""
    from ..reliability.ledger import LEDGER_DIRNAME
    from ..reliability.scheduler import WorkQueue
    from ..reliability.supervisor import RestartPolicy

    queue = WorkQueue(Path(run_dir) / LEDGER_DIRNAME, events=events)
    if not create:
        meta = queue.load_manifest()
        queue.lease_timeout_s = float(
            meta.get("lease_timeout_s", queue.lease_timeout_s))
        queue.max_attempts = int(meta.get("max_attempts", queue.max_attempts))
        if meta.get("retry_backoff_s") is not None:
            queue.backoff = RestartPolicy(
                backoff_base_s=float(meta["retry_backoff_s"]),
                backoff_max_s=max(30.0, float(meta["retry_backoff_s"])))
    return queue
