"""Headline benchmarks: full 3-phase GAN-SDF training wall-clock.

Two workloads, each the paper's full schedule (256 + 64 + 1024 epochs, seed 42):

  * real_shape — the real-panel scale from BASELINE.md's north star:
    T=240/60/300 (train/valid/test), N=10,000 stocks, 46 characteristics,
    178 macro series (the shape of `/root/reference/notebooks/demo_full.ipynb`
    cell 3's workload). The PyTorch reference trains this in ~40 min (~2400 s)
    on CPU (`/root/reference/README.md:203`). North star: < 60 s.
  * synthetic_small — the reference's bundled synthetic shape (120×500×46,
    8 macro), measured at 294 s for the reference on this machine's CPU
    (`python -m src.train --data_dir data/synthetic_data`, 2026-07-29).

Compile accounting is explicit (VERDICT r1 "what's weak" #1): the bench runs
with a FRESH persistent-cache dir so `cold_compile_s` is a true cold XLA
compile; `warm_compile_s` re-lowers the same programs through the now-warm
persistent cache (a second Trainer, empty in-memory cache); `execute_s` is
the pure on-device run with compiled programs in hand.

Prints ONE JSON line. Headline value = real-shape cold total (cold compile +
execute), the honest analogue of the reference's from-scratch wall-clock;
vs_baseline = 2400 / value.
"""

import json
import os
import tempfile
import time
from pathlib import Path

REFERENCE_REAL_CPU_SECONDS = 2400.0  # ~40 min/model CPU, README.md:203
REFERENCE_SMALL_CPU_SECONDS = 294.0  # measured, same machine, same workload
REPO = Path(__file__).parent
DATA_SMALL = REPO / "bench_data"
DATA_REAL = REPO / "bench_data_real"


def _ensure_data():
    from deeplearninginassetpricing_paperreplication_tpu.data.synthetic import (
        generate_all_splits,
    )

    if not (DATA_SMALL / "char" / "Char_train.npz").exists():
        generate_all_splits(
            DATA_SMALL,
            n_periods_train=120, n_periods_valid=30, n_periods_test=60,
            n_stocks=500, n_features=46, n_macro=8, seed=42, verbose=False,
        )
    if not (DATA_REAL / "char" / "Char_train.npz").exists():
        print("[bench] generating real-shape panel (one-time, a few minutes)...",
              flush=True)
        generate_all_splits(
            DATA_REAL,
            n_periods_train=240, n_periods_valid=60, n_periods_test=300,
            n_stocks=10000, n_features=46, n_macro=178, seed=42,
            verbose=False, compress=False,
        )


def _run_workload(name, data_dir, measure_dedicated=False):
    """Train the full 3-phase schedule; return timing + metric dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearninginassetpricing_paperreplication_tpu.data.panel import load_splits
    from deeplearninginassetpricing_paperreplication_tpu.training.trainer import Trainer
    from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
        device_put_batch,
        sync_batch,
    )

    # load_s = disk read + host→device transfer, COMPLETE (sync_batch forces
    # true residency — plain block_until_ready is a no-op on remote-attached
    # devices, which would silently bill the transfer to the first training
    # dispatch). The transfer itself is mask-packed: only valid panel entries
    # ship, scattered into zeros on device (bit-exact, ~coverage of the bytes).
    # Compilation runs BEFORE the transfer (phase programs lower from shape
    # structs): on remote-attached devices, compile RPCs and bulk transfer
    # share one link, so overlapping them contends and inflates both —
    # measured 77 s compile when overlapped vs ~15-20 s quiet.
    t_load = time.time()
    train_ds, valid_ds, test_ds = load_splits(data_dir)
    disk_s = time.time() - t_load

    cfg = GANConfig(
        macro_feature_dim=train_ds.macro_feature_dim,
        individual_feature_dim=train_ds.individual_feature_dim,
    )
    tcfg = TrainConfig()  # paper defaults: 256/64/1024, lr 1e-3, seed 42
    gan = GAN(cfg)
    params = gan.init(jax.random.key(tcfg.seed))
    # share_sdf_program: the paper schedule nests (1024 = 4×256), so ONE
    # switched 256-epoch program serves phases 1 and 3 — one fewer big
    # program on the cold-compile critical path (the remote compile service
    # serializes large compiles, so dropping a program saves its full
    # latency) for a measured ~+1.6 ms/epoch execute cost
    trainer = Trainer(gan, tcfg, has_test=True, share_sdf_program=True)

    host_batches = [ds.full_batch() for ds in (train_ds, valid_ds, test_ds)]
    # the explicit sharding matters: executables lowered from shardingless
    # structs pay a per-program first-call relayout of the big arrays
    # (~10 s at this shape); with it, first dispatch == steady state
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    struct_b = [
        {k: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype,
                                 sharding=sharding)
         for k, v in hb.items()}
        for hb in host_batches
    ]

    from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
        warm_scatter,
    )

    # the compute route consumes the panel at bf16 (ExecutionConfig.bf16_panel
    # default) -> ship `individual` bf16 over the wire: half the dominant
    # payload, identical computed values (the later f32->bf16 cast reproduces
    # the same bf16 numbers; PARITY_BF16.json covers the route end-to-end)
    bf16_wire = gan.exec_cfg.bf16_wire_ok(cfg)

    # cold compile: fresh persistent cache (set up in main), empty in-memory.
    # The per-split scatter programs warm here too (device-born zero inputs,
    # no host bytes), so transfer_s measures bytes-on-the-wire, not compiles.
    t0 = time.time()
    trainer.precompile(params, *struct_b)
    for hb in host_batches:
        warm_scatter(hb, bf16_wire=bf16_wire)
    cold_compile_s = time.time() - t0

    t0 = time.time()
    train_b, valid_b, test_b = (
        device_put_batch(hb, bf16_wire=bf16_wire) for hb in host_batches
    )
    for b in (train_b, valid_b, test_b):
        sync_batch(b)
    transfer_s = time.time() - t0
    load_s = disk_s + transfer_s

    # first run: compiled programs, but may still absorb residual one-time
    # device/session setup the warmup dummy didn't trigger
    t0 = time.time()
    final_params, _hist = trainer.train(
        params, train_b, valid_b, test_b, verbose=False, precompile=False
    )
    jax.block_until_ready(jax.tree.leaves(final_params))
    cold_execute_s = time.time() - t0

    # steady state: identical second run, everything warm
    t0 = time.time()
    final_params, _hist = trainer.train(
        params, train_b, valid_b, test_b, verbose=False, precompile=False
    )
    jax.block_until_ready(jax.tree.leaves(final_params))
    execute_s = time.time() - t0

    # warm compile: new Trainer (empty in-memory cache) re-lowers through the
    # now-populated persistent cache
    trainer2 = Trainer(gan, tcfg, has_test=True, share_sdf_program=True)
    t0 = time.time()
    trainer2.precompile(params, train_b, valid_b, test_b)
    warm_compile_s = time.time() - t0

    # the DEFAULT route: dedicated per-phase programs (share_sdf_program
    # False, what Trainer() gives users). The cold path above shares one
    # switched program across phases 1/3 to cut cold compile, paying a
    # measured ~+1.6 ms/epoch execute — so per-phase epoch timings and the
    # bandwidth accounting must come from THIS run, not the shared one.
    dedicated = None
    if measure_dedicated:
        trainer3 = Trainer(gan, tcfg, has_test=True)
        t0 = time.time()
        trainer3.precompile(params, train_b, valid_b, test_b)
        ded_compile_s = time.time() - t0
        t0 = time.time()
        final_params3, _ = trainer3.train(
            params, train_b, valid_b, test_b, verbose=False, precompile=False
        )
        jax.block_until_ready(jax.tree.leaves(final_params3))
        # one warm repeat = the steady-state number
        t0 = time.time()
        final_params3, _ = trainer3.train(
            params, train_b, valid_b, test_b, verbose=False, precompile=False
        )
        jax.block_until_ready(jax.tree.leaves(final_params3))
        ded_execute_s = time.time() - t0
        dedicated = {
            "compile_s": round(ded_compile_s, 2),
            "execute_s": round(ded_execute_s, 2),
            "phase_execute_seconds": dict(trainer3.phase_seconds),
        }

    test_metrics = trainer.final_eval(final_params, test_b)
    result = {
        "shape": f"T={train_ds.T}/{valid_ds.T}/{test_ds.T} N={train_ds.N} "
                 f"F={train_ds.individual_feature_dim} M={train_ds.macro_feature_dim}",
        "load_s": round(load_s, 2),
        "transfer_s": round(transfer_s, 2),
        "cold_compile_s": round(cold_compile_s, 2),
        "warm_compile_s": round(warm_compile_s, 2),
        "cold_execute_s": round(cold_execute_s, 2),
        "execute_s": round(execute_s, 2),
        "cold_total_s": round(cold_compile_s + cold_execute_s, 2),
        "warm_total_s": round(warm_compile_s + execute_s, 2),
        # what a user with a persistent cache on disk (any run after the
        # first on a machine, the shipped-container case) actually waits:
        # cache-hit lowering + cold execute. Reported ALONGSIDE the true
        # cold number, never in place of it.
        "cached_cold_total_s": round(warm_compile_s + cold_execute_s, 2),
        "phase_execute_seconds": dict(trainer.phase_seconds),
        **({"dedicated_route": dedicated} if dedicated else {}),
        "test_sharpe": round(test_metrics["sharpe"], 4),
    }
    shapes = {
        "T_train": train_ds.T, "T_valid": valid_ds.T, "T_test": test_ds.T,
        "N": train_ds.N, "F": train_ds.individual_feature_dim,
    }
    batches = {"cfg": cfg, "train": train_b, "valid": valid_b, "test": test_b}
    return result, shapes, batches


# v5e HBM peak per chip (public spec: 16 GB @ 819 GB/s)
HBM_PEAK_GBPS = 819.0


def _bandwidth_accounting(real, shapes):
    """Analytic HBM panel traffic per epoch vs measured epoch time.

    The epoch is panel-read-bound: each fused-kernel pass streams the
    feature-major bf16 panel once. Passes per epoch —
      phase 3 train step: FFN fwd + FFN bwd (recompute) + EM fwd + EM bwd
      phase 1 train step: FFN fwd + FFN bwd
      every epoch's valid AND test evals: FFN fwd + EM fwd each.
    Secondary [T, N] f32 arrays (returns, mask, weights, xr) add ~5-8% and
    are excluded — this measures the dominant term the ARCHITECTURE.md
    "HBM-bound" claim rests on.
    """
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        TrainConfig,
    )

    tcfg = TrainConfig()  # the schedule _run_workload trains with
    F, N = shapes["F"], shapes["N"]
    bpe = 2  # bf16 panel bytes per element
    eval_bytes = 2 * (shapes["T_valid"] + shapes["T_test"]) * F * N * bpe
    p3_bytes = 4 * shapes["T_train"] * F * N * bpe + eval_bytes
    p1_bytes = 2 * shapes["T_train"] * F * N * bpe + eval_bytes
    # the DEFAULT (dedicated-programs) route's timings — the shared-program
    # cold path pays ~+1.6 ms/epoch that is not a property of the kernels
    ph = real.get("dedicated_route", {}).get(
        "phase_execute_seconds", real["phase_execute_seconds"])
    out = {"hbm_peak_gbps": HBM_PEAK_GBPS}
    for name, nbytes, key, epochs in (
        ("phase3", p3_bytes, "phase3_conditional", tcfg.num_epochs),
        ("phase1", p1_bytes, "phase1_unconditional", tcfg.num_epochs_unc),
    ):
        sec = ph.get(key)
        if not sec:
            continue
        per_epoch_s = sec / epochs
        gbps = nbytes / per_epoch_s / 1e9
        out[name] = {
            "panel_bytes_per_epoch": nbytes,
            "epoch_ms": round(per_epoch_s * 1e3, 3),
            "achieved_gbps": round(gbps, 1),
            "hbm_utilization": round(gbps / HBM_PEAK_GBPS, 3),
        }
    return out


def _run_ensemble_bench(cfg, batches):
    """BASELINE.json config 4: the 9-seed ensemble, full paper schedule,
    vmapped over members through the fused kernels on one chip."""
    import jax
    import numpy as np

    from deeplearninginassetpricing_paperreplication_tpu.parallel.ensemble import (
        ensemble_metrics,
        train_ensemble,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        TrainConfig,
    )

    seeds = (42, 123, 456, 789, 1000, 2000, 3000, 4000, 5000)
    tcfg = TrainConfig()
    epochs = tcfg.num_epochs_unc + tcfg.num_epochs_moment + tcfg.num_epochs

    t0 = time.time()
    gan, vparams, _hist = train_ensemble(
        cfg, batches["train"], batches["valid"], batches["test"],
        seeds=seeds, tcfg=tcfg, verbose=False,
    )
    # force true completion (block_until_ready is a no-op on the tunnel)
    np.asarray(sum(x.sum() for x in jax.tree.leaves(vparams)))
    cold_s = time.time() - t0  # training only: vmapped compiles + execute
    m_test = ensemble_metrics(gan, vparams, batches["test"])

    # warm: retrace hits the persistent cache; timing ≈ pure execute
    t0 = time.time()
    gan, vparams, _hist = train_ensemble(
        cfg, batches["train"], batches["valid"], batches["test"],
        seeds=seeds, tcfg=tcfg, verbose=False,
    )
    jax.block_until_ready(jax.tree.leaves(vparams))
    np.asarray(sum(x.sum() for x in jax.tree.leaves(vparams)))
    warm_s = time.time() - t0

    return {
        "n_members": len(seeds),
        "epochs_per_member": epochs,
        "cold_wall_s": round(cold_s, 2),
        "warm_wall_s": round(warm_s, 2),
        "member_epoch_ms": round(1e3 * warm_s / (epochs * len(seeds)), 3),
        "ensemble_test_sharpe": round(float(m_test["ensemble_sharpe"]), 4),
        "ensemble_test_ev": round(float(m_test["explained_variation"]), 4),
        "ensemble_test_xs_r2": round(float(m_test["cross_sectional_r2"]), 4),
        "individual_test_sharpes": [
            round(float(s), 4) for s in m_test["individual_sharpes"]
        ],
        "note": "members train through the MEMBER-FUSED kernels (one panel "
                "read per pass for all 9; docs/ARCHITECTURE.md 'member "
                "fusion'): the residual cost is per-member MXU/VPU compute, "
                "the floor for 9 distinct 12k-param models on one chip",
    }


def _run_sweep_bucket_bench(cfg, batches):
    """One architecture bucket of the 384-config search: 4 lrs × 1 seed as a
    single vmapped grid, paper search schedule (64/16/256)."""
    import numpy as np

    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        train_bucket,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        TrainConfig,
    )

    lrs = (1e-3, 5e-4, 2e-3, 1e-4)
    tcfg = TrainConfig(num_epochs_unc=64, num_epochs_moment=16,
                       num_epochs=256, ignore_epoch=16)
    epochs = tcfg.num_epochs_unc + tcfg.num_epochs_moment + tcfg.num_epochs
    t0 = time.time()
    out = train_bucket(cfg, lrs, (42,), batches["train"], batches["valid"], tcfg)
    np.asarray(out["best_valid_sharpe"])
    cold_wall = time.time() - t0
    # warm: identical second bucket — compiles cached, timing ≈ pure execute.
    # member_epoch_ms from the WARM wall (VERDICT r3 weak #4: the cold number
    # conflated compile and execute, so the '96 buckets' extrapolation was
    # not computable from the artifact)
    t0 = time.time()
    out = train_bucket(cfg, lrs, (42,), batches["train"], batches["valid"], tcfg)
    np.asarray(out["best_valid_sharpe"])
    warm_wall = time.time() - t0
    n = len(lrs)
    return {
        "grid_points": n,
        "epochs_per_member": epochs,
        "cold_wall_s": round(cold_wall, 2),  # includes this bucket's compiles
        "warm_wall_s": round(warm_wall, 2),
        "member_epoch_ms": round(1e3 * warm_wall / (epochs * n), 3),
        "best_valid_sharpe": round(float(np.max(out["best_valid_sharpe"])), 4),
        "note": "the full 384-config search = 96 such buckets (distinct "
                "architectures recompile; same-shape buckets reuse the "
                "persistent cache); see sweep_results/report.json for the "
                "measured end-to-end search",
    }


def main():
    # fresh persistent-cache dir => cold_compile_s is a true cold compile
    cache_dir = tempfile.mkdtemp(prefix="dlap_bench_xla_")
    os.environ["DLAP_CACHE_DIR"] = cache_dir
    from deeplearninginassetpricing_paperreplication_tpu.utils.cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache(cache_dir)
    _ensure_data()

    import jax
    import jax.numpy as jnp

    # Absorb the one-time device/session initialization before any timed
    # section (remote-attached TPUs pay ~20 s of session setup on early
    # executions; it belongs to the platform, not the training programs, and
    # is reported separately here). A few differently-shaped ops, including
    # a scan, to trigger the lazily-initialized paths.
    t0 = time.time()
    jnp.asarray((jnp.ones((2048, 2048)) @ jnp.ones((2048, 2048))).sum())
    x = jnp.ones((64, 512))
    carry, _ = jax.lax.scan(lambda c, t: (c * 0.5 + t.sum() * 1e-9, None), 0.0, x)
    jnp.asarray(carry)
    jnp.asarray(jax.random.bernoulli(jax.random.key(0, impl="rbg"), 0.5,
                                     (1024, 1024)).sum())
    device_init_s = round(time.time() - t0, 2)

    real, real_shapes, real_batches = _run_workload(
        "real_shape", DATA_REAL, measure_dedicated=True)
    small, _, _ = _run_workload("synthetic_small", DATA_SMALL)

    # the multi-model axes (BASELINE.json configs 4-5) on the real-shape
    # panel, reusing its device-resident batches
    ensemble = _run_ensemble_bench(real_batches["cfg"], real_batches)
    sweep_bucket = _run_sweep_bucket_bench(real_batches["cfg"], real_batches)

    value = real["cold_total_s"]
    print(
        json.dumps(
            {
                "metric": "3phase_train_real_shape_240x10000_1344ep_cold_total",
                "value": value,
                "unit": "s",
                "vs_baseline": round(REFERENCE_REAL_CPU_SECONDS / value, 2),
                "vs_baseline_note": "TPU wall on a synthetic panel of the "
                                    "real SHAPE vs the reference README's "
                                    "'~40 min/model' real-data CPU anecdote "
                                    "— same workload shape and schedule, "
                                    "not the same data or machine",
                "compile_weather_note": "cold_compile_s rides the shared "
                                        "remote compile service, whose "
                                        "latency for the SAME programs "
                                        "swings ~6 s to ~137 s hour to hour "
                                        "with link load; execute_s and the "
                                        "warm numbers are stable (±5%) and "
                                        "are the comparison figures. "
                                        "cached_cold_total_s is what any "
                                        "run after the first on a machine "
                                        "pays (persistent cache on disk).",
                "real_shape": real,
                "ensemble_real_shape": ensemble,
                "sweep_bucket_real_shape": sweep_bucket,
                "bandwidth": _bandwidth_accounting(real, real_shapes),
                "synthetic_small": {
                    **small,
                    "vs_baseline": round(
                        REFERENCE_SMALL_CPU_SECONDS / small["cold_total_s"], 2
                    ),
                },
                "device_init_s": device_init_s,
                "device": str(jax.devices()[0]),
                "execution": {
                    "pallas_ffn": __import__(
                        "deeplearninginassetpricing_paperreplication_tpu.utils.config",
                        fromlist=["ExecutionConfig"],
                    ).ExecutionConfig().use_pallas((64, 64)),
                    "parity": "PARITY.json + PARITY_BF16.json (120x500), "
                              "PARITY_MID.json (240x2000) and the "
                              "PARITY_WIDTH.json series (240x500/2000/4000"
                              ", default TPU route): |d test Sharpe| vs "
                              "torch reference within the 0.02 bar and "
                              "flat in panel width",
                },
            }
        )
    )


if __name__ == "__main__":
    main()
